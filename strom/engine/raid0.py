"""Software RAID0 stripe math.

The reference decodes md-raid0 striping *in the kernel* so each NVMe READ
lands on the right member device (SURVEY.md §2.1 "Extent resolver", §3.3;
reference cite UNVERIFIED — empty mount, SURVEY.md §0).  strom-tpu does the
same arithmetic in userspace: a logical byte range over an N-member stripe
becomes per-member (offset, length) segments, which the engine reads
concurrently — same math the kernel's raid0 map performs, applied to member
files/devices opened directly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class StripeSegment:
    member: int        # member index [0, n)
    member_offset: int # byte offset within the member
    logical_offset: int  # byte offset within the logical (striped) address space
    length: int


def plan_stripe_reads(offset: int, length: int, n_members: int, chunk: int) -> list[StripeSegment]:
    """Map logical [offset, offset+length) over an n-member RAID0 with the given
    chunk size into per-member segments, ordered by logical offset.

    Layout (classic md-raid0): logical chunk k lives on member (k % n) at
    member-chunk index (k // n).
    """
    if n_members <= 0:
        raise ValueError("n_members must be positive")
    if chunk <= 0:
        raise ValueError("chunk must be positive")
    if offset < 0 or length < 0:
        raise ValueError("offset/length must be non-negative")
    segs: list[StripeSegment] = []
    pos = offset
    end = offset + length
    while pos < end:
        chunk_idx = pos // chunk
        within = pos % chunk
        take = min(chunk - within, end - pos)
        member = chunk_idx % n_members
        member_off = (chunk_idx // n_members) * chunk + within
        segs.append(StripeSegment(member, member_off, pos, take))
        pos += take
    return segs


def coalesce(segs: list[StripeSegment]) -> list[StripeSegment]:
    """Merge adjacent segments on the same member that are contiguous in both
    member and logical space (happens when chunk > block size)."""
    out: list[StripeSegment] = []
    for s in segs:
        if out:
            p = out[-1]
            if (p.member == s.member
                    and p.member_offset + p.length == s.member_offset
                    and p.logical_offset + p.length == s.logical_offset):
                out[-1] = StripeSegment(p.member, p.member_offset, p.logical_offset, p.length + s.length)
                continue
        out.append(s)
    return out


def plan_stripe_windows(segs: Sequence[StripeSegment], n_members: int,
                        window_bytes: int) -> list[StripeSegment]:
    """Reorder logical-order stripe segments into overlap windows: within
    each window of ~*window_bytes* total, segments are grouped into
    per-member runs (member-offset order preserved, so each run is a
    sequential read on its member).

    The engine keeps its queue-depth pipeline full ACROSS the list, so a
    window sized to the in-flight budget (queue_depth × block_size) means
    member ops for window N+1 are entering the submission queue while window
    N's completions drain — continuous per-member streams instead of a
    chunk-granular round-robin hopping files every raid_chunk bytes. Every
    byte mapping is unchanged (dest offsets are explicit); only submission
    order moves. window_bytes <= 0 keeps logical order. Consecutive windows
    continue each member's run at the exact next member offset, so
    downstream run detection (the native engine's residency-probe
    coalescing) still sees long member-contiguous streaks."""
    if window_bytes <= 0 or n_members <= 1:
        return list(segs)
    # the planning decision on the timeline: how many member ops entered
    # the overlap reorder and at what window size (pairs with the
    # stripe_windows counter; an instant, not a span — planning is pure math)
    from strom.obs.events import ring

    ring.instant("raid0.stripe_windows", cat="read",
                 args={"segments": len(segs), "members": n_members,
                       "window_bytes": window_bytes})
    out: list[StripeSegment] = []
    win: list[StripeSegment] = []
    acc = 0

    def flush() -> None:
        by_member: dict[int, list[StripeSegment]] = {}
        for s in win:
            by_member.setdefault(s.member, []).append(s)
        for m in sorted(by_member):
            out.extend(by_member[m])

    for s in segs:
        win.append(s)
        acc += s.length
        if acc >= window_bytes:
            flush()
            win = []
            acc = 0
    if win:
        flush()
    return out


def count_stripe_windows(segs: Sequence[StripeSegment], n_members: int,
                         window_bytes: int) -> int:
    """Exactly how many windows :func:`plan_stripe_windows` flushes for the
    same inputs (same accumulation rule: a flush can consume MORE than
    window_bytes when segment lengths don't divide it, so ceil(total/wb)
    would overcount) — kept adjacent so the two can't drift."""
    if window_bytes <= 0 or n_members <= 1:
        return 0
    windows = 0
    acc = 0
    for s in segs:
        acc += s.length
        if acc >= window_bytes:
            windows += 1
            acc = 0
    return windows + (1 if acc else 0)


SIZE_SIDECAR_SUFFIX = ".stromsz"


def stripe_file(src: str, members: Sequence[str], chunk: int) -> int:
    """Write *src*'s bytes into RAID0 member files (logical chunk k → member
    k % n at member-chunk k // n), zero-padding the tail to a full stripe
    width so the striped logical size covers the whole source. Fixture/bench
    helper: the inverse of what :func:`plan_stripe_reads` decodes.

    Returns the TRUE source size, and records it in a ``.stromsz`` sidecar
    next to the first member: without it, ``StripedFile.size`` reports the
    zero-padded stripe width, and formats that trust the size — trailing
    parquet footers, rawbin record counting — silently read the padding as
    data. Members are written to temp names and renamed only on completion,
    so an interrupted stripe can never be mistaken for a finished one.
    """
    n = len(members)
    if n <= 0 or chunk <= 0:
        raise ValueError("need >= 1 member and a positive chunk")
    size = os.stat(src).st_size
    width = chunk * n
    padded = -(-size // width) * width
    tmps = [m + ".tmp" for m in members] \
        + [members[0] + SIZE_SIDECAR_SUFFIX + ".tmp"]
    outs = [open(t, "wb") for t in tmps[:-1]]
    try:
        try:
            with open(src, "rb") as f:
                for pos in range(0, padded, chunk):
                    data = f.read(chunk)
                    if len(data) < chunk:
                        data = data.ljust(chunk, b"\0")
                    outs[(pos // chunk) % n].write(data)
        finally:
            for o in outs:
                o.close()
        with open(tmps[-1], "w") as f:
            f.write(str(size))
        for m in members:
            os.replace(m + ".tmp", m)
        os.replace(tmps[-1], members[0] + SIZE_SIDECAR_SUFFIX)
    except BaseException:
        # a failed stripe (ENOSPC mid-write) must not leave GiB-scale .tmp
        # garbage next to the dataset
        for t in tmps:
            try:
                os.unlink(t)
            except OSError:
                pass
        raise
    return size


def logical_size(member_sizes: list[int], chunk: int) -> int:
    """Usable striped capacity given member sizes (md-raid0 uses min size × n for
    equal members; we require the common prefix that stripes evenly)."""
    if not member_sizes:
        return 0
    usable = min(member_sizes)
    full_chunks = usable // chunk
    return full_chunks * chunk * len(member_sizes)
