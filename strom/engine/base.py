"""Engine interface: submit/wait block reads into a registered staging pool.

This is the strom-tpu analogue of the reference's kernel-side DMA submit
engine + async completion path (SURVEY.md §2.1 "DMA submit engine",
"Async completion / WAIT"; reference cite UNVERIFIED — empty mount,
SURVEY.md §0).  The contract deliberately mirrors the ioctl surface:

==========================  =============================================
reference ioctl             Engine equivalent
==========================  =============================================
MAP_GPU_MEMORY              staging pool allocated+registered at engine init
LIST/INFO_GPU_MEMORY        Engine.buffers() / Engine.buffer_info()
MEMCPY_SSD2GPU(_ASYNC)      Engine.submit(ReadRequest...)
MEMCPY_WAIT                 Engine.wait(...)
stat ioctl / /proc node     Engine.stats()
==========================  =============================================

Two implementations share this interface: the C++ io_uring engine
(:mod:`strom.engine.uring_engine`, the fast path) and a pure-Python
preadv thread pool (:mod:`strom.engine.python_engine`, the portable
fallback).
"""

from __future__ import annotations

import abc
import dataclasses
import errno
import os
from typing import Iterable, Sequence

import numpy as np

from strom.config import StromConfig

_ENODATA = errno.ENODATA


@dataclasses.dataclass(frozen=True)
class ReadRequest:
    """One block read: file[offset : offset+length] → pool[buf_index][buf_offset:]."""

    file_index: int    # from Engine.register_file
    offset: int        # byte offset in file
    length: int        # bytes to read (<= buffer_size - buf_offset)
    buf_index: int     # staging pool slot
    tag: int           # caller-chosen completion tag
    buf_offset: int = 0


@dataclasses.dataclass(frozen=True)
class RawRead:
    """One block read straight into caller-owned memory (no staging pool).

    *dest* must be a writable C-contiguous uint8 view whose lifetime the caller
    guarantees until the op completes; for the O_DIRECT path it must satisfy
    the file's memory alignment (use :func:`strom.delivery.buffers.alloc_aligned`).
    """

    file_index: int
    offset: int
    length: int
    dest: np.ndarray
    tag: int


@dataclasses.dataclass(frozen=True)
class Completion:
    tag: int
    result: int        # bytes read (>=0) or negative errno


class EngineError(OSError):
    pass


class Engine(abc.ABC):
    """Owns the staging pool and the submission/completion machinery."""

    name: str = "abstract"
    # True: read_vectored is internally thread-safe (per-ring locking) and
    # the delivery layer must NOT wrap gathers in its own whole-transfer
    # lock (see MultiRingEngine). Single-ring engines keep the default.
    concurrent_gathers: bool = False

    def __init__(self, config: StromConfig):
        self.config = config

    # -- file registration (≙ CHECK_FILE handing an fd to the kmod) ---------
    @abc.abstractmethod
    def register_file(self, path: str, *, o_direct: bool | None = None) -> int:
        """Open (or adopt) *path* and return a file index for ReadRequests.

        o_direct=None uses the engine config / per-file auto-probe."""

    @abc.abstractmethod
    def unregister_file(self, file_index: int) -> None: ...

    @abc.abstractmethod
    def file_uses_o_direct(self, file_index: int) -> bool: ...

    # -- staging pool (≙ MAP/LIST/INFO_GPU_MEMORY) --------------------------
    @abc.abstractmethod
    def buffer(self, buf_index: int) -> np.ndarray:
        """Zero-copy uint8 view of one pool slot (length == buffer_size)."""

    @property
    def num_buffers(self) -> int:
        return self.config.num_buffers

    @property
    def buffer_size(self) -> int:
        return self.config.buffer_size

    def buffer_info(self) -> dict:
        return {
            "num_buffers": self.num_buffers,
            "buffer_size": self.buffer_size,
            "total_bytes": self.num_buffers * self.buffer_size,
            "engine": self.name,
        }

    # -- submission / completion (≙ MEMCPY_SSD2GPU_ASYNC / MEMCPY_WAIT) -----
    @abc.abstractmethod
    def submit(self, requests: Sequence[ReadRequest]) -> int:
        """Queue reads; returns number submitted. Non-blocking up to queue_depth;
        raises EngineError if more than queue_depth ops would be in flight."""

    @abc.abstractmethod
    def submit_raw(self, requests: Sequence[RawRead]) -> int:
        """Queue reads into caller-owned memory (bypasses the staging pool).

        All-or-nothing: a batch exceeding the free queue depth raises
        EngineError(EAGAIN) with nothing submitted. (The uring engine can be
        raced past its pre-check by a concurrent submitter; its EngineError
        then carries ``.accepted`` — see UringEngine.submit_raw.)"""

    @abc.abstractmethod
    def wait(self, min_completions: int = 1, timeout_s: float | None = None) -> list[Completion]:
        """Block until >= min_completions ops retire (or timeout); return them."""

    @abc.abstractmethod
    def in_flight(self) -> int: ...

    @abc.abstractmethod
    def stats(self) -> dict: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- optional registered-dest support (io_uring READ_FIXED) -------------
    def register_dest(self, arr: np.ndarray) -> int:
        """Register a caller slab so gathers into it can use pre-pinned
        fixed buffers. -1 = not supported by this engine (the default);
        reads work identically either way."""
        return -1

    def unregister_dest(self, arr: np.ndarray) -> None:
        pass

    def unregister_dest_addr(self, addr: int) -> None:
        pass

    # -- vectored gather: the delivery layer's hot path ---------------------
    def read_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                      dest: np.ndarray, *, retries: int = 1) -> int:
        """Execute a whole gather list: chunks of (file_index, file_offset,
        dest_offset, length) → dest, block_size-chunked and pipelined at
        queue_depth, with per-chunk retry. Returns total bytes read.

        Must not run concurrently with other submitters on this engine (the
        delivery layer serializes transfers). Raises EngineError; ENODATA
        means a short read (range extends past EOF).

        This default uses submit_raw/wait per block; the C++ engine overrides
        it with a single native call (one Python-boundary crossing per
        transfer instead of per 128KiB block).
        """
        block = self.config.block_size
        qd = self.config.queue_depth
        d8 = dest.view(np.uint8).reshape(-1)
        if not hasattr(self, "_vec_tag"):
            self._vec_tag = 0
        # tag -> (file_idx, file_off, dest_off, want, attempts)
        pending: dict[int, tuple[int, int, int, int, int]] = {}
        it = ((fi, fo + p, do + p, min(block, ln - p))
              for (fi, fo, do, ln) in chunks
              for p in range(0, ln, block))
        exhausted = False
        total = 0
        inflight_peak = 0
        err: EngineError | None = None
        try:
            while not exhausted or pending:
                while not exhausted and len(pending) < qd and err is None:
                    try:
                        fi, fo, do, ln = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    tag = self._vec_tag
                    self._vec_tag += 1
                    self.submit_raw([RawRead(fi, fo, ln, d8[do: do + ln], tag)])
                    pending[tag] = (fi, fo, do, ln, 0)
                if len(pending) > inflight_peak:
                    inflight_peak = len(pending)
                if not pending:
                    break
                for c in self.wait(min_completions=1):
                    entry = pending.pop(c.tag, None)
                    if entry is None:
                        continue  # foreign tag: not ours to account
                    fi, fo, do, want, attempts = entry
                    if c.result < 0:
                        if attempts < retries and err is None:
                            from strom.utils.stats import global_stats

                            global_stats.add("chunk_retries")
                            tag = self._vec_tag
                            self._vec_tag += 1
                            self.submit_raw(
                                [RawRead(fi, fo, want, d8[do: do + want], tag)])
                            pending[tag] = (fi, fo, do, want, attempts + 1)
                            continue
                        if err is None:
                            err = EngineError(
                                -c.result,
                                f"read failed after {attempts + 1} attempts: "
                                f"{os.strerror(-c.result)}")
                    elif c.result < want:
                        total += c.result
                        if err is None:
                            err = EngineError(
                                _ENODATA, f"short read ({c.result} < {want}) — "
                                          "file smaller than requested range?")
                    else:
                        total += c.result
                if err is not None:
                    exhausted = True  # stop feeding; drain what's in flight
        except BaseException:
            while pending:
                done = self.wait(min_completions=1, timeout_s=30.0)
                if not done:
                    break
                for c in done:
                    pending.pop(c.tag, None)
            raise
        if err is not None:
            raise err
        if inflight_peak:
            # overlap observability: how deep the submit-while-draining
            # pipeline actually ran — a peak pinned at queue_depth means the
            # gather kept the queue full across op boundaries (the overlap
            # claim); a shallow peak means the op stream, not the engine,
            # was the limit
            from strom.utils.stats import global_stats

            global_stats.gauge("gather_inflight_peak").max(inflight_peak)
        return total

    # -- convenience: synchronous read of an arbitrary range ----------------
    def read_into(self, file_index: int, offset: int, length: int,
                  out: np.ndarray | memoryview, out_offset: int = 0) -> int:
        """Synchronously read file[offset:offset+length] into *out* using the
        staging pool in block_size chunks. Returns bytes read (short at EOF)."""
        block = self.config.block_size
        out_mv = memoryview(out).cast("B") if not isinstance(out, np.ndarray) else memoryview(out.view(np.uint8))
        done = 0
        pending: dict[int, tuple[int, int, int]] = {}  # tag -> (buf_index, out_pos, want)
        free = list(range(min(self.num_buffers, self.config.queue_depth)))
        next_tag = 0
        pos = 0
        short_read = False
        while pos < length or pending:
            while pos < length and free and not short_read:
                want = min(block, length - pos)
                buf = free.pop()
                tag = next_tag
                next_tag += 1
                self.submit([ReadRequest(file_index, offset + pos, want, buf, tag)])
                pending[tag] = (buf, pos, want)
                pos += want
            if not pending:
                break
            for c in self.wait(min_completions=1):
                buf, out_pos, want = pending.pop(c.tag)
                if c.result < 0:
                    raise EngineError(-c.result, f"read failed: {os.strerror(-c.result)}")
                if c.result:
                    out_mv[out_offset + out_pos: out_offset + out_pos + c.result] = \
                        self.buffer(buf)[:c.result]
                done += c.result
                if c.result < want:
                    short_read = True  # EOF: stop submitting further chunks
                free.append(buf)
        return done


    def read_into_direct(self, file_index: int, offset: int, length: int,
                         dest: np.ndarray) -> int:
        """Read file[offset:offset+length) straight into *dest* (uint8, len >=
        length), chunked at block_size and pipelined at queue_depth, with no
        staging-pool bounce. Returns bytes read (short at EOF)."""
        block = self.config.block_size
        pending: dict[int, int] = {}  # tag -> want
        next_tag = 0
        pos = 0
        done = 0
        short_read = False
        d8 = dest.view(np.uint8).reshape(-1)
        while pos < length or pending:
            while (pos < length and len(pending) < self.config.queue_depth
                   and not short_read):
                want = min(block, length - pos)
                tag = next_tag
                next_tag += 1
                self.submit_raw([RawRead(file_index, offset + pos, want,
                                         d8[pos: pos + want], tag)])
                pending[tag] = want
                pos += want
            if not pending:
                break
            for c in self.wait(min_completions=1):
                want = pending.pop(c.tag)
                if c.result < 0:
                    raise EngineError(-c.result, f"read failed: {os.strerror(-c.result)}")
                done += c.result
                if c.result < want:
                    short_read = True
        return done


def iter_chunks(offset: int, length: int, block: int) -> Iterable[tuple[int, int]]:
    """Split [offset, offset+length) into (offset, len) chunks of *block* bytes."""
    pos = offset
    end = offset + length
    while pos < end:
        take = min(block, end - pos)
        yield pos, take
        pos += take
