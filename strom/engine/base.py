"""Engine interface: submit/wait block reads into a registered staging pool.

This is the strom-tpu analogue of the reference's kernel-side DMA submit
engine + async completion path (SURVEY.md §2.1 "DMA submit engine",
"Async completion / WAIT"; reference cite UNVERIFIED — empty mount,
SURVEY.md §0).  The contract deliberately mirrors the ioctl surface:

==========================  =============================================
reference ioctl             Engine equivalent
==========================  =============================================
MAP_GPU_MEMORY              staging pool allocated+registered at engine init
LIST/INFO_GPU_MEMORY        Engine.buffers() / Engine.buffer_info()
MEMCPY_SSD2GPU(_ASYNC)      Engine.submit(ReadRequest...)
MEMCPY_WAIT                 Engine.wait(...)
stat ioctl / /proc node     Engine.stats()
==========================  =============================================

Two implementations share this interface: the C++ io_uring engine
(:mod:`strom.engine.uring_engine`, the fast path) and a pure-Python
preadv thread pool (:mod:`strom.engine.python_engine`, the portable
fallback).
"""

from __future__ import annotations

import abc
import dataclasses
import errno
import os
import time
from typing import Iterable, Sequence

import numpy as np

from strom.config import StromConfig

_ENODATA = errno.ENODATA
_ECANCELED = errno.ECANCELED


@dataclasses.dataclass(frozen=True)
class ReadRequest:
    """One block read: file[offset : offset+length] → pool[buf_index][buf_offset:]."""

    file_index: int    # from Engine.register_file
    offset: int        # byte offset in file
    length: int        # bytes to read (<= buffer_size - buf_offset)
    buf_index: int     # staging pool slot
    tag: int           # caller-chosen completion tag
    buf_offset: int = 0


@dataclasses.dataclass(frozen=True)
class RawRead:
    """One block read straight into caller-owned memory (no staging pool).

    *dest* must be a writable C-contiguous uint8 view whose lifetime the caller
    guarantees until the op completes; for the O_DIRECT path it must satisfy
    the file's memory alignment (use :func:`strom.delivery.buffers.alloc_aligned`).
    """

    file_index: int
    offset: int
    length: int
    dest: np.ndarray
    tag: int


@dataclasses.dataclass(frozen=True)
class Completion:
    tag: int
    result: int        # bytes read (>=0) or negative errno


@dataclasses.dataclass(frozen=True)
class ChunkCompletion:
    """One gather chunk retired by the async vectored path: *index* is the
    position in the chunk list handed to :meth:`Engine.submit_vectored`;
    *result* is the chunk's full byte count, or a negative errno when the
    chunk failed (retries exhausted / short read → -ENODATA)."""

    index: int
    result: int


class EngineError(OSError):
    pass


class StreamToken:
    """Handle for one in-flight vectored gather (:meth:`Engine.submit_vectored`).

    The token owns the submission state machine's bookkeeping: which chunks
    retired, how many block-size pieces are in flight, and the per-chunk
    error results. It is NOT thread-safe — exactly one thread drives
    poll/drain per token (the delivery layer's streaming gather does), the
    same contract read_vectored has always had.
    """

    __slots__ = ("chunks", "retries", "_d8", "_left", "_results", "_pending",
                 "_pieces", "_backlog", "_exhausted", "_ready", "bytes_done",
                 "cancelled", "inflight_peak", "_err", "chunks_done",
                 "req_id")

    def __init__(self, chunks: Sequence[tuple[int, int, int, int]],
                 dest: np.ndarray, block: int, retries: int,
                 req_id: "int | None" = None):
        self.chunks = list(chunks)
        self.retries = retries
        # causal request tracing (ISSUE 8): the req_id of the request this
        # gather belongs to, if traced — carried on the token so poll/drain
        # telemetry and tools can attribute engine work to one request
        self.req_id = req_id
        self._d8 = dest.view(np.uint8).reshape(-1)
        # bytes of each chunk not yet landed; a chunk retires when it hits 0
        self._left = [ln for (_, _, _, ln) in self.chunks]
        self._results: list[int | None] = [None] * len(self.chunks)
        # tag -> (chunk_idx, file_idx, file_off, dest_off, want, attempts)
        self._pending: dict[int, tuple[int, int, int, int, int, int]] = {}
        self._pieces = ((ci, fi, fo + p, do + p, min(block, ln - p), 0)
                        for ci, (fi, fo, do, ln) in enumerate(self.chunks)
                        for p in range(0, ln, block))
        # pieces bounced by a full queue (EAGAIN / partial batch accept):
        # resubmitted before the iterator advances
        self._backlog: list[tuple[int, int, int, int, int, int]] = []
        self._exhausted = not self.chunks
        self._ready: list[ChunkCompletion] = []
        self.bytes_done = 0
        self.cancelled = False
        self.inflight_peak = 0
        self._err: EngineError | None = None
        self.chunks_done = 0

    @property
    def done(self) -> bool:
        return (self._exhausted and not self._backlog and not self._pending) \
            or self.cancelled

    @property
    def error(self) -> EngineError | None:
        return self._err


class Engine(abc.ABC):
    """Owns the staging pool and the submission/completion machinery."""

    name: str = "abstract"
    # True: read_vectored is internally thread-safe (per-ring locking) and
    # the delivery layer must NOT wrap gathers in its own whole-transfer
    # lock (see MultiRingEngine). Single-ring engines keep the default.
    concurrent_gathers: bool = False

    def __init__(self, config: StromConfig):
        self.config = config

    # -- file registration (≙ CHECK_FILE handing an fd to the kmod) ---------
    @abc.abstractmethod
    def register_file(self, path: str, *, o_direct: bool | None = None) -> int:
        """Open (or adopt) *path* and return a file index for ReadRequests.

        o_direct=None uses the engine config / per-file auto-probe."""

    @abc.abstractmethod
    def unregister_file(self, file_index: int) -> None: ...

    @abc.abstractmethod
    def file_uses_o_direct(self, file_index: int) -> bool: ...

    # -- staging pool (≙ MAP/LIST/INFO_GPU_MEMORY) --------------------------
    @abc.abstractmethod
    def buffer(self, buf_index: int) -> np.ndarray:
        """Zero-copy uint8 view of one pool slot (length == buffer_size)."""

    @property
    def num_buffers(self) -> int:
        return self.config.num_buffers

    @property
    def buffer_size(self) -> int:
        return self.config.buffer_size

    def buffer_info(self) -> dict:
        return {
            "num_buffers": self.num_buffers,
            "buffer_size": self.buffer_size,
            "total_bytes": self.num_buffers * self.buffer_size,
            "engine": self.name,
        }

    # -- submission / completion (≙ MEMCPY_SSD2GPU_ASYNC / MEMCPY_WAIT) -----
    @abc.abstractmethod
    def submit(self, requests: Sequence[ReadRequest]) -> int:
        """Queue reads; returns number submitted. Non-blocking up to queue_depth;
        raises EngineError if more than queue_depth ops would be in flight."""

    @abc.abstractmethod
    def submit_raw(self, requests: Sequence[RawRead]) -> int:
        """Queue reads into caller-owned memory (bypasses the staging pool).

        All-or-nothing: a batch exceeding the free queue depth raises
        EngineError(EAGAIN) with nothing submitted. (The uring engine can be
        raced past its pre-check by a concurrent submitter; its EngineError
        then carries ``.accepted`` — see UringEngine.submit_raw.)"""

    @abc.abstractmethod
    def wait(self, min_completions: int = 1, timeout_s: float | None = None) -> list[Completion]:
        """Block until >= min_completions ops retire (or timeout); return them."""

    @abc.abstractmethod
    def in_flight(self) -> int: ...

    @abc.abstractmethod
    def stats(self) -> dict: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- per-op telemetry scope (multi-tenant observability) ----------------
    # The delivery context threads its label scope here so two pipelines /
    # tenants sharing one engine fleet surface distinguishable per-op
    # latency (engine_op_lat_us histogram) and queue occupancy
    # (engine_inflight gauge) on /metrics, while the unlabeled aggregate
    # stays the whole engine's truth. engine_inflight is a LAST-STATE gauge
    # (not a sum across scopes): each write snapshots the engine-wide
    # in-flight count at that scope's most recent submit/reap edge.
    def set_scope(self, scope) -> None:
        """Install the telemetry scope (a ``StatsRegistry`` or
        ``ScopedStats``) per-op accounting writes through."""
        self._op_scope = scope

    @property
    def op_scope(self):
        sc = getattr(self, "_op_scope", None)
        if sc is None:
            from strom.utils.stats import global_stats

            return global_stats
        return sc

    def _note_submitted(self, requests: Sequence) -> None:
        """Stamp submit time per tag (engine_op_lat_us measures submit →
        completion, the queue-resident latency the consumer actually pays,
        not just device service time) and refresh the occupancy gauge."""
        m = getattr(self, "_op_submit_t", None)
        if m is None:
            m = self._op_submit_t = {}
        t = time.perf_counter()
        for r in requests:
            m[r.tag] = t
        try:
            self.op_scope.set_gauge("engine_inflight", self.in_flight())
        except Exception:
            pass  # accounting must never fail a submission

    def _note_completed(self, completions: Sequence[Completion]) -> None:
        m = getattr(self, "_op_submit_t", None)
        sc = self.op_scope
        if m:
            t = time.perf_counter()
            h = sc.histogram("engine_op_lat")
            for c in completions:
                t0 = m.pop(c.tag, None)
                if t0 is not None:
                    h.observe_us((t - t0) * 1e6)
        try:
            sc.set_gauge("engine_inflight", self.in_flight())
        except Exception:
            pass

    # -- optional registered-dest support (io_uring READ_FIXED) -------------
    def register_dest(self, arr: np.ndarray) -> int:
        """Register a caller slab so gathers into it can use pre-pinned
        fixed buffers. -1 = not supported by this engine (the default);
        reads work identically either way."""
        return -1

    def unregister_dest(self, arr: np.ndarray) -> None:
        pass

    def unregister_dest_addr(self, addr: int) -> None:
        pass

    # -- vectored gather: the delivery layer's hot path ---------------------
    def read_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                      dest: np.ndarray, *, retries: int = 1) -> int:
        """Execute a whole gather list: chunks of (file_index, file_offset,
        dest_offset, length) → dest, block_size-chunked and pipelined at
        queue_depth, with per-chunk retry. Returns total bytes read.

        Must not run concurrently with other submitters on this engine (the
        delivery layer serializes transfers). Raises EngineError; ENODATA
        means a short read (range extends past EOF).

        This default uses submit_raw/wait per block; the C++ engine overrides
        it with a single native call (one Python-boundary crossing per
        transfer instead of per 128KiB block).
        """
        block = self.config.block_size
        qd = self.config.queue_depth
        d8 = dest.view(np.uint8).reshape(-1)
        if not hasattr(self, "_vec_tag"):
            self._vec_tag = 0
        # tag -> (file_idx, file_off, dest_off, want, attempts)
        pending: dict[int, tuple[int, int, int, int, int]] = {}
        it = ((fi, fo + p, do + p, min(block, ln - p))
              for (fi, fo, do, ln) in chunks
              for p in range(0, ln, block))
        exhausted = False
        total = 0
        inflight_peak = 0
        err: EngineError | None = None
        try:
            while not exhausted or pending:
                while not exhausted and len(pending) < qd and err is None:
                    try:
                        fi, fo, do, ln = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    tag = self._vec_tag
                    self._vec_tag += 1
                    self.submit_raw([RawRead(fi, fo, ln, d8[do: do + ln], tag)])
                    pending[tag] = (fi, fo, do, ln, 0)
                if len(pending) > inflight_peak:
                    inflight_peak = len(pending)
                if not pending:
                    break
                for c in self.wait(min_completions=1):
                    entry = pending.pop(c.tag, None)
                    if entry is None:
                        continue  # foreign tag: not ours to account
                    fi, fo, do, want, attempts = entry
                    if c.result < 0:
                        if attempts < retries and err is None:
                            self.op_scope.add("chunk_retries")
                            tag = self._vec_tag
                            self._vec_tag += 1
                            self.submit_raw(
                                [RawRead(fi, fo, want, d8[do: do + want], tag)])
                            pending[tag] = (fi, fo, do, want, attempts + 1)
                            continue
                        if err is None:
                            err = EngineError(
                                -c.result,
                                f"read failed after {attempts + 1} attempts: "
                                f"{os.strerror(-c.result)}")
                    elif c.result < want:
                        total += c.result
                        if err is None:
                            err = EngineError(
                                _ENODATA, f"short read ({c.result} < {want}) — "
                                          "file smaller than requested range?")
                    else:
                        total += c.result
                if err is not None:
                    exhausted = True  # stop feeding; drain what's in flight
        except BaseException:
            while pending:
                done = self.wait(min_completions=1, timeout_s=30.0)
                if not done:
                    break
                for c in done:
                    pending.pop(c.tag, None)
            raise
        if err is not None:
            raise err
        if inflight_peak:
            # overlap observability: how deep the submit-while-draining
            # pipeline actually ran — a peak pinned at queue_depth means the
            # gather kept the queue full across op boundaries (the overlap
            # claim); a shallow peak means the op stream, not the engine,
            # was the limit
            self.op_scope.gauge("gather_inflight_peak").max(inflight_peak)
        return total

    # -- async vectored gather: completion-driven submission ---------------
    # The intra-batch streaming API (ISSUE 5 tentpole): submit a whole
    # gather, then poll it for CHUNK-granular completions while doing other
    # work (decode, device_put) between polls — the SQ/CQ decoupling the
    # blocking read_vectored hides inside one call. On the uring engine the
    # generic implementation below batches submissions through
    # sc_submit_raw_batch (one io_uring_enter per refill) and reaps through
    # sc_wait — real ring-native decoupling; on the python engine the same
    # code rides the worker pool's submit/done queues. MultiRingEngine
    # overrides it to fan per-file sub-tokens across member rings.
    #
    # Concurrency contract: a live token owns the engine's gather path the
    # same way a read_vectored call does — the delivery layer holds its
    # engine lock from submit_vectored until drain/close (per-ring locks on
    # the multi engine). Exactly one thread drives poll/drain per token.

    def submit_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                        dest: np.ndarray, *, retries: int = 1,
                        req_id: "int | None" = None) -> StreamToken:
        """Begin an async gather of (file_index, file_offset, dest_offset,
        length) chunks into *dest*. Pieces are submitted up to queue_depth
        immediately; the rest flow in as :meth:`poll` reaps completions.
        The returned token must be driven to :meth:`drain` (or handed to
        :meth:`cancel`) before the engine is used for another transfer.
        *req_id* tags the token with the traced request it executes
        (strom/obs/request.py), for attribution only."""
        tok = StreamToken(chunks, dest, self.config.block_size, retries,
                          req_id=req_id)
        self._track_token(tok)
        self._pump_token(tok)
        return tok

    def poll(self, token: StreamToken, min_completions: int = 1,
             timeout_s: float | None = None) -> list[ChunkCompletion]:
        """Advance the gather: reap engine completions, retry failed pieces,
        top the submission queue back up, and return chunks that fully
        retired since the last call. Blocks until *min_completions* chunk
        completions are available (0 = never block), the token is done, or
        *timeout_s* elapses."""
        if token.cancelled:
            raise EngineError(_ECANCELED, "token cancelled (engine closing?)")
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        self._pump_token(token)
        while (len(token._ready) < max(min_completions, 1)
               and token._pending and not token.cancelled):
            if min_completions <= 0:
                wait_s = 0.0
            elif deadline is None:
                wait_s = None
            else:
                wait_s = max(0.0, deadline - time.monotonic())
            got = self._reap_token(token, wait_s)
            self._pump_token(token)
            if min_completions <= 0:
                break
            if not got and deadline is not None \
                    and time.monotonic() >= deadline:
                break
        out = token._ready
        token._ready = []
        if token.done:
            self._untrack_token(token)
        return out

    def drain(self, token: StreamToken) -> int:
        """Run the token to completion and return total bytes landed.
        Raises the first chunk error (retries exhausted, short read) AFTER
        every in-flight piece has retired — a caller reacting to the error
        can never race live engine writes into its buffer."""
        while not token.done:
            self.poll(token, min_completions=1)
        self._untrack_token(token)
        if token.cancelled:
            raise EngineError(_ECANCELED, "token cancelled (engine closing?)")
        if token._err is not None:
            raise token._err
        return token.bytes_done

    def cancel(self, token: StreamToken, timeout_s: float = 30.0) -> None:
        """Stop feeding the token and reap everything already in flight
        (the kernel/worker owns the dest bytes until each piece completes —
        abandoning them would leave writes landing into recycled memory).
        The token is marked cancelled FIRST — a concurrent poll/drain
        driver (close() racing a live streamed gather) raises ECANCELED on
        its next call and stops competing for completions — then the
        remaining pieces are reaped in short wait slices, re-checking the
        (possibly concurrently drained) pending set between slices."""
        token.cancelled = True
        token._exhausted = True
        token._backlog.clear()
        deadline = time.monotonic() + timeout_s
        while token._pending and time.monotonic() < deadline:
            self._reap_token(token, 0.05)
        self._untrack_token(token)

    # token bookkeeping for cancellation-on-close: engines call
    # _cancel_live_tokens() at the top of close() so no completion is left
    # in flight against a dying ring/worker pool
    def _track_token(self, tok: StreamToken) -> None:
        if not hasattr(self, "_live_tokens"):
            self._live_tokens: list[StreamToken] = []
        self._live_tokens.append(tok)

    def _untrack_token(self, tok: StreamToken) -> None:
        toks = getattr(self, "_live_tokens", None)
        if toks is not None and tok in toks:
            toks.remove(tok)

    def _cancel_live_tokens(self) -> None:
        for tok in list(getattr(self, "_live_tokens", ())):
            try:
                self.cancel(tok)
            except Exception:
                pass

    def _pump_token(self, tok: StreamToken) -> None:
        """Refill the submission queue from the backlog + piece iterator up
        to queue_depth, batched through ONE submit_raw call (one
        io_uring_enter on the native engine). Partial accepts (a concurrent
        submitter raced us past the depth pre-check — uring's ``.accepted``
        contract) push the unaccepted tail back onto the backlog."""
        if tok._err is not None or tok.cancelled:
            return
        qd = self.config.queue_depth
        while len(tok._pending) < qd:
            batch: list[tuple[int, int, int, int, int, int]] = []
            while len(tok._pending) + len(batch) < qd:
                if tok._backlog:
                    batch.append(tok._backlog.pop())
                    continue
                if tok._exhausted:
                    break
                try:
                    batch.append(next(tok._pieces))
                except StopIteration:
                    tok._exhausted = True
                    break
            if not batch:
                return
            if not hasattr(self, "_vec_tag"):
                self._vec_tag = 0
            reqs = []
            for piece in batch:
                ci, fi, fo, do, want, attempts = piece
                tag = self._vec_tag
                self._vec_tag += 1
                # registered BEFORE submission: a completion can land (and a
                # concurrent reap must find the entry) inside submit_raw
                tok._pending[tag] = piece
                reqs.append(RawRead(fi, fo, want,
                                    tok._d8[do: do + want], tag))
            try:
                self.submit_raw(reqs)
            except EngineError as e:
                if e.errno != errno.EAGAIN:
                    # unsubmittable op (bad index/addr, closed engine):
                    # resubmitting is futile — requests past `accepted`
                    # (0 when absent) never entered the ring; unregister
                    # them and fail the token (in-flight pieces still
                    # drain through poll/drain)
                    accepted = getattr(e, "accepted", 0)
                    for r in reqs[accepted:]:
                        tok._pending.pop(r.tag, None)
                    tok._err = e
                    tok._exhausted = True
                    tok._backlog.clear()
                    return
                # queue full: requests[accepted:] never entered the ring —
                # back onto the backlog for the next refill
                accepted = getattr(e, "accepted", 0)
                for r, piece in zip(reqs[accepted:], batch[accepted:]):
                    tok._pending.pop(r.tag, None)
                    tok._backlog.append(piece)
                break
            if len(tok._pending) > tok.inflight_peak:
                tok.inflight_peak = len(tok._pending)
        if len(tok._pending) > tok.inflight_peak:
            tok.inflight_peak = len(tok._pending)

    def _reap_token(self, tok: StreamToken, timeout_s: float | None) -> int:
        """One wait() round: retire pieces, resubmit failed ones within the
        retry budget, record chunk completions. Returns completions seen."""
        try:
            comps = self.wait(min_completions=1, timeout_s=timeout_s)
        except EngineError as e:
            tok._err = tok._err or e
            tok._exhausted = True
            tok._backlog.clear()
            return 0
        n = 0
        for c in comps:
            piece = tok._pending.pop(c.tag, None)
            if piece is None:
                continue  # foreign tag: not ours to account
            n += 1
            ci, fi, fo, do, want, attempts = piece
            if c.result < 0 and attempts < tok.retries \
                    and tok._err is None and not tok.cancelled:
                self.op_scope.add("chunk_retries")
                tok._backlog.append((ci, fi, fo, do, want, attempts + 1))
                continue
            if c.result < 0:
                err = EngineError(
                    -c.result, f"read failed after {attempts + 1} attempts: "
                               f"{os.strerror(-c.result)}")
            elif c.result < want:
                tok.bytes_done += c.result
                err = EngineError(
                    _ENODATA, f"short read ({c.result} < {want}) — "
                              "file smaller than requested range?")
            else:
                tok.bytes_done += c.result
                err = None
            if err is not None:
                if tok._err is None:
                    tok._err = err
                tok._exhausted = True  # stop feeding; drain what's in flight
                tok._backlog.clear()
                if tok._results[ci] is None:
                    tok._results[ci] = -(err.errno or errno.EIO)
                    tok.chunks_done += 1
                    tok._ready.append(
                        ChunkCompletion(ci, tok._results[ci]))
                continue
            tok._left[ci] -= want
            if tok._left[ci] == 0 and tok._results[ci] is None:
                ln = tok.chunks[ci][3]
                tok._results[ci] = ln
                tok.chunks_done += 1
                tok._ready.append(ChunkCompletion(ci, ln))
        return n

    # -- convenience: synchronous read of an arbitrary range ----------------
    def read_into(self, file_index: int, offset: int, length: int,
                  out: np.ndarray | memoryview, out_offset: int = 0) -> int:
        """Synchronously read file[offset:offset+length] into *out* using the
        staging pool in block_size chunks. Returns bytes read (short at EOF)."""
        block = self.config.block_size
        out_mv = memoryview(out).cast("B") if not isinstance(out, np.ndarray) else memoryview(out.view(np.uint8))
        done = 0
        pending: dict[int, tuple[int, int, int]] = {}  # tag -> (buf_index, out_pos, want)
        free = list(range(min(self.num_buffers, self.config.queue_depth)))
        next_tag = 0
        pos = 0
        short_read = False
        while pos < length or pending:
            while pos < length and free and not short_read:
                want = min(block, length - pos)
                buf = free.pop()
                tag = next_tag
                next_tag += 1
                self.submit([ReadRequest(file_index, offset + pos, want, buf, tag)])
                pending[tag] = (buf, pos, want)
                pos += want
            if not pending:
                break
            for c in self.wait(min_completions=1):
                buf, out_pos, want = pending.pop(c.tag)
                if c.result < 0:
                    raise EngineError(-c.result, f"read failed: {os.strerror(-c.result)}")
                if c.result:
                    out_mv[out_offset + out_pos: out_offset + out_pos + c.result] = \
                        self.buffer(buf)[:c.result]
                done += c.result
                if c.result < want:
                    short_read = True  # EOF: stop submitting further chunks
                free.append(buf)
        return done


    def read_into_direct(self, file_index: int, offset: int, length: int,
                         dest: np.ndarray) -> int:
        """Read file[offset:offset+length) straight into *dest* (uint8, len >=
        length), chunked at block_size and pipelined at queue_depth, with no
        staging-pool bounce. Returns bytes read (short at EOF)."""
        block = self.config.block_size
        pending: dict[int, int] = {}  # tag -> want
        next_tag = 0
        pos = 0
        done = 0
        short_read = False
        d8 = dest.view(np.uint8).reshape(-1)
        while pos < length or pending:
            while (pos < length and len(pending) < self.config.queue_depth
                   and not short_read):
                want = min(block, length - pos)
                tag = next_tag
                next_tag += 1
                self.submit_raw([RawRead(file_index, offset + pos, want,
                                         d8[pos: pos + want], tag)])
                pending[tag] = want
                pos += want
            if not pending:
                break
            for c in self.wait(min_completions=1):
                want = pending.pop(c.tag)
                if c.result < 0:
                    raise EngineError(-c.result, f"read failed: {os.strerror(-c.result)}")
                done += c.result
                if c.result < want:
                    short_read = True
        return done


def iter_chunks(offset: int, length: int, block: int) -> Iterable[tuple[int, int]]:
    """Split [offset, offset+length) into (offset, len) chunks of *block* bytes."""
    pos = offset
    end = offset + length
    while pos < end:
        take = min(block, end - pos)
        yield pos, take
        pos += take
