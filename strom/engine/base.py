"""Engine interface: submit/wait block reads into a registered staging pool.

This is the strom-tpu analogue of the reference's kernel-side DMA submit
engine + async completion path (SURVEY.md §2.1 "DMA submit engine",
"Async completion / WAIT"; reference cite UNVERIFIED — empty mount,
SURVEY.md §0).  The contract deliberately mirrors the ioctl surface:

==========================  =============================================
reference ioctl             Engine equivalent
==========================  =============================================
MAP_GPU_MEMORY              staging pool allocated+registered at engine init
LIST/INFO_GPU_MEMORY        Engine.buffers() / Engine.buffer_info()
MEMCPY_SSD2GPU(_ASYNC)      Engine.submit(ReadRequest...)
MEMCPY_WAIT                 Engine.wait(...)
stat ioctl / /proc node     Engine.stats()
==========================  =============================================

Two implementations share this interface: the C++ io_uring engine
(:mod:`strom.engine.uring_engine`, the fast path) and a pure-Python
preadv thread pool (:mod:`strom.engine.python_engine`, the portable
fallback).
"""

from __future__ import annotations

import abc
import contextlib
import dataclasses
import errno
import os
import time
from typing import Iterable, Sequence

import numpy as np

from strom.config import StromConfig

_ENODATA = errno.ENODATA
_ECANCELED = errno.ECANCELED


@dataclasses.dataclass(frozen=True)
class ReadRequest:
    """One block read: file[offset : offset+length] → pool[buf_index][buf_offset:]."""

    file_index: int    # from Engine.register_file
    offset: int        # byte offset in file
    length: int        # bytes to read (<= buffer_size - buf_offset)
    buf_index: int     # staging pool slot
    tag: int           # caller-chosen completion tag
    buf_offset: int = 0


@dataclasses.dataclass(frozen=True)
class RawRead:
    """One block read straight into caller-owned memory (no staging pool).

    *dest* must be a writable C-contiguous uint8 view whose lifetime the caller
    guarantees until the op completes; for the O_DIRECT path it must satisfy
    the file's memory alignment (use :func:`strom.delivery.buffers.alloc_aligned`).
    """

    file_index: int
    offset: int
    length: int
    dest: np.ndarray
    tag: int


@dataclasses.dataclass(frozen=True)
class RawWrite:
    """One block write from caller-owned memory to file[offset:offset+length)
    (ISSUE 13: the write twin of :class:`RawRead`). *src* must be a readable
    C-contiguous uint8 view whose lifetime the caller guarantees until the op
    completes; for the O_DIRECT path it must satisfy the file's memory
    alignment (the slab pool's buffers do). The file must have been
    registered with ``writable=True``."""

    file_index: int
    offset: int
    length: int
    src: np.ndarray
    tag: int

    @property
    def dest(self) -> np.ndarray:
        # uniform accessor: engine internals (keepalives, fault flips,
        # python workers) address "the op's buffer" without branching on
        # direction; for a write that buffer is the source
        return self.src


@dataclasses.dataclass(frozen=True)
class Completion:
    tag: int
    result: int        # bytes read/written (>=0) or negative errno


@dataclasses.dataclass(frozen=True)
class ChunkCompletion:
    """One gather chunk retired by the async vectored path: *index* is the
    position in the chunk list handed to :meth:`Engine.submit_vectored`;
    *result* is the chunk's full byte count, or a negative errno when the
    chunk failed (retries exhausted / short read → -ENODATA)."""

    index: int
    result: int


class EngineError(OSError):
    pass


class EngineStallError(EngineError):
    """The engine stopped answering: no completion arrived within the
    configured ``engine_wait_timeout_s`` while ops were in flight. Carries
    the stuck tags so the operator (and the flight bundle) can say WHICH
    ops wedged instead of staring at a silent 30 s loop."""

    def __init__(self, timeout_s: float, tags: Sequence[int], where: str):
        self.stuck_tags = tuple(tags)
        shown = ", ".join(str(t) for t in self.stuck_tags[:8])
        if len(self.stuck_tags) > 8:
            shown += f", ... ({len(self.stuck_tags)} total)"
        super().__init__(
            errno.ETIMEDOUT,
            f"engine stall in {where}: no completion for {timeout_s:.1f}s "
            f"with {len(self.stuck_tags)} op(s) in flight (tags: {shown})")


class DeadlineExceeded(EngineError):
    """The request's deadline expired mid-gather: retries stop, waits
    stop, and the gather fails fast instead of blowing the tenant's SLO
    budget on a read nobody is still waiting for."""

    def __init__(self, msg: str):
        super().__init__(errno.ETIMEDOUT, f"deadline exceeded: {msg}")


class StreamToken:
    """Handle for one in-flight vectored gather (:meth:`Engine.submit_vectored`).

    The token owns the submission state machine's bookkeeping: which chunks
    retired, how many block-size pieces are in flight, and the per-chunk
    error results. It is NOT thread-safe — exactly one thread drives
    poll/drain per token (the delivery layer's streaming gather does), the
    same contract read_vectored has always had.
    """

    __slots__ = ("chunks", "retries", "_d8", "_left", "_results", "_pending",
                 "_pieces", "_backlog", "_exhausted", "_ready", "bytes_done",
                 "cancelled", "inflight_peak", "_err", "chunks_done",
                 "req_id", "deadline", "fail_fast", "_delayed",
                 "retries_used", "failed_chunks", "op")

    def __init__(self, chunks: Sequence[tuple[int, int, int, int]],
                 dest: np.ndarray, block: int, retries: int,
                 req_id: "int | None" = None,
                 deadline: "float | None" = None, fail_fast: bool = True,
                 op: str = "read"):
        self.chunks = list(chunks)
        self.retries = retries
        # op direction (ISSUE 13): "read" gathers file->dest, "write"
        # scatters dest->file (dest is then the SOURCE buffer). The whole
        # submit/poll/drain state machine is direction-agnostic — only the
        # RawRead/RawWrite built per piece differs.
        self.op = op
        # causal request tracing (ISSUE 8): the req_id of the request this
        # gather belongs to, if traced — carried on the token so poll/drain
        # telemetry and tools can attribute engine work to one request
        self.req_id = req_id
        # deadline (ISSUE 9): absolute time.monotonic() seconds; poll/drain
        # waits and retry scheduling stop at it — the gather fails fast with
        # DeadlineExceeded instead of retrying into a dead SLO window
        self.deadline = deadline
        # fail_fast=True (the read_vectored contract): the first exhausted
        # chunk stops feeding the rest. False (the streamed/resilient path):
        # a failed chunk surfaces as a negative ChunkCompletion and the
        # REST of the gather keeps flowing, so one bad extent no longer
        # kills a whole batch — the delivery layer re-reads just the
        # failed chunk on the fallback path
        self.fail_fast = fail_fast
        self._d8 = dest.view(np.uint8).reshape(-1)
        # bytes of each chunk not yet landed; a chunk retires when it hits 0
        self._left = [ln for (_, _, _, ln) in self.chunks]
        self._results: list[int | None] = [None] * len(self.chunks)
        # tag -> (chunk_idx, file_idx, file_off, dest_off, want, attempts)
        self._pending: dict[int, tuple[int, int, int, int, int, int]] = {}
        self._pieces = ((ci, fi, fo + p, do + p, min(block, ln - p), 0)
                        for ci, (fi, fo, do, ln) in enumerate(self.chunks)
                        for p in range(0, ln, block))
        # pieces bounced by a full queue (EAGAIN / partial batch accept):
        # resubmitted before the iterator advances
        self._backlog: list[tuple[int, int, int, int, int, int]] = []
        # retries waiting out their backoff: (ready_monotonic_s, piece) —
        # _pump_token promotes due entries to the backlog (ISSUE 9)
        self._delayed: list[tuple[float, tuple[int, int, int, int, int, int]]] = []
        self._exhausted = not self.chunks
        self._ready: list[ChunkCompletion] = []
        self.bytes_done = 0
        self.cancelled = False
        self.inflight_peak = 0
        self._err: EngineError | None = None
        self.chunks_done = 0
        self.retries_used = 0   # per-gather retry-budget consumption
        self.failed_chunks = 0  # chunks retired with a negative result

    @property
    def done(self) -> bool:
        return (self._exhausted and not self._backlog and not self._pending
                and not self._delayed) or self.cancelled

    def next_retry_in_s(self) -> "float | None":
        """Seconds until the earliest backoff retry is due (None: no
        delayed retries pending)."""
        if not self._delayed:
            return None
        return max(0.0, min(t for t, _ in self._delayed) - time.monotonic())

    def pending_chunk_indices(self) -> set:
        """Chunk indices with at least one piece IN FLIGHT right now —
        the hedge-eligible set: a chunk the engine was never asked for
        has nothing to race (strom/delivery/stream.py)."""
        return {p[0] for p in self._pending.values()}

    def deadline_remaining_s(self) -> "float | None":
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    @property
    def error(self) -> EngineError | None:
        return self._err


class Engine(abc.ABC):
    """Owns the staging pool and the submission/completion machinery."""

    name: str = "abstract"
    # True: read_vectored is internally thread-safe (per-ring locking) and
    # the delivery layer must NOT wrap gathers in its own whole-transfer
    # lock (see MultiRingEngine). Single-ring engines keep the default.
    concurrent_gathers: bool = False

    def __init__(self, config: StromConfig):
        self.config = config

    # -- file registration (≙ CHECK_FILE handing an fd to the kmod) ---------
    @abc.abstractmethod
    def register_file(self, path: str, *, o_direct: bool | None = None,
                      writable: bool = False) -> int:
        """Open (or adopt) *path* and return a file index for ReadRequests.

        o_direct=None uses the engine config / per-file auto-probe.
        writable=True (ISSUE 13) opens the file read-write so the index
        also accepts :class:`RawWrite` ops / ``op="write"`` gathers; the
        caller creates and sizes the file first."""

    @abc.abstractmethod
    def unregister_file(self, file_index: int) -> None: ...

    @abc.abstractmethod
    def file_uses_o_direct(self, file_index: int) -> bool: ...

    # -- staging pool (≙ MAP/LIST/INFO_GPU_MEMORY) --------------------------
    @abc.abstractmethod
    def buffer(self, buf_index: int) -> np.ndarray:
        """Zero-copy uint8 view of one pool slot (length == buffer_size)."""

    @property
    def num_buffers(self) -> int:
        return self.config.num_buffers

    @property
    def buffer_size(self) -> int:
        return self.config.buffer_size

    def buffer_info(self) -> dict:
        return {
            "num_buffers": self.num_buffers,
            "buffer_size": self.buffer_size,
            "total_bytes": self.num_buffers * self.buffer_size,
            "engine": self.name,
        }

    # -- submission / completion (≙ MEMCPY_SSD2GPU_ASYNC / MEMCPY_WAIT) -----
    @abc.abstractmethod
    def submit(self, requests: Sequence[ReadRequest]) -> int:
        """Queue reads; returns number submitted. Non-blocking up to queue_depth;
        raises EngineError if more than queue_depth ops would be in flight."""

    @abc.abstractmethod
    def submit_raw(self, requests: Sequence[RawRead]) -> int:
        """Queue reads into caller-owned memory (bypasses the staging pool).

        All-or-nothing: a batch exceeding the free queue depth raises
        EngineError(EAGAIN) with nothing submitted. (The uring engine can be
        raced past its pre-check by a concurrent submitter; its EngineError
        then carries ``.accepted`` — see UringEngine.submit_raw.)"""

    @abc.abstractmethod
    def wait(self, min_completions: int = 1, timeout_s: float | None = None) -> list[Completion]:
        """Block until >= min_completions ops retire (or timeout); return them."""

    @abc.abstractmethod
    def in_flight(self) -> int: ...

    @abc.abstractmethod
    def stats(self) -> dict: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    def __enter__(self) -> "Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- per-op telemetry scope (multi-tenant observability) ----------------
    # The delivery context threads its label scope here so two pipelines /
    # tenants sharing one engine fleet surface distinguishable per-op
    # latency (engine_op_lat_us histogram) and queue occupancy
    # (engine_inflight gauge) on /metrics, while the unlabeled aggregate
    # stays the whole engine's truth. engine_inflight is a LAST-STATE gauge
    # (not a sum across scopes): each write snapshots the engine-wide
    # in-flight count at that scope's most recent submit/reap edge.
    def set_scope(self, scope) -> None:
        """Install the telemetry scope (a ``StatsRegistry`` or
        ``ScopedStats``) per-op accounting writes through."""
        self._op_scope = scope

    @property
    def op_scope(self):
        sc = getattr(self, "_op_scope", None)
        if sc is None:
            from strom.utils.stats import global_stats

            return global_stats
        return sc

    def _note_submitted(self, requests: Sequence) -> None:
        """Stamp submit time per tag (engine_op_lat_us measures submit →
        completion, the queue-resident latency the consumer actually pays,
        not just device service time) and refresh the occupancy gauge."""
        m = getattr(self, "_op_submit_t", None)
        if m is None:
            m = self._op_submit_t = {}
        t = time.perf_counter()
        for r in requests:
            m[r.tag] = t
        # accounting must never fail a submission
        with contextlib.suppress(Exception):
            self.op_scope.set_gauge("engine_inflight", self.in_flight())

    def _note_completed(self, completions: Sequence[Completion]) -> None:
        m = getattr(self, "_op_submit_t", None)
        sc = self.op_scope
        if m:
            t = time.perf_counter()
            h = sc.histogram("engine_op_lat")
            for c in completions:
                t0 = m.pop(c.tag, None)
                if t0 is not None:
                    h.observe_us((t - t0) * 1e6)
        with contextlib.suppress(Exception):
            sc.set_gauge("engine_inflight", self.in_flight())

    # -- resilience policy (ISSUE 9) ----------------------------------------
    @property
    def retry_policy(self):
        """The engine's retry policy (backoff + jitter + per-gather budget),
        built lazily from config — shared by the blocking and async gather
        paths so their retry behavior can never diverge."""
        pol = getattr(self, "_retry_policy", None)
        if pol is None:
            from strom.engine.resilience import RetryPolicy

            pol = self._retry_policy = RetryPolicy.from_config(self.config)
        return pol

    @property
    def wait_timeout_s(self) -> float:
        """Engine stall watchdog bound: the longest any generic gather path
        waits on a single completion before raising EngineStallError
        (config ``engine_wait_timeout_s``; was a hard-coded 30 s)."""
        return getattr(self.config, "engine_wait_timeout_s", 30.0)

    @staticmethod
    def _request_deadline() -> "float | None":
        """The current traced request's absolute deadline (monotonic
        seconds), if one is active and carries one — how a caller-level
        deadline reaches the engine's wait loops without threading a
        parameter through every override."""
        try:
            from strom.obs import request as _request

            req = _request.current()
            return getattr(req, "deadline", None) if req is not None else None
        # stromlint: ignore[swallowed-exceptions] -- no traced request (or
        # an uninitialized tracing import during teardown) legitimately
        # means 'no deadline'; there is nothing to count
        except Exception:
            return None

    def _note_stall(self, where: str) -> None:
        with contextlib.suppress(Exception):
            self.op_scope.add("engine_stall_timeouts")

    # -- optional registered-dest support (io_uring READ_FIXED) -------------
    def register_dest(self, arr: np.ndarray) -> int:
        """Register a caller slab so gathers into it can use pre-pinned
        fixed buffers. -1 = not supported by this engine (the default);
        reads work identically either way."""
        return -1

    def unregister_dest(self, arr: np.ndarray) -> None:
        pass

    def unregister_dest_addr(self, addr: int) -> None:
        pass

    # -- vectored gather: the delivery layer's hot path ---------------------
    def read_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                      dest: np.ndarray, *, retries: int = 1) -> int:
        """Execute a whole gather list: chunks of (file_index, file_offset,
        dest_offset, length) → dest, block_size-chunked and pipelined at
        queue_depth, with per-chunk retry. Returns total bytes read.

        Must not run concurrently with other submitters on this engine (the
        delivery layer serializes transfers). Raises EngineError; ENODATA
        means a short read (range extends past EOF).

        This default uses submit_raw/wait per block; the C++ engine overrides
        it with a single native call (one Python-boundary crossing per
        transfer instead of per 128KiB block).
        """
        block = self.config.block_size
        qd = self.config.queue_depth
        policy = self.retry_policy
        deadline = self._request_deadline()
        stall_s = self.wait_timeout_s
        d8 = dest.view(np.uint8).reshape(-1)
        if not hasattr(self, "_vec_tag"):
            self._vec_tag = 0
        # tag -> (file_idx, file_off, dest_off, want, attempts)
        pending: dict[int, tuple[int, int, int, int, int]] = {}
        # backoff retries waiting to become due: (ready_t, fi, fo, do, want,
        # attempts) — resubmitted ahead of the fresh-piece iterator
        delayed: list[tuple[float, int, int, int, int, int]] = []
        it = ((fi, fo + p, do + p, min(block, ln - p))
              for (fi, fo, do, ln) in chunks
              for p in range(0, ln, block))
        exhausted = False
        total = 0
        inflight_peak = 0
        retries_used = 0
        err: EngineError | None = None
        try:
            while not exhausted or pending or delayed:
                now = time.monotonic()
                while delayed and len(pending) < qd and err is None:
                    # due retries first (they were in flight before any
                    # still-fresh piece); not-due ones wait their backoff
                    delayed.sort()
                    if delayed[0][0] > now:
                        break
                    _, fi, fo, do, want, attempts = delayed.pop(0)
                    tag = self._vec_tag
                    self._vec_tag += 1
                    self.submit_raw([RawRead(fi, fo, want,
                                             d8[do: do + want], tag)])
                    pending[tag] = (fi, fo, do, want, attempts)
                while not exhausted and len(pending) < qd and err is None:
                    try:
                        fi, fo, do, ln = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    tag = self._vec_tag
                    self._vec_tag += 1
                    self.submit_raw([RawRead(fi, fo, ln, d8[do: do + ln], tag)])
                    pending[tag] = (fi, fo, do, ln, 0)
                if len(pending) > inflight_peak:
                    inflight_peak = len(pending)
                if not pending:
                    if delayed and err is None:
                        # nothing in flight: sleep out the earliest backoff
                        wake = min(d[0] for d in delayed)
                        if deadline is not None and wake >= deadline:
                            self.op_scope.add("deadline_exceeded")
                            raise DeadlineExceeded(
                                f"{len(delayed)} retrie(s) still backing "
                                "off at deadline")
                        time.sleep(max(0.0, wake - time.monotonic()))
                        continue
                    break
                wait_s = stall_s
                if delayed:
                    # wake for the earliest backoff retry: a due resubmit
                    # must not wait behind an unrelated slow completion
                    # (the async token path bounds with next_retry_in_s;
                    # this is the blocking twin)
                    wait_s = min(wait_s,
                                 max(min(d[0] for d in delayed)
                                     - time.monotonic(), 0.001))
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        self.op_scope.add("deadline_exceeded")
                        raise DeadlineExceeded(
                            f"{len(pending)} op(s) still in flight")
                    wait_s = min(wait_s, left)
                got = self.wait(min_completions=1, timeout_s=wait_s)
                if not got:
                    if err is None and deadline is not None \
                            and time.monotonic() >= deadline:
                        self.op_scope.add("deadline_exceeded")
                        raise DeadlineExceeded(
                            f"{len(pending)} op(s) still in flight")
                    if wait_s >= stall_s:
                        self._note_stall("read_vectored")
                        if err is None:
                            raise EngineStallError(stall_s, list(pending),
                                                   "read_vectored")
                        # engine wedged while draining after a chunk error:
                        # bounded abandon (same contract as the exception
                        # drain below) and surface the original error —
                        # not an unbounded wait for completions that are
                        # never coming
                        break
                for c in got:
                    entry = pending.pop(c.tag, None)
                    if entry is None:
                        continue  # foreign tag: not ours to account
                    fi, fo, do, want, attempts = entry
                    failed_errno = -c.result if c.result < 0 else \
                        (_ENODATA if c.result < want else 0)
                    if failed_errno and err is None:
                        within_deadline = deadline is None \
                            or time.monotonic() < deadline
                        if policy.should_retry(failed_errno, attempts,
                                               retries, retries_used):
                            if within_deadline:
                                retries_used += 1
                                self.op_scope.add("chunk_retries")
                                delay = policy.delay_s(attempts)
                                if delay > 0:
                                    self.op_scope.add("retry_backoff_waits")
                                delayed.append((time.monotonic() + delay,
                                                fi, fo, do, want,
                                                attempts + 1))
                                continue
                            # a retry the policy would take, denied by the
                            # deadline: the typed failure (and its count)
                            # — matching the token path's poll branch
                            self.op_scope.add("deadline_exceeded")
                            err = DeadlineExceeded(
                                f"piece retry at +{fo} denied "
                                f"({len(pending)} op(s) in flight)")
                        elif attempts < retries and \
                                retries_used >= policy.budget:
                            self.op_scope.add("retry_budget_exhausted")
                    if c.result < 0:
                        if err is None:
                            err = EngineError(
                                -c.result,
                                f"read failed after {attempts + 1} attempts: "
                                f"{os.strerror(-c.result)}")
                    elif c.result < want:
                        total += c.result
                        if err is None:
                            err = EngineError(
                                _ENODATA, f"short read ({c.result} < {want}) — "
                                          "file smaller than requested range?")
                    else:
                        total += c.result
                if err is not None:
                    exhausted = True  # stop feeding; drain what's in flight
                    delayed.clear()
        except BaseException as exc:
            # a deadline miss drains with a short grace, not the full stall
            # watchdog: fail-fast is the deadline's contract, and the ops a
            # wedged engine will never complete are abandoned (and counted)
            # either way
            drain_s = min(self.wait_timeout_s, 1.0) \
                if isinstance(exc, DeadlineExceeded) else self.wait_timeout_s
            while pending:
                done = self.wait(min_completions=1, timeout_s=drain_s)
                if not done:
                    # stuck in-flight ops: counted and abandoned (the
                    # pre-existing bounded-drain contract), now diagnosable
                    self._note_stall("read_vectored drain")
                    break
                for c in done:
                    pending.pop(c.tag, None)
            raise
        if err is not None:
            raise err
        if inflight_peak:
            # overlap observability: how deep the submit-while-draining
            # pipeline actually ran — a peak pinned at queue_depth means the
            # gather kept the queue full across op boundaries (the overlap
            # claim); a shallow peak means the op stream, not the engine,
            # was the limit
            self.op_scope.gauge("gather_inflight_peak").max(inflight_peak)
        return total

    # -- async vectored gather: completion-driven submission ---------------
    # The intra-batch streaming API (ISSUE 5 tentpole): submit a whole
    # gather, then poll it for CHUNK-granular completions while doing other
    # work (decode, device_put) between polls — the SQ/CQ decoupling the
    # blocking read_vectored hides inside one call. On the uring engine the
    # generic implementation below batches submissions through
    # sc_submit_raw_batch (one io_uring_enter per refill) and reaps through
    # sc_wait — real ring-native decoupling; on the python engine the same
    # code rides the worker pool's submit/done queues. MultiRingEngine
    # overrides it to fan per-file sub-tokens across member rings.
    #
    # Concurrency contract: a live token owns the engine's gather path the
    # same way a read_vectored call does — the delivery layer holds its
    # engine lock from submit_vectored until drain/close (per-ring locks on
    # the multi engine). Exactly one thread drives poll/drain per token.

    def submit_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                        dest: np.ndarray, *, retries: int = 1,
                        req_id: "int | None" = None,
                        deadline: "float | None" = None,
                        fail_fast: bool = True,
                        op: str = "read") -> StreamToken:
        """Begin an async gather of (file_index, file_offset, dest_offset,
        length) chunks into *dest*. Pieces are submitted up to queue_depth
        immediately; the rest flow in as :meth:`poll` reaps completions.
        The returned token must be driven to :meth:`drain` (or handed to
        :meth:`cancel`) before the engine is used for another transfer.
        *req_id* tags the token with the traced request it executes
        (strom/obs/request.py), for attribution only. *deadline* (absolute
        monotonic seconds; default: the active traced request's) bounds
        poll/drain waits and retry scheduling; *fail_fast*=False lets the
        rest of the gather continue past an exhausted chunk (it retires as
        a negative ChunkCompletion instead of stopping the feed) — the
        streamed delivery path recovers such chunks on the fallback
        engine. ``op="write"`` (ISSUE 13) runs the gather in reverse:
        *dest* is the SOURCE buffer and each chunk writes
        dest[dest_offset:dest_offset+length) to file[file_offset:) — the
        files must be registered ``writable=True``; retries rewrite whole
        pieces (idempotent at fixed offsets), short writes retry like
        short reads."""
        if op not in ("read", "write"):
            raise ValueError(f"op must be 'read' or 'write', got {op!r}")
        if deadline is None:
            deadline = self._request_deadline()
        tok = StreamToken(chunks, dest, self.config.block_size, retries,
                          req_id=req_id, deadline=deadline,
                          fail_fast=fail_fast, op=op)
        self._track_token(tok)
        self._pump_token(tok)
        return tok

    def write_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                       src: np.ndarray, *, retries: int = 1) -> int:
        """Blocking write twin of :meth:`read_vectored` (ISSUE 13): execute
        a whole scatter list of (file_index, file_offset, src_offset,
        length) chunks from *src*, block_size-chunked and pipelined at
        queue_depth with per-chunk retry, through the async token machinery.
        Returns total bytes written; raises EngineError on any failed or
        short chunk. Same single-transfer concurrency contract as
        read_vectored."""
        tok = self.submit_vectored(chunks, src, retries=retries, op="write")
        return self.drain(tok)

    def poll(self, token: StreamToken, min_completions: int = 1,
             timeout_s: float | None = None) -> list[ChunkCompletion]:
        """Advance the gather: reap engine completions, retry failed pieces,
        top the submission queue back up, and return chunks that fully
        retired since the last call. Blocks until *min_completions* chunk
        completions are available (0 = never block), the token is done, or
        *timeout_s* elapses."""
        if token.cancelled:
            raise EngineError(_ECANCELED, "token cancelled (engine closing?)")
        deadline = None if timeout_s is None else \
            time.monotonic() + timeout_s
        self._pump_token(token)
        while (len(token._ready) < max(min_completions, 1)
               and (token._pending or token._delayed)
               and not token.cancelled):
            if min_completions <= 0:
                wait_s = 0.0
            elif deadline is None:
                wait_s = None
            else:
                wait_s = max(0.0, deadline - time.monotonic())
            # cap every blocking wait at the request deadline and at the
            # next backoff-retry due time (a delayed retry with nothing in
            # flight must not sleep a full caller timeout before its
            # resubmit) — and at the stall watchdog, so a wedged engine
            # raises a diagnosable EngineStallError instead of hanging
            req_left = token.deadline_remaining_s()
            if wait_s is None or wait_s > 0:
                bound = self.wait_timeout_s
                retry_in = token.next_retry_in_s()
                if retry_in is not None:
                    bound = min(bound, max(retry_in, 0.001))
                if req_left is not None:
                    bound = min(bound, max(req_left, 0.0))
                wait_s = bound if wait_s is None else min(wait_s, bound)
            if req_left is not None and req_left <= 0:
                # close the token even when an earlier chunk error already
                # set _err: the zero wait bound above would otherwise spin
                # hot zero-timeout reaps until a caller watchdog fired,
                # with the chunks never getting their deadline closure
                if token._err is None:
                    self.op_scope.add("deadline_exceeded")
                    token._err = DeadlineExceeded(
                        f"{len(token._pending)} op(s) in flight, "
                        f"{len(token._delayed)} retrie(s) backing off")
                token._exhausted = True
                token._backlog.clear()
                token._delayed.clear()
                self._fail_pending_chunks(token)
                break
            wait_t0 = time.monotonic()
            got = self._reap_token(token, wait_s)
            self._pump_token(token)
            if min_completions <= 0:
                break
            # stall diagnosis needs the wait to have actually gone QUIET
            # for the whole watchdog: under concurrent gathers the engine
            # wait can return early with another token's completions
            # (got == 0 for us), which is a busy engine, not a wedged one
            if not got and not token._ready and token._pending \
                    and wait_s is not None \
                    and wait_s >= self.wait_timeout_s \
                    and time.monotonic() - wait_t0 >= self.wait_timeout_s:
                self._note_stall("poll")
                raise EngineStallError(self.wait_timeout_s,
                                       list(token._pending), "poll")
            if not got and deadline is not None \
                    and time.monotonic() >= deadline:
                break
        out = token._ready
        token._ready = []
        if token.done:
            self._untrack_token(token)
        return out

    def _fail_pending_chunks(self, token: StreamToken) -> None:
        """Retire every not-yet-completed chunk with the token's error
        (deadline expiry): the chunks get their negative ChunkCompletion
        so chunk accounting closes, while still-in-flight PIECES keep
        draining through poll/cancel — their dest writes stay owned by
        the kernel/worker until each retires."""
        e = token._err.errno if token._err is not None else errno.EIO
        for ci, r in enumerate(token._results):
            if r is None:
                token._results[ci] = -(e or errno.EIO)
                token.chunks_done += 1
                token.failed_chunks += 1
                token._ready.append(ChunkCompletion(ci, token._results[ci]))

    def drain(self, token: StreamToken) -> int:
        """Run the token to completion and return total bytes landed.
        Raises the first chunk error (retries exhausted, short read) AFTER
        every in-flight piece has retired — a caller reacting to the error
        can never race live engine writes into its buffer. Two bounded
        exceptions to "after every piece" (ISSUE 9): a DeadlineExceeded
        token fails fast (the caller must :meth:`cancel` before touching
        dest), and a completion wait past ``engine_wait_timeout_s`` with
        zero progress raises EngineStallError naming the stuck tags
        instead of looping silently."""
        while not token.done:
            if isinstance(token._err, DeadlineExceeded):
                # fail fast: still-in-flight pieces stay kernel-owned;
                # cancel() reaps them before dest may be reused
                raise token._err
            # no caller timeout: poll's own stall watchdog owns the bound.
            # (Passing timeout_s=wait_timeout_s would make poll's wait
            # slices deadline-minus-now — an epsilon UNDER the watchdog,
            # so the stall check could never fire and a wedged engine
            # would loop here forever.)
            self.poll(token, min_completions=1)
        self._untrack_token(token)
        if token.cancelled:
            raise EngineError(_ECANCELED, "token cancelled (engine closing?)")
        if token._err is not None:
            raise token._err
        return token.bytes_done

    def cancel(self, token: StreamToken,
               timeout_s: "float | None" = None) -> None:
        """Stop feeding the token and reap everything already in flight
        (the kernel/worker owns the dest bytes until each piece completes —
        abandoning them would leave writes landing into recycled memory).
        The token is marked cancelled FIRST — a concurrent poll/drain
        driver (close() racing a live streamed gather) raises ECANCELED on
        its next call and stops competing for completions — then the
        remaining pieces are reaped in short wait slices, re-checking the
        (possibly concurrently drained) pending set between slices.
        *timeout_s* defaults to ``engine_wait_timeout_s``; expiry counts
        an engine_stall_timeouts episode (the abandoned pieces are the
        diagnosable stuck tags)."""
        if timeout_s is None:
            timeout_s = self.wait_timeout_s
        token.cancelled = True
        token._exhausted = True
        token._backlog.clear()
        token._delayed.clear()
        deadline = time.monotonic() + timeout_s
        while token._pending and time.monotonic() < deadline:
            self._reap_token(token, 0.05)
        if token._pending:
            self._note_stall("cancel")
        self._untrack_token(token)

    # token bookkeeping for cancellation-on-close: engines call
    # _cancel_live_tokens() at the top of close() so no completion is left
    # in flight against a dying ring/worker pool
    def _track_token(self, tok: StreamToken) -> None:
        if not hasattr(self, "_live_tokens"):
            self._live_tokens: list[StreamToken] = []
        self._live_tokens.append(tok)

    def _untrack_token(self, tok: StreamToken) -> None:
        toks = getattr(self, "_live_tokens", None)
        if toks is not None and tok in toks:
            toks.remove(tok)

    def _cancel_live_tokens(self) -> None:
        for tok in list(getattr(self, "_live_tokens", ())):
            # best-effort reap at close: a child that cannot cancel anymore
            # is already past the point where its completions could land
            with contextlib.suppress(Exception):
                self.cancel(tok)

    def _pump_token(self, tok: StreamToken) -> None:
        """Refill the submission queue from the backlog + piece iterator up
        to queue_depth, batched through ONE submit_raw call (one
        io_uring_enter on the native engine). Partial accepts (a concurrent
        submitter raced us past the depth pre-check — uring's ``.accepted``
        contract) push the unaccepted tail back onto the backlog."""
        if (tok._err is not None and tok.fail_fast) or tok.cancelled:
            return
        if tok._delayed:
            # promote due backoff retries to the backlog (ISSUE 9): they
            # re-enter the submission queue ahead of fresh pieces
            now = time.monotonic()
            due = [p for t, p in tok._delayed if t <= now]
            if due:
                tok._delayed = [(t, p) for t, p in tok._delayed if t > now]
                tok._backlog.extend(due)
        qd = self.config.queue_depth
        while len(tok._pending) < qd:
            batch: list[tuple[int, int, int, int, int, int]] = []
            while len(tok._pending) + len(batch) < qd:
                if tok._backlog:
                    batch.append(tok._backlog.pop())
                    continue
                if tok._exhausted:
                    break
                try:
                    batch.append(next(tok._pieces))
                except StopIteration:
                    tok._exhausted = True
                    break
            if not batch:
                return
            if not hasattr(self, "_vec_tag"):
                self._vec_tag = 0
            reqs = []
            is_write = tok.op == "write"
            for piece in batch:
                ci, fi, fo, do, want, attempts = piece
                tag = self._vec_tag
                self._vec_tag += 1
                # registered BEFORE submission: a completion can land (and a
                # concurrent reap must find the entry) inside submit_raw
                tok._pending[tag] = piece
                if is_write:
                    reqs.append(RawWrite(fi, fo, want,
                                         tok._d8[do: do + want], tag))
                else:
                    reqs.append(RawRead(fi, fo, want,
                                        tok._d8[do: do + want], tag))
            try:
                self.submit_raw(reqs)
            except EngineError as e:
                if e.errno != errno.EAGAIN:
                    # unsubmittable op (bad index/addr, closed engine):
                    # resubmitting is futile — requests past `accepted`
                    # (0 when absent) never entered the ring; unregister
                    # them and fail the token (in-flight pieces still
                    # drain through poll/drain)
                    accepted = getattr(e, "accepted", 0)
                    for r in reqs[accepted:]:
                        tok._pending.pop(r.tag, None)
                    tok._err = e
                    tok._exhausted = True
                    tok._backlog.clear()
                    tok._delayed.clear()
                    return
                # queue full: requests[accepted:] never entered the ring —
                # back onto the backlog for the next refill
                accepted = getattr(e, "accepted", 0)
                for r, piece in zip(reqs[accepted:], batch[accepted:]):
                    tok._pending.pop(r.tag, None)
                    tok._backlog.append(piece)
                break
            if len(tok._pending) > tok.inflight_peak:
                tok.inflight_peak = len(tok._pending)
        if len(tok._pending) > tok.inflight_peak:
            tok.inflight_peak = len(tok._pending)

    def _reap_token(self, tok: StreamToken, timeout_s: float | None) -> int:
        """One wait() round: retire pieces, resubmit failed ones within the
        retry budget, record chunk completions. Returns completions seen."""
        try:
            comps = self.wait(min_completions=1, timeout_s=timeout_s)
        except EngineError as e:
            tok._err = tok._err or e
            tok._exhausted = True
            tok._backlog.clear()
            tok._delayed.clear()
            return 0
        policy = self.retry_policy
        n = 0
        for c in comps:
            piece = tok._pending.pop(c.tag, None)
            if piece is None:
                continue  # foreign tag: not ours to account
            n += 1
            ci, fi, fo, do, want, attempts = piece
            # transient failures AND injected/true short reads are
            # retryable (ISSUE 9): a short-read retry re-reads the whole
            # piece, so a flaky link's truncated transfer recovers to the
            # full bytes while a genuine EOF still fails with ENODATA once
            # the budget is spent
            failed_errno = -c.result if c.result < 0 else \
                (_ENODATA if c.result < want else 0)
            chunk_already_failed = tok._results[ci] is not None \
                and not tok.fail_fast
            retry_eligible = tok._err is None or not tok.fail_fast
            if failed_errno and retry_eligible and not tok.cancelled \
                    and not chunk_already_failed:
                left = tok.deadline_remaining_s()
                if policy.should_retry(failed_errno, attempts, tok.retries,
                                       tok.retries_used) \
                        and (left is None or left > 0):
                    tok.retries_used += 1
                    self.op_scope.add("chunk_retries")
                    delay = policy.delay_s(attempts)
                    if delay > 0:
                        self.op_scope.add("retry_backoff_waits")
                    tok._delayed.append(
                        (time.monotonic() + delay,
                         (ci, fi, fo, do, want, attempts + 1)))
                    continue
                if attempts < tok.retries \
                        and tok.retries_used >= policy.budget:
                    self.op_scope.add("retry_budget_exhausted")
            if c.result < 0:
                err = EngineError(
                    -c.result,
                    f"{tok.op} failed after {attempts + 1} attempts: "
                    f"{os.strerror(-c.result)}")
            elif c.result < want:
                tok.bytes_done += c.result
                err = EngineError(
                    _ENODATA, f"short {tok.op} ({c.result} < {want})"
                    + (" — file smaller than requested range?"
                       if tok.op == "read" else ""))
            else:
                tok.bytes_done += c.result
                err = None
            if err is not None:
                if tok._err is None:
                    tok._err = err
                if tok.fail_fast:
                    # stop feeding; drain what's in flight
                    tok._exhausted = True
                    tok._backlog.clear()
                    tok._delayed.clear()
                if tok._results[ci] is None:
                    tok._results[ci] = -(err.errno or errno.EIO)
                    tok.chunks_done += 1
                    tok.failed_chunks += 1
                    tok._ready.append(
                        ChunkCompletion(ci, tok._results[ci]))
                continue
            tok._left[ci] -= want
            if tok._left[ci] == 0 and tok._results[ci] is None:
                ln = tok.chunks[ci][3]
                tok._results[ci] = ln
                tok.chunks_done += 1
                tok._ready.append(ChunkCompletion(ci, ln))
        return n

    # -- convenience: synchronous read of an arbitrary range ----------------
    def read_into(self, file_index: int, offset: int, length: int,
                  out: np.ndarray | memoryview, out_offset: int = 0) -> int:
        """Synchronously read file[offset:offset+length] into *out* using the
        staging pool in block_size chunks. Returns bytes read (short at EOF)."""
        block = self.config.block_size
        out_mv = memoryview(out).cast("B") if not isinstance(out, np.ndarray) else memoryview(out.view(np.uint8))
        done = 0
        pending: dict[int, tuple[int, int, int]] = {}  # tag -> (buf_index, out_pos, want)
        free = list(range(min(self.num_buffers, self.config.queue_depth)))
        next_tag = 0
        pos = 0
        short_read = False
        while pos < length or pending:
            while pos < length and free and not short_read:
                want = min(block, length - pos)
                buf = free.pop()
                tag = next_tag
                next_tag += 1
                self.submit([ReadRequest(file_index, offset + pos, want, buf, tag)])
                pending[tag] = (buf, pos, want)
                pos += want
            if not pending:
                break
            for c in self.wait(min_completions=1):
                buf, out_pos, want = pending.pop(c.tag)
                if c.result < 0:
                    raise EngineError(-c.result, f"read failed: {os.strerror(-c.result)}")
                if c.result:
                    out_mv[out_offset + out_pos: out_offset + out_pos + c.result] = \
                        self.buffer(buf)[:c.result]
                done += c.result
                if c.result < want:
                    short_read = True  # EOF: stop submitting further chunks
                free.append(buf)
        return done


    def read_into_direct(self, file_index: int, offset: int, length: int,
                         dest: np.ndarray) -> int:
        """Read file[offset:offset+length) straight into *dest* (uint8, len >=
        length), chunked at block_size and pipelined at queue_depth, with no
        staging-pool bounce. Returns bytes read (short at EOF)."""
        block = self.config.block_size
        pending: dict[int, int] = {}  # tag -> want
        next_tag = 0
        pos = 0
        done = 0
        short_read = False
        d8 = dest.view(np.uint8).reshape(-1)
        while pos < length or pending:
            while (pos < length and len(pending) < self.config.queue_depth
                   and not short_read):
                want = min(block, length - pos)
                tag = next_tag
                next_tag += 1
                self.submit_raw([RawRead(file_index, offset + pos, want,
                                         d8[pos: pos + want], tag)])
                pending[tag] = want
                pos += want
            if not pending:
                break
            for c in self.wait(min_completions=1):
                want = pending.pop(c.tag)
                if c.result < 0:
                    raise EngineError(-c.result, f"read failed: {os.strerror(-c.result)}")
                done += c.result
                if c.result < want:
                    short_read = True
        return done


def iter_chunks(offset: int, length: int, block: int) -> Iterable[tuple[int, int]]:
    """Split [offset, offset+length) into (offset, len) chunks of *block* bytes."""
    pos = offset
    end = offset + length
    while pos < end:
        take = min(block, end - pos)
        yield pos, take
        pos += take
