"""ctypes binding to the C++ io_uring engine (libstrom_core.so).

The production data path (SURVEY.md §2.2: "C++ io_uring engine ... registered
buffers + registered fds, O_DIRECT ... completion futures surfaced to Python
... GIL-free wait").  ctypes foreign calls release the GIL, so submit/wait run
concurrently with Python-side work; bulk bytes never transit Python — they
land in the engine-owned pool and are exposed as zero-copy numpy views.
"""

from __future__ import annotations

import contextlib
import ctypes
import errno as _errno
import os
import threading
import time
from typing import Sequence

import numpy as np

from strom.config import StromConfig
from strom.engine.base import (Completion, DeadlineExceeded, Engine,
                               EngineError, RawRead, RawWrite, ReadRequest)
from strom.utils.stats import StatsRegistry
from strom.utils.locks import make_lock

_HIST_BUCKETS = 24


class _ScCompletion(ctypes.Structure):
    _fields_ = [("tag", ctypes.c_uint64), ("res", ctypes.c_int64)]


class _ScStats(ctypes.Structure):
    _fields_ = [
        ("ops_submitted", ctypes.c_uint64),
        ("ops_completed", ctypes.c_uint64),
        ("ops_errored", ctypes.c_uint64),
        ("ops_faulted", ctypes.c_uint64),
        ("bytes_read", ctypes.c_uint64),
        ("unaligned_fallback_reads", ctypes.c_uint64),
        ("eof_topup_reads", ctypes.c_uint64),
        ("lat_count", ctypes.c_uint64),
        ("lat_total_us", ctypes.c_uint64),
        ("lat_hist", ctypes.c_uint64 * _HIST_BUCKETS),
        ("in_flight", ctypes.c_uint32),
        ("fixed_buffers", ctypes.c_uint8),
        ("fixed_files", ctypes.c_uint8),
        ("mlocked", ctypes.c_uint8),
        ("chunk_retries", ctypes.c_uint64),
        ("coop_taskrun", ctypes.c_uint8),
        ("sparse_table", ctypes.c_uint8),
        ("ext_buffers", ctypes.c_uint32),
        ("ops_fixed", ctypes.c_uint64),
        ("sqpoll", ctypes.c_uint8),
        ("sqpoll_wakeup_errno", ctypes.c_uint32),
        ("cached_bytes", ctypes.c_uint64),
        ("media_bytes", ctypes.c_uint64),
        ("residency_probes", ctypes.c_uint64),
        ("ops_written", ctypes.c_uint64),
        ("bytes_written", ctypes.c_uint64),
        ("enter_submit_calls", ctypes.c_uint64),
        ("sqpoll_wakeups", ctypes.c_uint64),
    ]


class _ScVecSeg(ctypes.Structure):
    _fields_ = [
        ("file_index", ctypes.c_int32),
        ("length", ctypes.c_uint32),
        ("offset", ctypes.c_uint64),
        ("dest_offset", ctypes.c_uint64),
    ]


class _ScRawOp(ctypes.Structure):
    _fields_ = [
        ("file_index", ctypes.c_int32),
        ("length", ctypes.c_uint32),
        ("offset", ctypes.c_uint64),
        ("tag", ctypes.c_uint64),
        ("addr", ctypes.c_void_p),
        ("buf_index", ctypes.c_int32),  # registered table index; -1 = plain READ
        ("op_flags", ctypes.c_int32),   # bit0: force the buffered fd (hybrid)
    ]


# sc_vec_seg.length / sc_raw_op.length are uint32; ctypes would silently mask
# larger Python ints (5 GiB -> 1 GiB), turning an oversized chunk into a
# zero-tailed array with no error. Chunks are split to this limit before they
# reach ctypes, and anything that still doesn't fit raises.
_MAX_SEG = 1 << 31


def _split_chunks(chunks, limit: int = _MAX_SEG):
    """Split (file_index, file_offset, dest_offset, length) chunks so every
    length fits the C ABI's uint32 fields. Pure function (unit-tested)."""
    out = []
    for fi, fo, do, ln in chunks:
        if ln < 0:
            raise ValueError(f"negative chunk length {ln}")
        while ln > limit:
            out.append((fi, fo, do, limit))
            fo += limit
            do += limit
            ln -= limit
        out.append((fi, fo, do, ln))
    return out


_lib = None
_lib_lock = make_lock("app.uring_lib")


def _load_lib(variant: str = ""):
    global _lib
    with _lib_lock:
        if _lib is not None and not variant:
            return _lib
        from strom._core.build import ensure_built

        lib = ctypes.CDLL(ensure_built(variant), use_errno=True)
        lib.sc_create.restype = ctypes.c_void_p
        lib.sc_create.argtypes = [ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint32]
        lib.sc_destroy.argtypes = [ctypes.c_void_p]
        lib.sc_pool_base.restype = ctypes.c_void_p
        lib.sc_pool_base.argtypes = [ctypes.c_void_p]
        lib.sc_register_file.restype = ctypes.c_int
        lib.sc_register_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.sc_unregister_file.restype = ctypes.c_int
        lib.sc_unregister_file.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.sc_file_is_o_direct.restype = ctypes.c_int
        lib.sc_file_is_o_direct.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.sc_submit_read.restype = ctypes.c_int
        lib.sc_submit_read.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
                                       ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
                                       ctypes.c_uint64]
        lib.sc_submit_read_raw.restype = ctypes.c_int
        lib.sc_submit_read_raw.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
                                           ctypes.c_uint32, ctypes.c_void_p, ctypes.c_uint64]
        lib.sc_wait.restype = ctypes.c_int
        lib.sc_wait.argtypes = [ctypes.c_void_p, ctypes.POINTER(_ScCompletion),
                                ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int]
        lib.sc_in_flight.restype = ctypes.c_uint32
        lib.sc_in_flight.argtypes = [ctypes.c_void_p]
        lib.sc_get_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(_ScStats)]
        lib.sc_set_fault_every.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.sc_set_enter_fail_once.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.sc_submit_raw_batch.restype = ctypes.c_int
        lib.sc_submit_raw_batch.argtypes = [ctypes.c_void_p, ctypes.POINTER(_ScRawOp),
                                            ctypes.c_uint32,
                                            ctypes.POINTER(ctypes.c_int32)]
        lib.sc_read_vectored.restype = ctypes.c_int64
        lib.sc_read_vectored.argtypes = [ctypes.c_void_p, ctypes.POINTER(_ScVecSeg),
                                         ctypes.c_uint64, ctypes.c_void_p,
                                         ctypes.c_uint32, ctypes.c_uint32,
                                         ctypes.c_int32]
        lib.sc_register_dest.restype = ctypes.c_int
        lib.sc_register_dest.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                         ctypes.c_uint64]
        lib.sc_unregister_dest.restype = ctypes.c_int
        lib.sc_unregister_dest.argtypes = [ctypes.c_void_p, ctypes.c_int]
        if not variant:
            _lib = lib
        return lib


def uring_available() -> bool:
    """True if the kernel accepts io_uring_setup and the .so builds."""
    try:
        lib = _load_lib()
    except (RuntimeError, OSError):
        return False
    h = lib.sc_create(2, 1, 4096, 0)
    if not h:
        return False
    lib.sc_destroy(ctypes.c_void_p(h))
    return True


class UringEngine(Engine):
    name = "uring"

    def __init__(self, config: StromConfig, *, variant: str = ""):
        super().__init__(config)
        self._lib = _load_lib(variant)
        flags = (1 if config.mlock else 0) | (2 if config.register_buffers else 0) \
            | 4 | (8 if config.coop_taskrun else 0) \
            | (16 if config.sqpoll else 0) \
            | (32 if config.residency_hybrid else 0)
        handle = self._lib.sc_create(config.queue_depth, config.num_buffers,
                                     config.buffer_size, flags)
        if not handle:
            err = ctypes.get_errno()
            raise EngineError(err or _errno.ENOSYS,
                              f"io_uring engine init failed: {os.strerror(err or _errno.ENOSYS)}")
        self._h = ctypes.c_void_p(handle)
        pool_base = self._lib.sc_pool_base(self._h)
        pool_bytes = config.num_buffers * config.buffer_size
        # Zero-copy view over the engine-owned mmap'd pool.
        self._np_pool = np.ctypeslib.as_array(
            ctypes.cast(pool_base, ctypes.POINTER(ctypes.c_uint8)), shape=(pool_bytes,))
        self._fault_every = config.fault_every
        if config.fault_every:
            self._lib.sc_set_fault_every(self._h, config.fault_every)
        self._stats = StatsRegistry("engine.uring")
        self._closed = False
        self._comp_buf = (_ScCompletion * max(config.queue_depth, 64))()
        self._raw_keepalive: dict[int, np.ndarray] = {}
        # caller slabs registered for READ_FIXED gathers: base addr -> (table
        # index, length). read_vectored consults this so delivery transfers
        # into a registered slab ride the fixed path with no API change.
        # _dest_lock serializes registration changes against close(): a slab
        # GC finalizer may call unregister_dest_addr from any thread while
        # the main thread tears the ring down.
        self._dest_regs: dict[int, tuple[int, int]] = {}
        self._dest_lock = make_lock("engine.uring_dest")

    def register_file(self, path: str, *, o_direct: bool | None = None,
                      writable: bool = False) -> int:
        want = self.config.o_direct if o_direct is None else o_direct
        mode = 2 if want is None else (1 if want else 0)
        if writable:
            mode |= 8  # O_RDWR on both fds (ISSUE 13 write path)
        rc = self._lib.sc_register_file(self._h, os.fsencode(path), mode)
        if rc < 0:
            raise EngineError(-rc, f"register_file({path}): {os.strerror(-rc)}")
        return rc

    def unregister_file(self, file_index: int) -> None:
        self._lib.sc_unregister_file(self._h, file_index)

    def file_uses_o_direct(self, file_index: int) -> bool:
        rc = self._lib.sc_file_is_o_direct(self._h, file_index)
        if rc < 0:
            raise EngineError(-rc, os.strerror(-rc))
        return bool(rc)

    def buffer(self, buf_index: int) -> np.ndarray:
        if not 0 <= buf_index < self.config.num_buffers:
            raise IndexError(buf_index)
        start = buf_index * self.config.buffer_size
        return self._np_pool[start: start + self.config.buffer_size]

    def register_dest(self, arr: np.ndarray) -> int:
        """Register a caller slab in the ring's sparse buffer table so
        vectored gathers into it use IORING_OP_READ_FIXED (pages pre-pinned
        once instead of per-IO). Returns the table index, or -1 when
        unavailable (legacy table, slots exhausted, slab > 1GiB, RLIMIT).
        The slab must outlive the registration (delivery ties it to the
        backing mmap's lifetime)."""
        from strom.delivery.buffers import buf_addr

        nbytes = arr.nbytes
        if nbytes > (1 << 30):  # kernel cap per registered entry
            return -1
        addr = buf_addr(arr)
        with self._dest_lock:
            if self._closed:
                return -1
            rc = self._lib.sc_register_dest(self._h, ctypes.c_void_p(addr),
                                            nbytes)
            if rc < 0:
                return -1
            self._dest_regs[addr] = (rc, nbytes)
            return rc

    def unregister_dest(self, arr: np.ndarray) -> None:
        from strom.delivery.buffers import buf_addr

        self.unregister_dest_addr(buf_addr(arr))

    def unregister_dest_addr(self, addr: int) -> None:
        with self._dest_lock:
            if self._closed:
                return
            reg = self._dest_regs.pop(addr, None)
            if reg is not None:
                self._lib.sc_unregister_dest(self._h, reg[0])

    def _dest_index(self, base: int, need: int) -> int:
        """Registered-buffer table index whose entry covers
        [base, base+need), or -1. Delivery gathers mostly land in VIEWS of
        a registered slab (scheduler slices, pool sub-spans) whose data
        pointer sits strictly inside the registration; the kernel
        bounds-checks READ_FIXED addresses against the whole entry, so an
        interior match rides the fixed path just like an exact one."""
        reg = self._dest_regs.get(base)
        if reg is not None and need <= reg[1]:
            return reg[0]
        # snapshot: registrations are few (one per live slab) and a GC
        # finalizer may mutate the dict from another thread mid-scan
        for addr, (idx, ln) in list(self._dest_regs.items()):
            if addr <= base and base + need <= addr + ln:
                return idx
        return -1

    def submit(self, requests: Sequence[ReadRequest]) -> int:
        self._note_submitted(requests)
        for i, r in enumerate(requests):
            rc = self._lib.sc_submit_read(self._h, r.file_index, r.offset, r.length,
                                          r.buf_index, r.buf_offset, r.tag)
            if rc < 0:
                # requests[i:] never entered the ring: drop their latency
                # stamps (same cleanup contract as submit_raw) — a stale
                # stamp would leak, and a later reused tag would pop it
                # into a wildly inflated engine_op_lat observation
                stamps = getattr(self, "_op_submit_t", None) or {}
                for rr in requests[i:]:
                    stamps.pop(rr.tag, None)
                raise EngineError(-rc, f"submit: {os.strerror(-rc)}")
        return len(requests)

    def submit_raw(self, requests: Sequence[RawRead]) -> int:
        """Batch submit through sc_submit_raw_batch: one ctypes call and one
        io_uring_enter for the whole sequence (the round-1 implementation
        looped one syscall per request — VERDICT.md weak #8).

        Contract (matches PythonEngine): all-or-nothing in the common case —
        a batch that cannot fit the queue depth raises EAGAIN with nothing
        submitted. If a concurrent submitter races us past the pre-check and
        the engine accepts only part of the batch, the raised EngineError
        carries ``.accepted`` = number of ops ALREADY IN FLIGHT: reap their
        completions and resubmit only ``requests[accepted:]`` — never the
        whole batch (tag reuse while the kernel still owns the first ops'
        buffers would corrupt memory)."""
        if not requests:
            return 0
        if self.in_flight() + len(requests) > self.config.queue_depth:
            raise EngineError(
                _errno.EAGAIN,
                f"queue depth exceeded ({self.in_flight()}+{len(requests)} > "
                f"{self.config.queue_depth})")
        ops = (_ScRawOp * len(requests))()
        for i, r in enumerate(requests):
            is_write = isinstance(r, RawWrite)
            if not r.dest.flags["C_CONTIGUOUS"] or \
                    (not is_write and not r.dest.flags["WRITEABLE"]):
                raise EngineError(_errno.EINVAL,
                                  "RawRead.dest must be writable C-contiguous")
            if r.length > 0xFFFFFFFF:
                raise EngineError(_errno.EINVAL,
                                  f"op length {r.length} exceeds uint32; "
                                  "split the op (see _split_chunks)")
            if r.dest.nbytes < r.length:
                raise EngineError(_errno.EINVAL,
                                  "op buffer smaller than length")
            addr = r.dest.__array_interface__["data"][0]
            ops[i] = _ScRawOp(r.file_index, r.length, r.offset, r.tag,
                              ctypes.c_void_p(addr), -1,
                              2 if is_write else 0)  # SC_OP_WRITE
        # Register keepalives BEFORE the C call: the kernel can complete an op
        # inside sc_submit_raw_batch, and a concurrent wait() must find the
        # entry to pop — insert-after-submit would leak the pinned dest.
        # (Same ordering for the per-op latency stamps: a completion landing
        # inside the submit call must find its t0.)
        self._note_submitted(requests)
        for r in requests:
            self._raw_keepalive[r.tag] = r.dest
        stop = ctypes.c_int32(0)
        rc = self._lib.sc_submit_raw_batch(self._h, ops, len(requests),
                                           ctypes.byref(stop))
        stamps = getattr(self, "_op_submit_t", None) or {}
        if rc < 0:
            for r in requests:
                self._raw_keepalive.pop(r.tag, None)
                stamps.pop(r.tag, None)
            raise EngineError(-rc, f"submit_raw: {os.strerror(-rc)}")
        if rc < len(requests):
            for r in requests[rc:]:
                self._raw_keepalive.pop(r.tag, None)
                stamps.pop(r.tag, None)
            if stop.value:
                # an op the engine can never accept (bad file index/addr):
                # retrying it is futile — surface its true errno
                err = EngineError(stop.value,
                                  f"submit_raw: op {rc} rejected: "
                                  f"{os.strerror(stop.value)}")
            else:
                err = EngineError(
                    _errno.EAGAIN,
                    f"submit_raw: queue full after {rc}/{len(requests)} ops "
                    "(reap completions, then resubmit requests[accepted:])")
            err.accepted = rc
            raise err
        return rc

    def wait(self, min_completions: int = 1, timeout_s: float | None = None) -> list[Completion]:
        timeout_ms = -1 if timeout_s is None else max(0, int(timeout_s * 1000))
        n = self._lib.sc_wait(self._h, self._comp_buf, len(self._comp_buf),
                              min_completions, timeout_ms)
        if n < 0:
            raise EngineError(-n, f"wait: {os.strerror(-n)}")
        out = [Completion(self._comp_buf[i].tag, self._comp_buf[i].res) for i in range(n)]
        if self._raw_keepalive:
            for c in out:
                self._raw_keepalive.pop(c.tag, None)
        if out:
            self._note_completed(out)
        return out

    def _deadline_groups(self, chunks: Sequence[tuple[int, int, int, int]]
                         ) -> list[list[tuple[int, int, int, int]]]:
        """Order-preserving sub-batches for deadline-bounded native
        gathers: big enough to amortize the C++ entry (>= one full
        queue-depth of blocks, floored at 64 MiB), small enough that a
        between-batch deadline check bounds lateness."""
        cap = max(64 << 20,
                  self.config.block_size * self.config.queue_depth)
        groups: list[list] = []
        cur: list = []
        size = 0
        for c in chunks:
            cur.append(c)
            size += c[3]
            if size >= cap:
                groups.append(cur)
                cur, size = [], 0
        if cur:
            groups.append(cur)
        return groups

    def read_vectored(self, chunks: Sequence[tuple[int, int, int, int]],
                      dest: np.ndarray, *, retries: int = 1) -> int:
        """Native override: the whole gather runs inside libstrom_core
        (sc_read_vectored) — batched SQE fills, one io_uring_enter per batch,
        retry + EOF topup in C++, GIL released for the entire transfer."""
        if not chunks:
            return 0
        deadline = self._request_deadline()
        if deadline is not None:
            # the native gather blocks inside C++ with no deadline hook,
            # so a deadline-carrying request runs it in native SUB-BATCHES
            # with a check between them (ISSUE 9): full C++ efficiency
            # per batch, lateness bounded at ~one batch — never a reroute
            # onto the slower generic pump (a generous never-hit deadline
            # must not cost the native path its throughput)
            if time.monotonic() >= deadline:
                self.op_scope.add("deadline_exceeded")
                raise DeadlineExceeded("gather not started")
            groups = self._deadline_groups(chunks)
            if len(groups) > 1:
                total = 0
                for g in groups:
                    if time.monotonic() >= deadline:
                        self.op_scope.add("deadline_exceeded")
                        raise DeadlineExceeded(
                            f"native gather stopped after {total} bytes")
                    total += self.read_vectored(g, dest, retries=retries)
                return total
        d8 = dest.view(np.uint8).reshape(-1)
        if not d8.flags["C_CONTIGUOUS"] or not d8.flags["WRITEABLE"]:
            raise EngineError(_errno.EINVAL, "dest must be writable C-contiguous")
        need = max(do + ln for (_, _, do, ln) in chunks)
        if d8.nbytes < need:
            raise EngineError(_errno.EINVAL, "dest smaller than gather plan")
        chunks = _split_chunks(chunks)
        segs = (_ScVecSeg * len(chunks))()
        for i, (fi, fo, do, ln) in enumerate(chunks):
            segs[i] = _ScVecSeg(fi, ln, fo, do)
        base = d8.__array_interface__["data"][0]
        dest_buf_index = self._dest_index(base, need)
        before = self._native_lat_snapshot()
        res = self._lib.sc_read_vectored(self._h, segs, len(chunks),
                                         ctypes.c_void_p(base),
                                         self.config.block_size, retries,
                                         dest_buf_index)
        after = self._native_lat_snapshot()
        retried = after[0] - before[0]
        if retried > 0:
            self.op_scope.add("chunk_retries", retried)
        # per-op latency for the native gather path (it never crosses the
        # Python submit/wait hooks): mirror the native latency histogram's
        # DELTA into the scoped engine_op_lat_us series — same log2 bucket
        # convention, so the scoped and engine-section histograms agree
        delta = [a - b for a, b in zip(after[1], before[1])]
        if any(delta):
            self.op_scope.histogram("engine_op_lat").add_buckets(
                delta, after[2] - before[2])
        self.op_scope.set_gauge("engine_inflight", self.in_flight())
        if res < 0:
            if -res == _errno.ENODATA:
                raise EngineError(_errno.ENODATA,
                                  "short read — file smaller than requested range?")
            raise EngineError(-res, f"read failed after {retries + 1} attempts: "
                                    f"{os.strerror(-res)}")
        return int(res)

    def _native_chunk_retries(self) -> int:
        s = _ScStats()
        self._lib.sc_get_stats(self._h, ctypes.byref(s))
        return int(s.chunk_retries)

    def _native_lat_snapshot(self) -> tuple[int, list[int], float]:
        """(chunk_retries, lat_hist buckets, lat_total_us) in one stats
        read: the before/after pair the native read_vectored path diffs to
        mirror per-op latency into the telemetry scope."""
        s = _ScStats()
        self._lib.sc_get_stats(self._h, ctypes.byref(s))
        return (int(s.chunk_retries),
                [int(s.lat_hist[i]) for i in range(_HIST_BUCKETS)],
                float(s.lat_total_us))

    def in_flight(self) -> int:
        return self._lib.sc_in_flight(self._h)

    def set_fault_every(self, n: int) -> None:
        self._fault_every = n
        self._lib.sc_set_fault_every(self._h, n)

    def set_enter_fail_once(self, err: int) -> None:
        """Test hook: the next kernel submission fails the whole batch with
        -err, exercising the submission-rollback path (the ops complete with
        synthetic failures instead of stranding sc_wait)."""
        self._lib.sc_set_enter_fail_once(self._h, err)

    def stats(self) -> dict:
        s = _ScStats()
        self._lib.sc_get_stats(self._h, ctypes.byref(s))
        total = s.lat_count
        out = {
            "engine": self.name,
            "ops_submitted": s.ops_submitted,
            "ops_completed": s.ops_completed,
            "ops_errored": s.ops_errored,
            "ops_faulted": s.ops_faulted,
            "bytes_read": s.bytes_read,
            "unaligned_fallback_reads": s.unaligned_fallback_reads,
            "eof_topup_reads": s.eof_topup_reads,
            "in_flight": s.in_flight,
            "chunk_retries": s.chunk_retries,
            "fixed_buffers": bool(s.fixed_buffers),
            "fixed_files": bool(s.fixed_files),
            "mlocked": bool(s.mlocked),
            "coop_taskrun": bool(s.coop_taskrun),
            "sqpoll": bool(s.sqpoll),
            "sqpoll_wakeup_errno": int(s.sqpoll_wakeup_errno),
            # cached/media are ADVISORY under memory pressure: residency is
            # snapshotted upfront per gather, so pages evicted before the
            # buffered read still count as cached_bytes (route chosen, not
            # where bytes were ultimately served — ADVICE.md r3 #5)
            "cached_bytes": int(s.cached_bytes),
            "media_bytes": int(s.media_bytes),
            "residency_probes": int(s.residency_probes),
            "ops_written": int(s.ops_written),
            "bytes_written": int(s.bytes_written),
            "sparse_table": bool(s.sparse_table),
            "ext_buffers": int(s.ext_buffers),
            "ops_fixed": int(s.ops_fixed),
            # registered-buffer coverage (ISSUE 16): what fraction of ops
            # rode READ_FIXED/WRITE_FIXED. The complement is named
            # *_unregistered_reads (reads dominate the op mix); both feed
            # /metrics and the compare_rounds engine column — the mechanism
            # half's before/after proof.
            "engine_fixed_buf_ratio":
                (s.ops_fixed / s.ops_submitted) if s.ops_submitted else 0.0,
            "engine_unregistered_reads":
                max(0, int(s.ops_submitted) - int(s.ops_fixed)),
            # submit-side io_uring_enter calls: under SQPOLL the poller
            # consumes published SQEs with no enter at all, so this per-GB
            # is the measured syscall A/B the sqpoll knob is gated on
            "enter_submit_calls": int(s.enter_submit_calls),
            "sqpoll_wakeups": int(s.sqpoll_wakeups),
            "read_latency_mean_us": (s.lat_total_us / total) if total else 0.0,
            # exact accumulated sum: the exposition's histogram _sum reads
            # this instead of reconstructing mean*count
            "read_latency_total_us": float(s.lat_total_us),
            "read_latency_count": total,
            # raw log2 buckets (bucket i ≈ [2^i, 2^(i+1)) us): feeds the
            # Prometheus histogram exposition (≙ the reference's /proc stats)
            "read_latency_hist": [int(s.lat_hist[i])
                                  for i in range(_HIST_BUCKETS)],
        }
        # percentiles from the log2 histogram — UPPER bucket edge, the same
        # convention as utils.stats._Histogram.percentile, so the two
        # engines' percentile gauges agree for identical distributions
        for q, name in ((0.5, "read_latency_p50_us"), (0.99, "read_latency_p99_us")):
            acc, val = 0, 0.0
            target = q * total
            for i in range(_HIST_BUCKETS):
                acc += s.lat_hist[i]
                if total and acc >= target:
                    val = float(2 ** (i + 1))
                    break
            out[name] = val
        return out

    def close(self) -> None:
        if self._closed:
            return
        # cancellation-on-close (ISSUE 5): drain every async token's
        # in-flight SQEs while the ring still exists — destroying a ring
        # with ops in flight would leave the kernel DMA-ing into pages whose
        # registration died with it
        self._cancel_live_tokens()
        # take the dest lock BEFORE flipping _closed and destroying the ring:
        # a slab finalizer mid-unregister would otherwise race sc_destroy and
        # call into a freed engine
        with self._dest_lock:
            if self._closed:
                return
            self._closed = True
            self._dest_regs.clear()  # registrations die with the ring
        # numpy views over the pool die with the engine mapping: drop our
        # reference first so accidental use raises instead of faulting.
        self._np_pool = None
        self._lib.sc_destroy(self._h)
        self._h = None

    def __del__(self) -> None:
        # GC-time close must never raise (interpreter teardown ordering is
        # arbitrary); an explicit close() reports its own failures
        with contextlib.suppress(Exception):
            if not self._closed and self._h:
                self.close()
