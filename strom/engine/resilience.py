"""Resilience primitives: retry policy, circuit breaker, hedge control.

ISSUE 9 tentpole. The paper's SSD→accelerator DMA path assumes the device
answers; production traffic does not get that luxury — flaky links,
transient EIO, latency spikes, short reads and wedged completions are the
steady state. This module holds the POLICY half of the failure story,
shared by the engine layer (per-piece retry with backoff + budget +
deadline, :mod:`strom.engine.base`) and the delivery layer (per-engine
circuit breaker + failover + hedged reads,
:mod:`strom.delivery.resilient`):

- :func:`classify_errno` — transient vs permanent. Transient errors
  (EIO, EAGAIN, ETIMEDOUT, ...) are retried within budget; permanent
  ones (EBADF, EINVAL, EFAULT, ...) fail immediately — retrying a bad
  file descriptor is pure latency with a guaranteed identical outcome.
- :class:`RetryPolicy` — exponential backoff with jitter, capped, under
  a per-gather retry BUDGET so a sick device produces a bounded number
  of resubmits per transfer (no retry storms), and deadline-aware: a
  retry whose backoff would land past the request deadline is not
  scheduled.
- :class:`CircuitBreaker` — per-engine error-rate trip over a rolling
  window, classic closed → open → half-open lifecycle. While open, the
  delivery layer reroutes reads to the fallback path; half-open lets a
  bounded probe stream through, and enough probe successes close it.
- :class:`HedgeController` — adaptive hedge threshold from a rolling
  latency reservoir: a read slice that has been quiet for longer than
  ``multiplier x rolling-p99`` (floored at ``min_s``) is re-submitted on
  the fallback path; first completion wins.

Everything here is clock-injectable for deterministic tests and writes
its counters through a PR-6 telemetry scope (labeled + aggregate).
"""

from __future__ import annotations

import contextlib
import errno as _errno
import random
import threading
import time
from collections import deque
from typing import Callable

from strom.utils.locks import make_lock

# Counters the resilience layer feeds (single-sourced, same contract as
# STALL_FIELDS / STREAM_FIELDS / SCHED_FIELDS): the ctx.stats()
# ["resilience"] section, the per-arm bench columns (cli._resil_delta),
# the compare_rounds "resilience" section and tools/lint_stats_names.py
# all read this tuple, so a restyled spelling cannot fork a column from
# its producer.
RESILIENCE_FIELDS = (
    "chunk_retries",
    "retry_backoff_waits",
    "retry_budget_exhausted",
    "deadline_exceeded",
    "engine_stall_timeouts",
    "breaker_state",
    "breaker_trips",
    "breaker_probes",
    "breaker_recoveries",
    "failover_reads",
    "failover_bytes",
    "hedges_fired",
    "hedges_won",
    "hedge_wasted_bytes",
    "faults_injected",
)

# Chaos bench arm columns (cli.bench_chaos → bench.py copy loop →
# compare_rounds "resilience" section; parity-tested like CACHE_BENCH_FIELDS)
CHAOS_BENCH_FIELDS = (
    "chaos_ok",
    "chaos_slowdown",
    "chaos_clean_images_per_s",
    "chaos_faulty_images_per_s",
    "chaos_faults_injected",
    "chaos_chunk_retries",
    "chaos_failover_reads",
    "chaos_breaker_trips",
    "chaos_hedges_fired",
)

# errnos worth a resubmit: the device/link may answer next time.
# ECONNREFUSED/ECONNRESET/EPIPE are the network-fault spellings the peer
# tier's chaos_net preset injects (ISSUE 15): peer fetches already degrade
# to the local engine, and a refused peer may be back next cooldown.
TRANSIENT_ERRNOS = frozenset({
    _errno.EIO, _errno.EAGAIN, _errno.EINTR, _errno.ETIMEDOUT,
    _errno.ENXIO, _errno.EBUSY, _errno.ENODATA,
    _errno.ECONNREFUSED, _errno.ECONNRESET, _errno.EPIPE,
})
# errnos where a retry is guaranteed to fail identically
PERMANENT_ERRNOS = frozenset({
    _errno.EBADF, _errno.EINVAL, _errno.EFAULT, _errno.ENOMEM,
    _errno.ENOSPC, _errno.ECANCELED, _errno.EPERM, _errno.EACCES,
})


def classify_errno(err: int) -> str:
    """'transient' or 'permanent' for a positive errno. Unknown errnos
    count as transient: optimism costs one bounded backoff; pessimism
    fails a gather that a resubmit would have saved."""
    e = abs(int(err))
    if e in PERMANENT_ERRNOS:
        return "permanent"
    return "transient"


class RetryPolicy:
    """Exponential backoff + jitter under a per-gather budget.

    One instance per engine (built from config, see
    :meth:`Engine.retry_policy <strom.engine.base.Engine>`); per-gather
    state (budget used) lives with the gather, not here — the policy is
    stateless apart from its jitter RNG.
    """

    __slots__ = ("backoff_s", "backoff_max_s", "jitter", "budget", "_rng")

    def __init__(self, *, backoff_s: float = 0.005,
                 backoff_max_s: float = 0.2, jitter: float = 0.25,
                 budget: int = 64, seed: int = 0xC0FFEE):
        self.backoff_s = max(float(backoff_s), 0.0)
        self.backoff_max_s = max(float(backoff_max_s), self.backoff_s)
        self.jitter = max(float(jitter), 0.0)
        self.budget = int(budget)
        self._rng = random.Random(seed)

    @classmethod
    def from_config(cls, config) -> "RetryPolicy":
        return cls(
            backoff_s=getattr(config, "io_retry_backoff_s", 0.005),
            backoff_max_s=getattr(config, "io_retry_backoff_max_s", 0.2),
            budget=getattr(config, "io_retry_budget", 64))

    def delay_s(self, attempts: int) -> float:
        """Backoff before retry number ``attempts + 1`` (attempts = how
        many tries already failed): base * 2^attempts, jittered up to
        ``+jitter`` fraction, capped. Jitter decorrelates a queue-depth's
        worth of simultaneous failures so the resubmits don't land as one
        thundering batch on a device that just choked on exactly that."""
        d = min(self.backoff_s * (2 ** max(attempts, 0)), self.backoff_max_s)
        if self.jitter:
            d *= 1.0 + self.jitter * self._rng.random()
        return d

    def should_retry(self, err: int, attempts: int, retries: int,
                     budget_used: int) -> bool:
        """Whether a failed piece (positive errno *err*, *attempts* tries
        done) earns a resubmit under the per-piece cap AND the per-gather
        budget. Deadline checks are the caller's (it owns the clock)."""
        if attempts >= retries:
            return False
        if budget_used >= self.budget:
            return False
        return classify_errno(err) == "transient"


class CircuitBreaker:
    """Error-rate circuit breaker over a rolling window.

    States (the ``breaker_state`` gauge): 0 = CLOSED (primary path),
    1 = HALF_OPEN (probing), 2 = OPEN (failover). Trips OPEN when the
    window holds >= *min_events* outcomes and the failure fraction is
    >= *error_rate*; after *cooldown_s* the next :meth:`allow` moves to
    HALF_OPEN and lets probes through — *half_open_successes* consecutive
    probe successes close it, any probe failure re-opens (cooldown
    restarts). ``on_trip`` (the flight-recorder dump hook) fires outside
    the lock on every CLOSED/HALF_OPEN → OPEN transition.
    """

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2

    def __init__(self, *, window_s: float = 10.0, min_events: int = 8,
                 error_rate: float = 0.5, cooldown_s: float = 5.0,
                 half_open_successes: int = 3, scope=None,
                 clock: Callable[[], float] = time.monotonic,
                 on_trip: "Callable[[str], None] | None" = None,
                 name: str = "engine"):
        self.window_s = float(window_s)
        self.min_events = int(min_events)
        self.error_rate = float(error_rate)
        self.cooldown_s = float(cooldown_s)
        self.half_open_successes = int(half_open_successes)
        self.name = name
        self._clock = clock
        self.on_trip = on_trip
        self._scope = scope
        self._lock = make_lock("resil.breaker")
        self._events: deque[tuple[float, bool]] = deque()
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probe_ok = 0
        self.trips = 0
        self.probes = 0
        self.recoveries = 0
        self._gauge(self.CLOSED)

    def _gauge(self, state: int) -> None:
        if self._scope is not None:
            # telemetry must never fail breaker state math
            with contextlib.suppress(Exception):
                self._scope.set_gauge("breaker_state", state)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _prune_locked(self, now: float) -> None:
        lo = now - self.window_s
        ev = self._events
        while ev and ev[0][0] < lo:
            ev.popleft()

    def allow(self) -> bool:
        """True = send this read down the primary path (CLOSED, or a
        HALF_OPEN probe); False = reroute to the fallback (OPEN)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = self._clock()
            if self._state == self.OPEN:
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._state = self.HALF_OPEN
                self._probe_ok = 0
                self._gauge(self.HALF_OPEN)
            # HALF_OPEN: probe with real traffic
            self.probes += 1
            if self._scope is not None:
                with contextlib.suppress(Exception):
                    self._scope.add("breaker_probes")
            return True

    def record_success(self) -> None:
        with self._lock:
            now = self._clock()
            self._events.append((now, True))
            self._prune_locked(now)
            if self._state == self.HALF_OPEN:
                self._probe_ok += 1
                if self._probe_ok >= self.half_open_successes:
                    self._state = self.CLOSED
                    self._events.clear()  # a fresh start, not stale failures
                    self.recoveries += 1
                    self._gauge(self.CLOSED)
                    if self._scope is not None:
                        with contextlib.suppress(Exception):
                            self._scope.add("breaker_recoveries")

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            now = self._clock()
            self._events.append((now, False))
            self._prune_locked(now)
            if self._state == self.HALF_OPEN:
                # one failed probe re-opens immediately: the engine already
                # proved it isn't back
                self._state = self.OPEN
                self._opened_at = now
                self.trips += 1
                tripped = True
            elif self._state == self.CLOSED:
                fails = sum(1 for _, ok in self._events if not ok)
                if len(self._events) >= self.min_events and \
                        fails / len(self._events) >= self.error_rate:
                    self._state = self.OPEN
                    self._opened_at = now
                    self.trips += 1
                    tripped = True
            if tripped:
                self._gauge(self.OPEN)
        if tripped:
            if self._scope is not None:
                with contextlib.suppress(Exception):
                    self._scope.add("breaker_trips")
            if self.on_trip is not None:
                # the flight-dump hook is advisory: a failed dump must not
                # turn a breaker trip into a read-path crash
                with contextlib.suppress(Exception):
                    self.on_trip(f"circuit breaker '{self.name}' tripped "
                                 f"(trip #{self.trips})")

    def info(self) -> dict:
        with self._lock:
            fails = sum(1 for _, ok in self._events if not ok)
            return {"state": ("closed", "half_open", "open")[self._state],
                    "breaker_state": self._state,
                    "window_events": len(self._events),
                    "window_failures": fails,
                    "breaker_trips": self.trips,
                    "breaker_probes": self.probes,
                    "breaker_recoveries": self.recoveries}


class HedgeController:
    """Adaptive hedge threshold from a rolling completion-cadence window.

    ``observe`` feeds INTER-COMPLETION gaps (seconds) — under pipelining
    this is completion spacing, not per-op service time, so on a deep
    queue the threshold reads "how long a quiet spell is abnormal for
    this gather", floored at ``min_s`` (the blast radius of a too-eager
    threshold is bounded by the delivery layer: one hedge per chunk,
    in-flight chunks only). ``threshold_s``
    returns ``max(min_s, multiplier * rolling_p99)``. The p99 is
    recomputed lazily every 16th observation (same amortization as the
    exemplar store's tail window) — hedging is a per-stall decision, not
    a per-completion sort. With fewer than 8 observations the floor
    stands alone: hedging a cold pipeline on no evidence would double
    every first read.
    """

    def __init__(self, *, min_s: float = 0.05, multiplier: float = 3.0,
                 window: int = 128):
        self.min_s = float(min_s)
        self.multiplier = float(multiplier)
        self._window = deque(maxlen=max(int(window), 8))
        self._lock = make_lock("resil.hedge")
        self._n = 0
        self._p99 = 0.0

    def observe(self, lat_s: float) -> None:
        with self._lock:
            self._window.append(float(lat_s))
            self._n += 1
            if self._n % 16 == 0:
                self._recompute_locked()

    def _recompute_locked(self) -> None:
        if len(self._window) < 8:
            self._p99 = 0.0
            return
        s = sorted(self._window)
        self._p99 = s[min(int(len(s) * 0.99), len(s) - 1)]

    def threshold_s(self) -> float:
        with self._lock:
            if self._p99 == 0.0 and len(self._window) >= 8:
                self._recompute_locked()
            return max(self.min_s, self.multiplier * self._p99)
