"""ViT-B/16 — consumer of the WebDataset pipeline (BASELINE config #3:
"WebDataset .tar shards → ViT-B/16 training loader (4×NVMe RAID0)",
BASELINE.json:9).

Pure-JAX functional implementation, TPU-first:
- patchify as one reshape + matmul (a [B,N,P²·3] @ [P²·3,D] MXU matmul, not a
  conv — same math, better fit for the systolic array at P=16);
- encoder layers stacked over depth and iterated with `lax.scan` (one compiled
  block body, like the Llama flagship);
- bfloat16 matmuls, float32 layer-norm/softmax accumulation.

The reference has no models (SURVEY.md §2.3) — consumers exist to close the
loop the way PG-Strom closes the reference's (SURVEY.md §3.5).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from strom.models.llama import attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch: int = 16
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_mlp: int = 3072
    num_classes: int = 1000
    dtype: str = "bfloat16"
    norm_eps: float = 1e-6

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def vit_b16(cls) -> "ViTConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "ViTConfig":
        """~300k params; unit tests and compile checks (input 32×32)."""
        return cls(image_size=32, patch=8, d_model=64, n_layers=2, n_heads=4,
                   d_mlp=128, num_classes=10)


def init_params(key: jax.Array, cfg: ViTConfig) -> dict:
    d, L, f = cfg.d_model, cfg.n_layers, cfg.d_mlp
    pdim = cfg.patch * cfg.patch * 3
    k = iter(jax.random.split(key, 12))
    dt = cfg.jdtype

    def dense(kk, *shape, scale_dim=None):
        scale = 1.0 / jnp.sqrt(scale_dim if scale_dim is not None else shape[-2])
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * scale).astype(dt)

    def ln(*shape):
        return {"scale": jnp.ones(shape, jnp.float32),
                "bias": jnp.zeros(shape, jnp.float32)}

    return {
        "patch_embed": dense(next(k), pdim, d),
        "patch_bias": jnp.zeros((d,), dt),
        "cls_token": jnp.zeros((1, 1, d), dt),
        "pos_embed": (jax.random.normal(next(k), (1, cfg.n_patches + 1, d),
                                        dtype=jnp.float32) * 0.02).astype(dt),
        "layers": {
            "ln1": ln(L, d),
            "wqkv": dense(next(k), L, d, 3 * d),
            "wo": dense(next(k), L, d, d),
            "ln2": ln(L, d),
            "w1": dense(next(k), L, d, f),
            "b1": jnp.zeros((L, f), dt),
            "w2": dense(next(k), L, f, d),
            "b2": jnp.zeros((L, d), dt),
        },
        "final_ln": ln(d),
        "head": {"w": dense(next(k), d, cfg.num_classes).astype(jnp.float32),
                 "b": jnp.zeros((cfg.num_classes,), jnp.float32)},
    }


def layer_norm(x: jax.Array, p: dict, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]).astype(x.dtype)


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[B,H,W,3] → [B, N, patch*patch*3] row-major patches."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, gh * gw, patch * patch * C)


def _block(x: jax.Array, lp: dict, cfg: ViTConfig) -> jax.Array:
    B, S, D = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    h = layer_norm(x, lp["ln1"], cfg.norm_eps)
    qkv = (h @ lp["wqkv"]).reshape(B, S, 3, nh, hd)
    q, kk, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = attention(q, kk, v, causal=False)
    x = x + attn.reshape(B, S, D) @ lp["wo"]
    h = layer_norm(x, lp["ln2"], cfg.norm_eps)
    h = jax.nn.gelu(h @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]
    return x + h


def forward(params: dict, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """images [B,H,W,3] (normalized float) → logits [B, classes] float32."""
    B = images.shape[0]
    x = patchify(images.astype(cfg.jdtype), cfg.patch)
    x = x @ params["patch_embed"] + params["patch_bias"]
    cls = jnp.broadcast_to(params["cls_token"], (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"]

    def body(carry, lp):
        return _block(carry, lp, cfg), None

    x, _ = lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln"], cfg.norm_eps)
    cls_out = x[:, 0].astype(jnp.float32)
    return cls_out @ params["head"]["w"] + params["head"]["b"]


def loss_fn(params: dict, images: jax.Array, labels: jax.Array,
            cfg: ViTConfig) -> jax.Array:
    from strom.models.resnet import softmax_xent

    return softmax_xent(forward(params, images, cfg), labels)


@partial(jax.jit, static_argnames=("cfg",))
def jit_forward(params: dict, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    return forward(params, images, cfg)
