"""Mixture-of-Experts Llama variant — the expert-parallel (ep) consumer.

TPU-first routing: Switch-style top-1 with *capacity-based dense dispatch* —
routing becomes two einsums against a [tokens, experts, capacity] dispatch
tensor (the Mesh-TensorFlow/Switch-Transformer formulation), so the whole MoE
layer is static-shaped MXU work and XLA inserts the token all-to-alls itself
when tokens are dp-sharded and experts are ep-sharded (scaling-book recipe:
annotate, let the compiler place collectives).

The reference has no models (SURVEY.md §2.3); this consumer exists to prove
the data path composes with every parallelism axis the mesh offers
(dp/tp/sp/ep).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax

from strom.models.llama import LlamaConfig, attention, rmsnorm, rope


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    base: LlamaConfig = dataclasses.field(default_factory=LlamaConfig.tiny)
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    router_z_weight: float = 1e-3

    @classmethod
    def tiny(cls, n_experts: int = 4) -> "MoEConfig":
        return cls(base=LlamaConfig.tiny(), n_experts=n_experts)

    def capacity(self, n_tokens: int) -> int:
        return max(1, math.ceil(n_tokens / self.n_experts * self.capacity_factor))


def init_params(key: jax.Array, cfg: MoEConfig) -> dict:
    """Llama attention params + per-layer router and stacked expert FFNs
    (leading dims: [n_layers, n_experts, ...])."""
    b = cfg.base
    d, f, L, E = b.d_model, b.d_ff, b.n_layers, cfg.n_experts
    nh, nkv, hd = b.n_heads, b.n_kv_heads, b.head_dim
    dt = b.jdtype
    k = iter(jax.random.split(key, 12))

    def dense(kk, *shape, scale_dim=None):
        scale = 1.0 / math.sqrt(scale_dim if scale_dim is not None else shape[-2])
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * scale).astype(dt)

    return {
        "embed": dense(next(k), b.vocab, d, scale_dim=d),
        "layers": {
            "attn_norm": jnp.ones((L, d), jnp.float32),
            "wq": dense(next(k), L, d, nh * hd),
            "wk": dense(next(k), L, d, nkv * hd),
            "wv": dense(next(k), L, d, nkv * hd),
            "wo": dense(next(k), L, nh * hd, d),
            "mlp_norm": jnp.ones((L, d), jnp.float32),
            # router in float32: routing decisions are precision-sensitive
            "router": (jax.random.normal(next(k), (L, d, E), dtype=jnp.float32)
                       * (1.0 / math.sqrt(d))),
            "w_gate": dense(next(k), L, E, d, f),
            "w_up": dense(next(k), L, E, d, f),
            "w_down": dense(next(k), L, E, f, d),
        },
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": dense(next(k), d, b.vocab),
    }


def switch_route(h: jax.Array, router: jax.Array, capacity: int
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with capacity. h [N, d] → (dispatch [N, E, C] one-hot,
    combine [N, E, C] probability-weighted, aux losses (lb, z))."""
    logits = h.astype(jnp.float32) @ router            # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                # [N]
    N, E = probs.shape
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)   # [N, E]

    # position of each token within its expert's queue (cumsum over tokens)
    pos = jnp.cumsum(onehot, axis=0) - onehot          # [N, E], 0-based
    keep = (pos < capacity) * onehot                   # dropped past capacity
    pos_clipped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_clipped, capacity, dtype=jnp.float32)
    dispatch = keep[..., None] * pos_onehot            # [N, E, C]
    gate = jnp.sum(probs * keep, axis=-1, keepdims=True)   # kept tokens' prob
    combine = dispatch * gate[..., None]

    # Switch load-balance loss: E * Σ_e fraction_tokens(e) * mean_prob(e)
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    lb_loss = E * jnp.sum(frac * mean_prob)
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return dispatch, combine, jnp.stack([lb_loss, z_loss])


def moe_ffn(h: jax.Array, lp: dict, cfg: MoEConfig) -> tuple[jax.Array, jax.Array]:
    """h [B, S, d] → (out [B, S, d], aux [2]). Dense-dispatch SwiGLU experts."""
    B, S, D = h.shape
    N = B * S
    C = cfg.capacity(N)
    hf = h.reshape(N, D)
    dispatch, combine, aux = switch_route(hf, lp["router"], C)
    dd = dispatch.astype(h.dtype)
    # gather tokens per expert: [E, C, d] — XLA turns this into the a2a when
    # tokens and experts live on different mesh axes
    expert_in = jnp.einsum("nec,nd->ecd", dd, hf.astype(h.dtype))
    gated = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, lp["w_gate"]))
    up = jnp.einsum("ecd,edf->ecf", expert_in, lp["w_up"])
    expert_out = jnp.einsum("ecf,efd->ecd", gated * up, lp["w_down"])
    out = jnp.einsum("nec,ecd->nd", combine.astype(h.dtype), expert_out)
    return out.reshape(B, S, D), aux


def block(x: jax.Array, lp: dict, cfg: MoEConfig, positions: jax.Array,
          attn_fn=None) -> tuple[jax.Array, jax.Array]:
    b = cfg.base
    B, S, D = x.shape
    nh, nkv, hd = b.n_heads, b.n_kv_heads, b.head_dim
    h = rmsnorm(x, lp["attn_norm"], b.norm_eps)
    q = (h @ lp["wq"]).reshape(B, S, nh, hd)
    k = (h @ lp["wk"]).reshape(B, S, nkv, hd)
    v = (h @ lp["wv"]).reshape(B, S, nkv, hd)
    q = rope(q, positions, b.rope_theta)
    k = rope(k, positions, b.rope_theta)
    attn = (attn_fn or attention)(q, k, v)
    x = x + attn.reshape(B, S, nh * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], b.norm_eps)
    ffn, aux = moe_ffn(h, lp, cfg)
    return x + ffn, aux


def forward(params: dict, tokens: jax.Array, cfg: MoEConfig,
            attn_fn=None, remat: bool = False) -> tuple[jax.Array, jax.Array]:
    """tokens [B, S] → (logits [B, S, vocab] f32, aux losses [2] summed).

    remat=True: per-layer jax.checkpoint, same trade as the dense model
    (strom.models.llama.forward) — mandatory for real batch×seq on one chip."""
    b = cfg.base
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = params["embed"][tokens].astype(b.jdtype)

    blk = block if not remat else jax.checkpoint(block, static_argnums=(2, 4))

    def body(carry, lp):
        y, aux = blk(carry, lp, cfg, positions, attn_fn)
        return y, aux

    x, auxes = lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], b.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, jnp.sum(auxes, axis=0)


def next_token_loss(params: dict, tokens: jax.Array, cfg: MoEConfig,
                    attn_fn=None, remat: bool = False) -> jax.Array:
    """Full-length roll/mask LM loss (same shape contract as the dense model)
    + weighted router aux losses."""
    B, L = tokens.shape
    logits, aux = forward(params, tokens, cfg, attn_fn, remat=remat)
    targets = jnp.roll(tokens, -1, axis=1)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    mask = (jnp.arange(L) < L - 1).astype(jnp.float32)
    lm = jnp.sum((logz - gold) * mask) / (B * (L - 1))
    return lm + cfg.aux_loss_weight * aux[0] + cfg.router_z_weight * aux[1]
