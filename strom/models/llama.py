"""Llama-style decoder-only transformer — the flagship model for the packed
-token pretrain pipeline (BASELINE config #4: "Llama-3-8B packed-token .bin
shards → JAX pretrain dataloader (v5p-8)", BASELINE.json:10).

Pure-JAX functional implementation, TPU-first:
- parameters stacked over layers and iterated with `lax.scan` (one compiled
  block body, fast XLA compiles at depth);
- bfloat16 activations/matmuls on the MXU, float32 softmax/norm accumulation;
- GQA (grouped-query attention) + RoPE + SwiGLU, matching the Llama-3 family;
- tensor-parallel sharding rules for every weight in
  :mod:`strom.parallel.sharding` (Megatron-style column/row split pairs).

The reference has no models (it is an I/O kernel module — SURVEY.md §2.3);
this model exists as the consumer of the data path, mirroring how PG-Strom
consumes the reference's DMA engine (SURVEY.md §3.5).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14_336
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """~2M params; unit tests and compile checks."""
        return cls(vocab=512, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
                   d_ff=256, rope_theta=10_000.0)

    @classmethod
    def small(cls) -> "LlamaConfig":
        """~100M params; single-host perf experiments."""
        return cls(vocab=32_000, d_model=768, n_layers=12, n_heads=12,
                   n_kv_heads=4, d_ff=2048)

    def param_count(self) -> int:
        d, f, v, l = self.d_model, self.d_ff, self.vocab, self.n_layers
        hd = self.head_dim
        attn = d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd + self.n_heads * hd * d
        mlp = 3 * d * f
        return v * d + l * (attn + mlp + 2 * d) + d + d * v


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Stacked-over-layers parameter pytree (leading dim = n_layers)."""
    d, hd = cfg.d_model, cfg.head_dim
    nh, nkv, f, L = cfg.n_heads, cfg.n_kv_heads, cfg.d_ff, cfg.n_layers
    k = iter(jax.random.split(key, 9))
    dt = cfg.jdtype

    def norm_init(*shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def dense_init(kk, *shape, scale_dim=None):
        scale = 1.0 / math.sqrt(scale_dim if scale_dim is not None else shape[-2])
        return (jax.random.normal(kk, shape, dtype=jnp.float32) * scale).astype(dt)

    return {
        "embed": dense_init(next(k), cfg.vocab, d, scale_dim=d),
        "layers": {
            "attn_norm": norm_init(L, d),
            "wq": dense_init(next(k), L, d, nh * hd),
            "wk": dense_init(next(k), L, d, nkv * hd),
            "wv": dense_init(next(k), L, d, nkv * hd),
            "wo": dense_init(next(k), L, nh * hd, d),
            "mlp_norm": norm_init(L, d),
            "w_gate": dense_init(next(k), L, d, f),
            "w_up": dense_init(next(k), L, d, f),
            "w_down": dense_init(next(k), L, f, d),
        },
        "final_norm": norm_init(d),
        "lm_head": dense_init(next(k), d, cfg.vocab),
    }


def rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * w).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [B, S, H, Dh]; positions: [B, S] (absolute)."""
    hd = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, hd // 2, dtype=jnp.float32) / (hd // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,hd/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True, q_offset: jax.Array | int = 0) -> jax.Array:
    """GQA core. q: [B,Sq,H,Dh]; k,v: [B,Sk,KV,Dh]. float32 softmax.

    q_offset: absolute position of q[0] minus that of k[0] — nonzero in ring
    attention where the query block sits mid-sequence."""
    B, Sq, H, Dh = q.shape
    KV = k.shape[2]
    group = H // KV
    qg = q.reshape(B, Sq, KV, group, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32)
    scores = scores / math.sqrt(Dh)
    if causal:
        qpos = jnp.arange(Sq) + q_offset
        kpos = jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    # guard fully-masked rows (produce 0 instead of nan)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
    return out.reshape(B, Sq, H, Dh)


def block(x: jax.Array, lp: dict, cfg: LlamaConfig, positions: jax.Array,
          attn_fn=None) -> jax.Array:
    B, S, D = x.shape
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, S, nh, hd)
    k = (h @ lp["wk"]).reshape(B, S, nkv, hd)
    v = (h @ lp["wv"]).reshape(B, S, nkv, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    # attn_fn hook: ring attention (strom.parallel.ring) substitutes here for
    # sequence-parallel long-context runs
    attn = (attn_fn or attention)(q, k, v)
    x = x + attn.reshape(B, S, nh * hd) @ lp["wo"]

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
    return x + gated @ lp["w_down"]


def forward(params: dict, tokens: jax.Array, cfg: LlamaConfig,
            positions: jax.Array | None = None, attn_fn=None,
            remat: bool = False) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, vocab] float32.

    remat=True wraps each layer in `jax.checkpoint`: the backward recomputes
    block activations instead of the scan stacking every intermediate over
    layers — without it a 12-layer step at B16×S2048 wants ~22G of HLO temps
    and OOMs a 16G chip. FLOPs-for-HBM is the standard TPU trade (the brief's
    "use jax.checkpoint / rematerialisation")."""
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = params["embed"][tokens].astype(cfg.jdtype)

    blk = block if not remat else jax.checkpoint(block, static_argnums=(2, 4))

    def body(carry, lp):
        return blk(carry, lp, cfg, positions, attn_fn), None

    x, _ = lax.scan(body, x, params["layers"])
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def next_token_loss(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                    attn_fn=None, remat: bool = False) -> jax.Array:
    """Mean cross-entropy of predicting tokens[:, 1:] from tokens[:, :-1].

    Computed as a full-length forward + roll/mask rather than slicing to
    S-1: identical values under causality, but every array keeps ONE
    sequence length — which is what lets sequence-parallel sharding divide
    the batch evenly (the loader's seq_len+1 record length must be divisible
    by the sp axis size)."""
    B, L = tokens.shape
    logits = forward(params, tokens, cfg, attn_fn=attn_fn, remat=remat)
    targets = jnp.roll(tokens, -1, axis=1)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    mask = (jnp.arange(L) < L - 1).astype(jnp.float32)  # last column: no target
    return jnp.sum((logz - gold) * mask) / (B * (L - 1))


@partial(jax.jit, static_argnames=("cfg",))
def jit_forward(params: dict, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    return forward(params, tokens, cfg)
