"""ResNet-50 — consumer of the ImageNet raw-JPEG pipeline (BASELINE config
#2: "ImageNet raw-JPEG shards → ResNet-50 JAX input pipeline", BASELINE.json:8).

Pure-JAX functional implementation, TPU-first:
- NHWC layout + HWIO kernels (the TPU-native conv layout XLA tiles onto the
  MXU without transposes);
- bfloat16 activations/convs, float32 batch-norm statistics;
- functional batch-norm: forward returns updated running stats, so the whole
  train step stays a pure jittable function.

The reference has no models (SURVEY.md §2.3) — this is the data path's
consumer, as PG-Strom is the reference's (SURVEY.md §3.5).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stages: tuple[int, ...] = (3, 4, 6, 3)   # bottleneck blocks per stage (50-layer)
    width: int = 64                          # stem channels
    num_classes: int = 1000
    dtype: str = "bfloat16"
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @classmethod
    def resnet50(cls) -> "ResNetConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "ResNetConfig":
        """~100k params; unit tests and compile checks (input 32×32)."""
        return cls(stages=(1, 1), width=8, num_classes=10)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    w = jax.random.normal(key, (kh, kw, cin, cout), dtype=jnp.float32)
    return (w * jnp.sqrt(2.0 / fan_in)).astype(dtype)


def _bn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _bn_state_init(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init_params(key: jax.Array, cfg: ResNetConfig) -> tuple[dict, dict]:
    """Returns (params, bn_state): learnable weights and running statistics."""
    dt = cfg.jdtype
    keys = iter(jax.random.split(key, 4 + sum(cfg.stages) * 4))
    params: dict = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width, dt),
                             "bn": _bn_init(cfg.width)}}
    state: dict = {"stem": _bn_state_init(cfg.width)}
    cin = cfg.width
    for si, n_blocks in enumerate(cfg.stages):
        mid = cfg.width * (2 ** si)
        cout = mid * 4
        blocks, bstate = [], []
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            b = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid, dt),
                "bn1": _bn_init(mid),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid, dt),
                "bn2": _bn_init(mid),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout, dt),
                "bn3": _bn_init(cout),
            }
            s = {"bn1": _bn_state_init(mid), "bn2": _bn_state_init(mid),
                 "bn3": _bn_state_init(cout)}
            if cin != cout or stride != 1:
                b["proj"] = _conv_init(next(keys), 1, 1, cin, cout, dt)
                b["proj_bn"] = _bn_init(cout)
                s["proj_bn"] = _bn_state_init(cout)
            blocks.append(b)
            bstate.append(s)
            cin = cout
        params[f"stage{si}"] = blocks
        state[f"stage{si}"] = bstate
    head_key = next(keys)
    params["head"] = {
        "w": (jax.random.normal(head_key, (cin, cfg.num_classes), jnp.float32)
              / jnp.sqrt(cin)).astype(jnp.float32),
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, state


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _batch_norm(x, p, s, cfg: ResNetConfig, train: bool):
    """Returns (normalized x, updated state). Stats in float32."""
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new_s = {"mean": m * s["mean"] + (1 - m) * mean,
                 "var": m * s["var"] + (1 - m) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var + cfg.bn_eps) * p["scale"]
    out = (x.astype(jnp.float32) - mean) * inv + p["bias"]
    return out.astype(x.dtype), new_s


def _bottleneck(x, b, s, cfg: ResNetConfig, stride: int, train: bool):
    new_s = {}
    h, new_s["bn1"] = _batch_norm(_conv(x, b["conv1"]), b["bn1"], s["bn1"], cfg, train)
    h = jax.nn.relu(h)
    h, new_s["bn2"] = _batch_norm(_conv(h, b["conv2"], stride), b["bn2"], s["bn2"], cfg, train)
    h = jax.nn.relu(h)
    h, new_s["bn3"] = _batch_norm(_conv(h, b["conv3"]), b["bn3"], s["bn3"], cfg, train)
    if "proj" in b:
        x, new_s["proj_bn"] = _batch_norm(_conv(x, b["proj"], stride),
                                          b["proj_bn"], s["proj_bn"], cfg, train)
    return jax.nn.relu(h + x), new_s


def forward(params: dict, state: dict, images: jax.Array, cfg: ResNetConfig,
            *, train: bool = True) -> tuple[jax.Array, dict]:
    """images [B,H,W,3] (any float dtype, already normalized) →
    (logits [B,classes] float32, new bn state)."""
    x = images.astype(cfg.jdtype)
    new_state: dict = {}
    x = _conv(x, params["stem"]["conv"], stride=2)
    x, new_state["stem"] = _batch_norm(x, params["stem"]["bn"], state["stem"], cfg, train)
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for si in range(len(cfg.stages)):
        blocks, bstate, outs = params[f"stage{si}"], state[f"stage{si}"], []
        for bi, (b, s) in enumerate(zip(blocks, bstate)):
            stride = 2 if (si > 0 and bi == 0) else 1
            x, ns = _bottleneck(x, b, s, cfg, stride, train)
            outs.append(ns)
        new_state[f"stage{si}"] = outs
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state


def softmax_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean cross-entropy; labels int32 [B]."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - gold)


def loss_fn(params: dict, state: dict, images: jax.Array, labels: jax.Array,
            cfg: ResNetConfig) -> tuple[jax.Array, dict]:
    logits, new_state = forward(params, state, images, cfg, train=True)
    return softmax_xent(logits, labels), new_state


IMAGENET_MEAN = jnp.array([0.485, 0.456, 0.406], jnp.float32)
IMAGENET_STD = jnp.array([0.229, 0.224, 0.225], jnp.float32)


def normalize_images(u8: jax.Array) -> jax.Array:
    """uint8 [.. ,3] → normalized float32 (on-device, fused into the step)."""
    return (u8.astype(jnp.float32) / 255.0 - IMAGENET_MEAN) / IMAGENET_STD


@partial(jax.jit, static_argnames=("cfg", "train"))
def jit_forward(params: dict, state: dict, images: jax.Array,
                cfg: ResNetConfig, train: bool = False) -> Any:
    return forward(params, state, images, cfg, train=train)
