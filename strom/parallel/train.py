"""Sharded training step for the flagship model (pjit over a dp×tp mesh).

This is the consumer the data path feeds: strom loaders deliver token batches
already sharded over ("dp", ...) and the step runs under jit with explicit
parameter shardings — XLA places the ICI collectives (psum of dp gradients,
tp all-reduces) itself.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from strom.models.llama import LlamaConfig, init_params, next_token_loss
from strom.parallel.sharding import param_shardings


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                   warmup: int = 100) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, 10_000, lr * 0.1)
    return optax.chain(optax.clip_by_global_norm(1.0),
                       optax.adamw(sched, weight_decay=weight_decay))


def _init_state(key: jax.Array, init_fn, shardings_fn, mesh: Mesh,
                optimizer: optax.GradientTransformation) -> TrainState:
    """Jit the initializer with out_shardings so big models never
    materialise unsharded on one device."""
    shapes = jax.eval_shape(init_fn, key)
    shardings = shardings_fn(shapes, mesh)
    params = jax.jit(init_fn, out_shardings=shardings)(key)
    opt_state = jax.jit(optimizer.init)(params)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), dtype=jnp.int32))


def init_train_state(key: jax.Array, cfg: LlamaConfig, mesh: Mesh,
                     optimizer: optax.GradientTransformation) -> TrainState:
    return _init_state(key, partial(init_params, cfg=cfg), param_shardings,
                       mesh, optimizer)


def make_train_step(cfg: LlamaConfig, mesh: Mesh,
                    optimizer: optax.GradientTransformation, *,
                    attn: str = "dense", sp: bool = False, donate: bool = True):
    """Compile a (state, tokens) -> (state, metrics) step.

    tokens arrive sharded P("dp"[, "sp"]) — exactly the sharding
    strom.pipelines loaders deliver — so no resharding happens on entry.

    attn="flash": the Pallas flash-attention kernel (blockwise forward AND
    backward, O(S) memory — strom.ops.flash_attention) replaces the dense op
    in every layer. This is the default TPU training path for long sequences;
    "dense" remains for short-sequence parity and debugging.

    sp=True: activations stay sequence-sharded and attention runs the ring
    algorithm (kv blocks rotate over ICI neighbor hops) instead of letting
    XLA all-gather the whole sequence — peak memory O(S/n_sp) per device.
    """
    if attn not in ("dense", "flash", "zigzag"):
        raise ValueError(
            f"attn must be 'dense', 'flash' or 'zigzag', got {attn!r}")
    if attn == "zigzag" and not sp:
        raise ValueError("attn='zigzag' is the load-balanced causal RING; "
                         "it needs sp=True")
    batch_sharding = NamedSharding(mesh, P("dp", "sp") if sp else P("dp", None))
    attn_fn = None
    if sp:
        from strom.parallel.ring import make_ring_attention

        # attn="flash": the Pallas kernels run INSIDE the ring — each ring
        # step is a real flash block (fwd + blockwise bwd), merged by
        # logsumexp. The flagship long-context combination: O(S/n_sp)
        # activations AND no [S_loc, S_loc] materialization per step.
        attn_fn = make_ring_attention(mesh, axis="sp", impl=attn)
    elif attn == "flash":
        from strom.ops.flash_attention import make_flash_attention

        attn_fn = make_flash_attention()

    def loss_fn(params, tokens):
        return next_token_loss(params, tokens, cfg, attn_fn=attn_fn, remat=True)

    def step(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        return TrainState(new_params, new_opt, state.step + 1), metrics

    donate_argnums = (0,) if donate else ()
    return jax.jit(step, in_shardings=(None, batch_sharding),
                   donate_argnums=donate_argnums)


def init_moe_train_state(key: jax.Array, cfg, mesh: Mesh,
                         optimizer: optax.GradientTransformation) -> TrainState:
    """MoE variant: expert stacks sharded over the mesh's ep axis."""
    from strom.models import moe
    from strom.parallel.sharding import moe_param_shardings

    return _init_state(key, partial(moe.init_params, cfg=cfg),
                       moe_param_shardings, mesh, optimizer)


def make_moe_train_step(cfg, mesh: Mesh,
                        optimizer: optax.GradientTransformation, *,
                        attn: str = "dense", sp: bool = False,
                        donate: bool = True):
    """(state, tokens) -> (state, metrics) for the MoE model: tokens arrive
    P("dp"[, "sp"]); expert weights stay ep-sharded and XLA places the token
    all-to-alls the dispatch einsums imply."""
    from strom.models import moe

    if attn not in ("dense", "flash", "zigzag"):
        raise ValueError(
            f"attn must be 'dense', 'flash' or 'zigzag', got {attn!r}")
    if attn == "zigzag" and not sp:
        raise ValueError("attn='zigzag' is the load-balanced causal RING; "
                         "it needs sp=True")
    batch_sharding = NamedSharding(mesh, P("dp", "sp") if sp else P("dp", None))
    attn_fn = None
    if sp:
        from strom.parallel.ring import make_ring_attention

        # attn="flash": the Pallas kernels run INSIDE the ring — each ring
        # step is a real flash block (fwd + blockwise bwd), merged by
        # logsumexp. The flagship long-context combination: O(S/n_sp)
        # activations AND no [S_loc, S_loc] materialization per step.
        attn_fn = make_ring_attention(mesh, axis="sp", impl=attn)
    elif attn == "flash":
        from strom.ops.flash_attention import make_flash_attention

        attn_fn = make_flash_attention()

    def loss_fn(params, tokens):
        return moe.next_token_loss(params, tokens, cfg, attn_fn=attn_fn,
                                   remat=True)

    def step(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        return TrainState(new_params, new_opt, state.step + 1), metrics

    donate_argnums = (0,) if donate else ()
    return jax.jit(step, in_shardings=(None, batch_sharding),
                   donate_argnums=donate_argnums)


jax.tree_util.register_dataclass(TrainState,
                                 data_fields=["params", "opt_state", "step"],
                                 meta_fields=[])
