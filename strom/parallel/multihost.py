"""Multi-host coordination: epoch barriers, straggler accounting, balanced
work assignment (SURVEY.md §2.3 "Multi-host coordination (DCN)": "shard→host
assignment; barrier at epoch boundaries; straggler accounting"; reference
cite UNVERIFIED — empty mount, SURVEY.md §0. The reference is single-host;
these duties exist because the TPU rebuild fans out across a pod).

All cross-process communication rides jax's distributed runtime
(`multihost_utils` over DCN) — no side channel, per the design stance that
jax's runtime IS the comm backend (SURVEY.md §5 "Distributed comm backend").
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Sequence


def process_count() -> int:
    import jax

    return jax.process_count()


def epoch_barrier(name: str) -> None:
    """Block until every process reaches this point (≙ the epoch-boundary
    barrier of SURVEY.md §2.3). No-op in single-process runs; the *name*
    disambiguates concurrent barriers (use e.g. f"epoch-{n}")."""
    import jax

    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def assign_balanced(sizes: Sequence[int], n_bins: int) -> list[list[int]]:
    """Greedy LPT (longest-processing-time-first) assignment of work units to
    bins: sort by size descending, place each in the currently-lightest bin.

    Deterministic in (sizes, n_bins) — every process computes the same
    assignment with no coordination, same as the samplers. Replaces
    round-robin for the Parquet fan-out, where skewed row-group sizes make
    the heaviest host the critical path (VERDICT.md missing #4); LPT is
    within 4/3 of optimal makespan.

    Returns n_bins lists of unit indices; each list preserves ascending index
    order (deterministic iteration within a host).
    """
    import heapq

    if n_bins <= 0:
        raise ValueError("n_bins must be positive")
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    bins: list[list[int]] = [[] for _ in range(n_bins)]
    # (load, bin) heap: O(n log b) instead of the naive O(n*b) lightest-bin
    # scan — at pod shape (256 bins, 10k+ units, VERDICT.md r3 next #5) the
    # naive scan is ~2.6M comparisons on the coordinator-free hot path every
    # process runs at every scan. Tie-break on bin index, identical to the
    # sequential scan's ordering, so assignments are unchanged.
    heap = [(0, j) for j in range(n_bins)]  # already a valid heap
    for i in order:
        load, b = heapq.heappop(heap)
        bins[b].append(i)
        heapq.heappush(heap, (load + sizes[i], b))
    for b in bins:
        b.sort()
    return bins


@dataclasses.dataclass(frozen=True)
class HostStepStats:
    process_index: int
    steps: int
    mean_s: float
    p99_s: float


@dataclasses.dataclass(frozen=True)
class StragglerReport:
    hosts: tuple[HostStepStats, ...]
    median_mean_s: float
    stragglers: tuple[int, ...]  # process indices slower than threshold×median

    def __str__(self) -> str:
        rows = ", ".join(f"p{h.process_index}: {h.mean_s * 1e3:.1f}ms"
                         f"(p99 {h.p99_s * 1e3:.1f})" for h in self.hosts)
        tail = f"; stragglers: {list(self.stragglers)}" if self.stragglers else ""
        return f"steps [{rows}]{tail}"


class StragglerMonitor:
    """Per-host step-time skew accounting.

    Each host records its own step durations (`record`, or wrap the loop
    body with `step()`); `report()` allgathers (mean, p99) across processes
    and flags hosts whose mean exceeds threshold× the median — the signal
    that one host's I/O (or its data shard) is the pod's critical path.
    """

    def __init__(self, window: int = 256):
        self._times: deque[float] = deque(maxlen=window)
        self._t0: float | None = None

    # -- recording ----------------------------------------------------------
    def record(self, seconds: float) -> None:
        self._times.append(seconds)

    def step(self) -> "StragglerMonitor":
        return self  # context-manager form: with monitor.step(): <step body>

    def __enter__(self) -> "StragglerMonitor":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        if self._t0 is not None:
            self.record(time.monotonic() - self._t0)
            self._t0 = None

    # -- local stats --------------------------------------------------------
    def local_stats(self) -> tuple[int, float, float]:
        """(steps, mean_s, p99_s) of the recorded window."""
        if not self._times:
            return 0, 0.0, 0.0
        ts = sorted(self._times)
        mean = sum(ts) / len(ts)
        p99 = ts[min(len(ts) - 1, int(0.99 * len(ts)))]
        return len(ts), mean, p99

    # -- cross-host report --------------------------------------------------
    def report(self, threshold: float = 1.25) -> StragglerReport:
        import jax
        import numpy as np

        steps, mean, p99 = self.local_stats()
        local = np.array([float(steps), mean, p99], dtype=np.float64)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            rows = np.asarray(multihost_utils.process_allgather(local))
        else:
            rows = local[None, :]
        hosts = tuple(HostStepStats(i, int(r[0]), float(r[1]), float(r[2]))
                      for i, r in enumerate(rows))
        means = sorted(h.mean_s for h in hosts)
        median = means[len(means) // 2]
        stragglers = tuple(h.process_index for h in hosts
                           if median > 0 and h.mean_s > threshold * median)
        return StragglerReport(hosts, median, stragglers)
