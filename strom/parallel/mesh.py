"""Device-mesh construction helpers.

The reference's only "fabric" is PCIe P2P between SSD and GPU BAR1; strom-tpu
scales over the pod's ICI/DCN via `jax.sharding.Mesh` + XLA collectives
(SURVEY.md §5 "Distributed comm backend").  Axis convention used across the
framework: dp (data) / sp (sequence) / tp (tensor).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(axes: dict[str, int] | None = None, *,
              devices: list | None = None) -> Mesh:
    """Build a Mesh from axis sizes, e.g. {"dp": 2, "tp": 4}.

    Sizes must multiply to the device count; an axis of size -1 absorbs the
    remainder (like a reshape).  With axes=None, a 1-axis "dp" mesh over all
    devices is returned.
    """
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if axes is None:
        axes = {"dp": n}
    names = tuple(axes)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs "
                         f"{int(np.prod(sizes))} devices, have {n}")
    arr = np.array(devs).reshape(sizes)
    return Mesh(arr, names)


def factor_mesh(n: int, *, want_tp: int = 0) -> dict[str, int]:
    """Pick a sensible {dp, tp} factorisation of n devices: tp as requested if
    it divides n, else the largest power of two <= min(n, 8)."""
    if want_tp and n % want_tp == 0:
        tp = want_tp
    else:
        tp = 1
        while tp * 2 <= min(n, 8) and n % (tp * 2) == 0:
            tp *= 2
    return {"dp": n // tp, "tp": tp}
