from strom.parallel.mesh import make_mesh  # noqa: F401
from strom.parallel.sharding import batch_spec, param_specs  # noqa: F401
