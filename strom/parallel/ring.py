"""Ring attention: exact causal attention over sequence-sharded activations
(long-context / context parallelism, first-class per the build brief; the
loader side already delivers sequence-sharded batches — SURVEY.md §5
"Long-context" row).

TPU-first mechanics: `shard_map` over the mesh's sequence axis; each step
computes a local q-block × kv-block partial with flash-style online-softmax
accumulation, then rotates the kv block one hop around the ring with
`lax.ppermute` — the collective rides ICI neighbor links, and XLA overlaps
the permute with the current block's matmuls.  Communication is O(S/n) per
step, n steps: total bytes ≈ one all-gather, but peak memory stays at one
kv block per device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite "-inf": masked rows stay nan-free


def _block_attn(q: jax.Array, k: jax.Array, v: jax.Array,
                q_pos0: jax.Array, k_pos0: jax.Array, causal: bool
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One q-block × kv-block partial: returns (scores_max [B,KV,G,Sq],
    exp-scores @ v [B,Sq,KV,G,Dh], row denominators) in float32."""
    B, Sq, H, Dh = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, Sq, KV, G, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    if causal:
        qpos = q_pos0 + jnp.arange(Sq)
        kpos = k_pos0 + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None, None], scores, _NEG_BIG)
    m = jnp.max(scores, axis=-1)                         # [B,KV,G,Sq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)                              # [B,KV,G,Sq]
    pv = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v).astype(jnp.float32)
    return m, pv, l


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str, causal: bool = True) -> jax.Array:
    """The shard_map-inner body: q,k,v are this device's sequence block
    ([B,Sq,H,Dh] / [B,Sk,KV,Dh]); returns the exact attention output for the
    local q block against the full (ring-assembled) sequence."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sq, H, Dh = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV

    q_pos0 = idx * Sq

    def update(m, l, acc, k_blk, v_blk, src):
        bm, pv, bl = _block_attn(q, k_blk, v_blk, q_pos0, src * Sk, causal)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)      # m starts at finite _NEG_BIG: no nan
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] \
            + pv * beta.transpose(0, 3, 1, 2)[..., None]
        return m_new, l, acc

    def step(carry, s):
        m, l, acc, k_blk, v_blk = carry
        # rotate one hop, THEN compute: n-1 rotations total, none wasted
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (idx - s) % n            # whose kv block we hold at step s
        m, l, acc = update(m, l, acc, k_blk, v_blk, src)
        return (m, l, acc, k_blk, v_blk), None

    m0 = jnp.full((B, KV, G, Sq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((B, KV, G, Sq), jnp.float32)
    acc0 = jnp.zeros((B, Sq, KV, G, Dh), jnp.float32)
    # step 0: our own kv block, no rotation needed
    m1, l1, acc1 = update(m0, l0, acc0, k, v, idx)
    (m, l, acc, _, _), _ = lax.scan(step, (m1, l1, acc1, k, v),
                                    jnp.arange(1, n))
    denom = l.transpose(0, 3, 1, 2)[..., None]
    out = acc / jnp.maximum(denom, 1e-30)
    return out.reshape(B, Sq, H, Dh).astype(q.dtype)


# ------------------------------------------------------------- ring × flash
# The ring with the Pallas flash kernel as its per-block engine: each ring
# step runs the real TPU kernel on (local q block, rotating kv block) and the
# partials merge by logsumexp. With equal sequence shards, causal masking
# degenerates to three static cases — the DIAGONAL block (src == idx) is the
# ordinary aligned causal kernel, earlier blocks (src < idx) are fully
# visible (non-causal kernel), later blocks contribute nothing — so the
# kernel never needs position offsets. Like the dense ring, masked-out steps
# still run (uniform lax.scan) and are discarded.
#
# Backward is a second ring pass: the forward's GLOBAL logsumexp (and
# Δ = rowsum(dO∘O), local per q row) feed the blockwise backward kernels,
# which then emit each (q block, kv block) pair's exact global-gradient
# contribution (see _flash_bwd). dK/dV accumulators travel WITH the kv
# blocks around the ring and take one final hop home.


def _lse_merge(o: jax.Array, lse: jax.Array, o_b: jax.Array, lse_b: jax.Array
               ) -> tuple[jax.Array, jax.Array]:
    """Merge two attention partials, each normalized w.r.t. its own
    logsumexp. o [B,S,H,Dh] f32; lse [B,H,S,1] f32 (kernel layout)."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w = jnp.exp(lse - lse_new).transpose(0, 2, 1, 3)      # [B,S,H,1]
    w_b = jnp.exp(lse_b - lse_new).transpose(0, 2, 1, 3)
    return o * w + o_b * w_b, lse_new


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k,
                         interpret):
    from strom.ops.flash_attention import _flash_fwd

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    o, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    o = o.astype(jnp.float32)

    def step(carry, s):
        o, lse, k_blk, v_blk = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (idx - s) % n
        o_b, lse_b = _flash_fwd(q, k_blk, v_blk, causal=False,
                                block_q=block_q, block_k=block_k,
                                interpret=interpret)
        o_b = o_b.astype(jnp.float32)
        if causal:
            visible = src < idx
            lse_b = jnp.where(visible, lse_b, _NEG_BIG)
            o_b = jnp.where(visible, o_b, 0.0)
        o, lse = _lse_merge(o, lse, o_b, lse_b)
        return (o, lse, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(step, (o, lse, k, v), jnp.arange(1, n))
    return o.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_flash_attention_local(q, k, v, axis_name: str, causal: bool = True,
                               block_q: int = 128, block_k: int = 128,
                               interpret: bool = False):
    """shard_map-inner ring attention running the Pallas flash kernels.
    Same contract as :func:`ring_attention_local`."""
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                  block_k, interpret)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, block_q, block_k,
                        interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                    block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, block_q, block_k, interpret,
                        res, g):
    from strom.ops.flash_attention import _delta, _flash_bwd

    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # Δ over the GLOBAL output's local rows, in the kernels' [B,H,S,1] layout
    delta = _delta(out, g)

    def pair(k_blk, v_blk, blk_causal):
        return _flash_bwd(q, k_blk, v_blk, out, lse, g, causal=blk_causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, delta=delta)

    dq, dk0, dv0 = pair(k, v, causal)  # diagonal block (aligned causal)
    dq = dq.astype(jnp.float32)

    def step(carry, s):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        # the grad accumulators travel WITH their kv block
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_blk = lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = lax.ppermute(dv_blk, axis_name, perm)
        src = (idx - s) % n
        dq_c, dk_c, dv_c = pair(k_blk, v_blk, False)
        if causal:
            visible = src < idx
            dq_c = jnp.where(visible, dq_c.astype(jnp.float32), 0.0)
            dk_c = jnp.where(visible, dk_c.astype(jnp.float32), 0.0)
            dv_c = jnp.where(visible, dv_c.astype(jnp.float32), 0.0)
        dq = dq + dq_c.astype(jnp.float32)
        dk_blk = dk_blk + dk_c.astype(jnp.float32)
        dv_blk = dv_blk + dv_c.astype(jnp.float32)
        return (dq, k_blk, v_blk, dk_blk, dv_blk), None

    carry0 = (dq, k, v, dk0.astype(jnp.float32), dv0.astype(jnp.float32))
    (dq, _, _, dk_blk, dv_blk), _ = lax.scan(step, carry0, jnp.arange(1, n))
    # after n-1 rotations each kv block sits one hop short of its owner
    perm = [(i, (i + 1) % n) for i in range(n)]
    dk_home = lax.ppermute(dk_blk, axis_name, perm)
    dv_home = lax.ppermute(dv_blk, axis_name, perm)
    return (dq.astype(q.dtype), dk_home.astype(k.dtype),
            dv_home.astype(v.dtype))


ring_flash_attention_local.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def make_ring_attention(mesh: Mesh, *, axis: str = "sp",
                        batch_axis: str = "dp", head_axis: str = "tp",
                        causal: bool = True, impl: str = "dense",
                        block_q: int = 128, block_k: int = 128,
                        interpret: bool | None = None):
    """A drop-in replacement for `strom.models.llama.attention` that runs the
    ring algorithm over *axis*: q,k,v sequence-sharded on it, output likewise.

    The specs also carry the mesh's batch/head axes when present, so entering
    the shard_map reshards nothing: batch stays dp-sharded, heads stay
    tp-sharded (n_kv_heads must divide by the tp size), and only the sequence
    axis participates in the ring.

    impl="flash" runs the Pallas flash kernels per ring block (forward AND
    blockwise backward — the long-context training path); "dense" is the
    pure-jax online-softmax ring (parity oracle, short sequences).
    """
    if impl not in ("dense", "flash"):
        raise ValueError(f"impl must be 'dense' or 'flash', got {impl!r}")
    b = batch_axis if batch_axis in mesh.axis_names else None
    h = head_axis if head_axis in mesh.axis_names else None
    spec = P(b, axis, h, None)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def ring_attn(q, k, v):
        if impl == "flash":
            return ring_flash_attention_local(q, k, v, axis, causal,
                                              block_q, block_k, interpret)
        return ring_attention_local(q, k, v, axis_name=axis, causal=causal)

    return ring_attn
