"""Ring attention: exact causal attention over sequence-sharded activations
(long-context / context parallelism, first-class per the build brief; the
loader side already delivers sequence-sharded batches — SURVEY.md §5
"Long-context" row).

TPU-first mechanics: `shard_map` over the mesh's sequence axis; each step
computes a local q-block × kv-block partial with flash-style online-softmax
accumulation, then rotates the kv block one hop around the ring with
`lax.ppermute` — the collective rides ICI neighbor links, and XLA overlaps
the permute with the current block's matmuls.  Communication is O(S/n) per
step, n steps: total bytes ≈ one all-gather, but peak memory stays at one
kv block per device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite "-inf": masked rows stay nan-free


def _block_attn(q: jax.Array, k: jax.Array, v: jax.Array,
                q_pos0: jax.Array, k_pos0: jax.Array, causal: bool
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One q-block × kv-block partial: returns (scores_max [B,KV,G,Sq],
    exp-scores @ v [B,Sq,KV,G,Dh], row denominators) in float32."""
    B, Sq, H, Dh = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, Sq, KV, G, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    if causal:
        qpos = q_pos0 + jnp.arange(Sq)
        kpos = k_pos0 + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None, None], scores, _NEG_BIG)
    m = jnp.max(scores, axis=-1)                         # [B,KV,G,Sq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)                              # [B,KV,G,Sq]
    pv = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v).astype(jnp.float32)
    return m, pv, l


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str, causal: bool = True) -> jax.Array:
    """The shard_map-inner body: q,k,v are this device's sequence block
    ([B,Sq,H,Dh] / [B,Sk,KV,Dh]); returns the exact attention output for the
    local q block against the full (ring-assembled) sequence."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sq, H, Dh = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV

    q_pos0 = idx * Sq

    def update(m, l, acc, k_blk, v_blk, src):
        bm, pv, bl = _block_attn(q, k_blk, v_blk, q_pos0, src * Sk, causal)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)      # m starts at finite _NEG_BIG: no nan
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] \
            + pv * beta.transpose(0, 3, 1, 2)[..., None]
        return m_new, l, acc

    def step(carry, s):
        m, l, acc, k_blk, v_blk = carry
        # rotate one hop, THEN compute: n-1 rotations total, none wasted
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (idx - s) % n            # whose kv block we hold at step s
        m, l, acc = update(m, l, acc, k_blk, v_blk, src)
        return (m, l, acc, k_blk, v_blk), None

    m0 = jnp.full((B, KV, G, Sq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((B, KV, G, Sq), jnp.float32)
    acc0 = jnp.zeros((B, Sq, KV, G, Dh), jnp.float32)
    # step 0: our own kv block, no rotation needed
    m1, l1, acc1 = update(m0, l0, acc0, k, v, idx)
    (m, l, acc, _, _), _ = lax.scan(step, (m1, l1, acc1, k, v),
                                    jnp.arange(1, n))
    denom = l.transpose(0, 3, 1, 2)[..., None]
    out = acc / jnp.maximum(denom, 1e-30)
    return out.reshape(B, Sq, H, Dh).astype(q.dtype)


# ------------------------------------------------------------- ring × flash
# The ring with the Pallas flash kernel as its per-block engine: each ring
# step runs the real TPU kernel on (local q block, rotating kv block) and the
# partials merge by logsumexp. With equal sequence shards, causal masking
# degenerates to three static cases — the DIAGONAL block (src == idx) is the
# ordinary aligned causal kernel, earlier blocks (src < idx) are fully
# visible (non-causal kernel), later blocks contribute nothing — so the
# kernel never needs position offsets. Like the dense ring, masked-out steps
# still run (uniform lax.scan) and are discarded.
#
# Backward is a second ring pass: the forward's GLOBAL logsumexp (and
# Δ = rowsum(dO∘O), local per q row) feed the blockwise backward kernels,
# which then emit each (q block, kv block) pair's exact global-gradient
# contribution (see _flash_bwd). dK/dV accumulators travel WITH the kv
# blocks around the ring and take one final hop home.


def _lse_merge(o: jax.Array, lse: jax.Array, o_b: jax.Array, lse_b: jax.Array
               ) -> tuple[jax.Array, jax.Array]:
    """Merge two attention partials, each normalized w.r.t. its own
    logsumexp. o [B,S,H,Dh] f32; lse [B,H,S,1] f32 (kernel layout)."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w = jnp.exp(lse - lse_new).transpose(0, 2, 1, 3)      # [B,S,H,1]
    w_b = jnp.exp(lse_b - lse_new).transpose(0, 2, 1, 3)
    return o * w + o_b * w_b, lse_new


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k,
                         interpret):
    from strom.ops.flash_attention import _flash_fwd

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    o, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    o = o.astype(jnp.float32)

    def step(carry, s):
        o, lse, k_blk, v_blk = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (idx - s) % n
        o_b, lse_b = _flash_fwd(q, k_blk, v_blk, causal=False,
                                block_q=block_q, block_k=block_k,
                                interpret=interpret)
        o_b = o_b.astype(jnp.float32)
        if causal:
            visible = src < idx
            lse_b = jnp.where(visible, lse_b, _NEG_BIG)
            o_b = jnp.where(visible, o_b, 0.0)
        o, lse = _lse_merge(o, lse, o_b, lse_b)
        return (o, lse, k_blk, v_blk), None

    (o, lse, _, _), _ = lax.scan(step, (o, lse, k, v), jnp.arange(1, n))
    return o.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_flash_attention_local(q, k, v, axis_name: str, causal: bool = True,
                               block_q: int = 128, block_k: int = 128,
                               interpret: bool = False):
    """shard_map-inner ring attention running the Pallas flash kernels.
    Same contract as :func:`ring_attention_local`."""
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                  block_k, interpret)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis_name, causal, block_q, block_k,
                        interpret):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q,
                                    block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, block_q, block_k, interpret,
                        res, g):
    from strom.ops.flash_attention import _delta, _flash_bwd

    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # Δ over the GLOBAL output's local rows, in the kernels' [B,H,S,1] layout
    delta = _delta(out, g)

    def pair(k_blk, v_blk, blk_causal):
        return _flash_bwd(q, k_blk, v_blk, out, lse, g, causal=blk_causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, delta=delta)

    dq, dk0, dv0 = pair(k, v, causal)  # diagonal block (aligned causal)
    dq = dq.astype(jnp.float32)

    def step(carry, s):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        # the grad accumulators travel WITH their kv block
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_blk = lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = lax.ppermute(dv_blk, axis_name, perm)
        src = (idx - s) % n
        dq_c, dk_c, dv_c = pair(k_blk, v_blk, False)
        if causal:
            visible = src < idx
            dq_c = jnp.where(visible, dq_c.astype(jnp.float32), 0.0)
            dk_c = jnp.where(visible, dk_c.astype(jnp.float32), 0.0)
            dv_c = jnp.where(visible, dv_c.astype(jnp.float32), 0.0)
        dq = dq + dq_c.astype(jnp.float32)
        dk_blk = dk_blk + dk_c.astype(jnp.float32)
        dv_blk = dv_blk + dv_c.astype(jnp.float32)
        return (dq, k_blk, v_blk, dk_blk, dv_blk), None

    carry0 = (dq, k, v, dk0.astype(jnp.float32), dv0.astype(jnp.float32))
    (dq, _, _, dk_blk, dv_blk), _ = lax.scan(step, carry0, jnp.arange(1, n))
    # after n-1 rotations each kv block sits one hop short of its owner
    perm = [(i, (i + 1) % n) for i in range(n)]
    dk_home = lax.ppermute(dk_blk, axis_name, perm)
    dv_home = lax.ppermute(dv_blk, axis_name, perm)
    return (dq.astype(q.dtype), dk_home.astype(k.dtype),
            dv_home.astype(v.dtype))


ring_flash_attention_local.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


# ------------------------------------------------------- zigzag (balanced)
# The plain causal ring discards ~half its compute: at ring step s a device
# whose kv source is "in its future" (src > idx) runs the kernel and throws
# the result away (uniform SPMD). The classic fix re-layouts the sequence in
# ZIGZAG order — split into 2n chunks, device i holds chunks (i, 2n−1−i) —
# so every device owns one early and one late chunk and each ring step
# leaves every device the same amount of VISIBLE work. The permutes are pure
# chunk routing (4 full-bijection ppermutes total, entry + exit), sit
# OUTSIDE the custom-vjp core (autodiff transposes them), and touch no model
# code: RoPE/embeddings were applied before attention on the contiguous
# layout, and the output returns to contiguous order.
#
# Per ring step the core runs 3 half-chunk flash calls — (q_early·kv_early)
# causal-or-masked, (q_late·kv_early) always fully visible, (q_late·kv_late)
# causal-or-masked; exactly one of the two maskable calls is discarded — so
# waste is ~1/3 of 1/4-sized kernels vs ~1/2 of full-sized ones.


def _zigzag_entry(x, axis_name: str):
    """Contiguous shard (chunks 2i, 2i+1) → zigzag pair (chunk i, 2n−1−i).
    x: [B, Sl, ...] with Sl even. Returns (early, late), each [B, Sl/2, ...]."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    h0, h1 = jnp.split(x, 2, axis=1)
    # owner(c) = c if c < n else 2n−1−c ; both perms are full bijections
    perm_a = [(i, 2 * i if 2 * i < n else 2 * n - 1 - 2 * i)
              for i in range(n)]
    perm_b = [(i, 2 * i + 1 if 2 * i + 1 < n else 2 * n - 2 - 2 * i)
              for i in range(n)]
    ra = lax.ppermute(h0, axis_name, perm_a)   # arrives: chunk with parity 0
    rb = lax.ppermute(h1, axis_name, perm_b)   # arrives: chunk with parity 1
    # device d's early chunk is d (even→ra, odd→rb); late is 2n−1−d (opposite)
    even = (idx % 2 == 0)
    early = jnp.where(even, ra, rb)
    late = jnp.where(even, rb, ra)
    return early, late


def _zigzag_exit(early, late, axis_name: str):
    """Inverse of :func:`_zigzag_entry`: zigzag pair → contiguous shard."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    even = (idx % 2 == 0)
    # perm_a_inv targets half0 (chunk 2i): source d sends its chunk-2i slot —
    # early when d even (chunk d == 2i), late when d odd (chunk 2n−1−d == 2i)
    perm_a_inv = [(2 * i if 2 * i < n else 2 * n - 1 - 2 * i, i)
                  for i in range(n)]
    perm_b_inv = [(2 * i + 1 if 2 * i + 1 < n else 2 * n - 2 - 2 * i, i)
                  for i in range(n)]
    pay_a = jnp.where(even, early, late)
    pay_b = jnp.where(even, late, early)   # chunk 2i+1 sits opposite
    h0 = lax.ppermute(pay_a, axis_name, perm_a_inv)
    h1 = lax.ppermute(pay_b, axis_name, perm_b_inv)
    return jnp.concatenate([h0, h1], axis=1)


def _zz_pairs(q1, q2, k1, k2, v1, v2, src, idx, block_q, block_k, interpret,
              fwd_state, flash_fwd):
    """One zigzag ring step's three half-chunk flash calls, merged into the
    per-half running (o, lse) state. src: whose kv pair we hold (chunk ids
    b1=src, b2=2n−1−src); q halves are chunks a1=idx, a2=2n−1−idx."""
    (o1, l1), (o2, l2) = fwd_state

    def call(q, k, v, causal):
        o, l = flash_fwd(q, k, v, causal=causal, block_q=block_q,
                         block_k=block_k, interpret=interpret)
        return o.astype(jnp.float32), l

    # a1 (early) vs b1: diagonal at src==idx, fully visible when src<idx.
    # The diagonal needs the CAUSAL kernel; off-diagonal the non-causal one —
    # run non-causal and fix the diagonal by select (diag only at step 0,
    # handled by the caller passing causal=True there).
    u1o, u1l = call(q1, k1, v1, False)
    vis1 = src < idx
    u1l = jnp.where(vis1, u1l, _NEG_BIG)
    u1o = jnp.where(vis1, u1o, 0.0)
    o1, l1 = _lse_merge(o1, l1, u1o, u1l)
    # a2 (late) vs b1 (early): always fully visible
    u2o, u2l = call(q2, k1, v1, False)
    o2, l2 = _lse_merge(o2, l2, u2o, u2l)
    # a2 vs b2: visible when src>idx (later early-chunk ⇒ EARLIER late-chunk)
    u3o, u3l = call(q2, k2, v2, False)
    vis3 = src > idx
    u3l = jnp.where(vis3, u3l, _NEG_BIG)
    u3o = jnp.where(vis3, u3o, 0.0)
    o2, l2 = _lse_merge(o2, l2, u3o, u3l)
    return (o1, l1), (o2, l2)


def _zz_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret):
    from strom.ops.flash_attention import _flash_fwd

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # inputs arrive ALREADY in zigzag order (early ‖ late): just split
    q1, q2 = jnp.split(q, 2, axis=1)
    k1, k2 = jnp.split(k, 2, axis=1)
    v1, v2 = jnp.split(v, 2, axis=1)

    # step 0: own kv pair — the two diagonals run the causal kernel
    o1, l1 = _flash_fwd(q1, k1, v1, causal=True, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    o2a, l2a = _flash_fwd(q2, k1, v1, causal=False, block_q=block_q,
                          block_k=block_k, interpret=interpret)
    o2b, l2b = _flash_fwd(q2, k2, v2, causal=True, block_q=block_q,
                          block_k=block_k, interpret=interpret)
    o1 = o1.astype(jnp.float32)
    o2, l2 = _lse_merge(o2a.astype(jnp.float32), l2a,
                        o2b.astype(jnp.float32), l2b)

    def step(carry, s):
        (o1, l1), (o2, l2), kk1, kk2, vv1, vv2 = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk1 = lax.ppermute(kk1, axis_name, perm)
        kk2 = lax.ppermute(kk2, axis_name, perm)
        vv1 = lax.ppermute(vv1, axis_name, perm)
        vv2 = lax.ppermute(vv2, axis_name, perm)
        src = (idx - s) % n
        st = _zz_pairs(q1, q2, kk1, kk2, vv1, vv2, src, idx, block_q,
                       block_k, interpret, ((o1, l1), (o2, l2)), _flash_fwd)
        return (st[0], st[1], kk1, kk2, vv1, vv2), None

    ((o1, l1), (o2, l2), _, _, _, _), _ = lax.scan(
        step, ((o1, l1), (o2, l2), k1, k2, v1, v2), jnp.arange(1, n))
    return (o1.astype(q.dtype), o2.astype(q.dtype)), (l1, l2), (q1, q2, k1,
                                                                k2, v1, v2)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _zz_core(q, k, v, axis_name, block_q, block_k, interpret):
    (o1, o2), _, _ = _zz_fwd_impl(q, k, v, axis_name, block_q, block_k,
                                  interpret)
    return jnp.concatenate([o1, o2], axis=1)  # zigzag order (early ‖ late)


def _zz_vjp_fwd(q, k, v, axis_name, block_q, block_k, interpret):
    (o1, o2), (l1, l2), zz = _zz_fwd_impl(q, k, v, axis_name, block_q,
                                          block_k, interpret)
    return jnp.concatenate([o1, o2], axis=1), (zz, (o1, o2), (l1, l2))


def _zz_vjp_bwd(axis_name, block_q, block_k, interpret, res, g):
    from strom.ops.flash_attention import _delta, _flash_bwd

    (q1, q2, k1, k2, v1, v2), (o1, o2), (l1, l2) = res
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    g1, g2 = jnp.split(g, 2, axis=1)
    d1 = _delta(o1, g1)
    d2 = _delta(o2, g2)

    def pair(qh, gh, oh, lh, dh, kb, vb, causal):
        return _flash_bwd(qh, kb, vb, oh, lh, gh, causal=causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, delta=dh)

    # step 0: own kv pair (diagonals causal)
    dq1, dk1, dv1 = pair(q1, g1, o1, l1, d1, k1, v1, True)
    dq2a, dk1b, dv1b = pair(q2, g2, o2, l2, d2, k1, v1, False)
    dq2b, dk2, dv2 = pair(q2, g2, o2, l2, d2, k2, v2, True)
    dq1 = dq1.astype(jnp.float32)
    dq2 = dq2a.astype(jnp.float32) + dq2b.astype(jnp.float32)
    dk1 = dk1.astype(jnp.float32) + dk1b.astype(jnp.float32)
    dv1 = dv1.astype(jnp.float32) + dv1b.astype(jnp.float32)
    dk2 = dk2.astype(jnp.float32)
    dv2 = dv2.astype(jnp.float32)

    def step(carry, s):
        dq1, dq2, kk1, kk2, vv1, vv2, dkk1, dkk2, dvv1, dvv2 = carry
        perm = [(i, (i + 1) % n) for i in range(n)]
        kk1 = lax.ppermute(kk1, axis_name, perm)
        kk2 = lax.ppermute(kk2, axis_name, perm)
        vv1 = lax.ppermute(vv1, axis_name, perm)
        vv2 = lax.ppermute(vv2, axis_name, perm)
        dkk1 = lax.ppermute(dkk1, axis_name, perm)
        dkk2 = lax.ppermute(dkk2, axis_name, perm)
        dvv1 = lax.ppermute(dvv1, axis_name, perm)
        dvv2 = lax.ppermute(dvv2, axis_name, perm)
        src = (idx - s) % n
        u_dq1, u_dk1, u_dv1 = pair(q1, g1, o1, l1, d1, kk1, vv1, False)
        vis1 = src < idx
        dq1n = dq1 + jnp.where(vis1, u_dq1.astype(jnp.float32), 0.0)
        dkk1 = dkk1 + jnp.where(vis1, u_dk1.astype(jnp.float32), 0.0)
        dvv1 = dvv1 + jnp.where(vis1, u_dv1.astype(jnp.float32), 0.0)
        u_dq2, u_dk1b, u_dv1b = pair(q2, g2, o2, l2, d2, kk1, vv1, False)
        dq2n = dq2 + u_dq2.astype(jnp.float32)
        dkk1 = dkk1 + u_dk1b.astype(jnp.float32)
        dvv1 = dvv1 + u_dv1b.astype(jnp.float32)
        u_dq2b, u_dk2, u_dv2 = pair(q2, g2, o2, l2, d2, kk2, vv2, False)
        vis3 = src > idx
        dq2n = dq2n + jnp.where(vis3, u_dq2b.astype(jnp.float32), 0.0)
        dkk2 = dkk2 + jnp.where(vis3, u_dk2.astype(jnp.float32), 0.0)
        dvv2 = dvv2 + jnp.where(vis3, u_dv2.astype(jnp.float32), 0.0)
        return (dq1n, dq2n, kk1, kk2, vv1, vv2, dkk1, dkk2, dvv1, dvv2), None

    carry0 = (dq1, dq2, k1, k2, v1, v2, dk1, dk2, dv1, dv2)
    (dq1, dq2, _, _, _, _, dk1, dk2, dv1, dv2), _ = lax.scan(
        step, carry0, jnp.arange(1, n))
    # kv (and their grads) sit one hop short of home after n−1 rotations
    perm = [(i, (i + 1) % n) for i in range(n)]
    dk1 = lax.ppermute(dk1, axis_name, perm)
    dk2 = lax.ppermute(dk2, axis_name, perm)
    dv1 = lax.ppermute(dv1, axis_name, perm)
    dv2 = lax.ppermute(dv2, axis_name, perm)
    return (jnp.concatenate([dq1, dq2], axis=1).astype(q1.dtype),
            jnp.concatenate([dk1, dk2], axis=1).astype(k1.dtype),
            jnp.concatenate([dv1, dv2], axis=1).astype(v1.dtype))


_zz_core.defvjp(_zz_vjp_fwd, _zz_vjp_bwd)


def zigzag_ring_flash_local(q, k, v, axis_name: str, block_q: int = 128,
                            block_k: int = 128, interpret: bool = False):
    """Causal ring×flash on the zigzag layout. Same contract as
    :func:`ring_attention_local`: contiguous sequence shards in, contiguous
    exact-attention output out — the zigzag relayout is internal."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return ring_flash_attention_local(q, k, v, axis_name, True, block_q,
                                          block_k, interpret)
    if q.shape[1] % 2:
        raise ValueError(f"zigzag needs an even per-device sequence length, "
                         f"got {q.shape[1]} (the shard splits into an "
                         "early and a late half-chunk)")
    qz = jnp.concatenate(_zigzag_entry(q, axis_name), axis=1)
    kz = jnp.concatenate(_zigzag_entry(k, axis_name), axis=1)
    vz = jnp.concatenate(_zigzag_entry(v, axis_name), axis=1)
    # core consumes/produces zigzag order; entry/exit permutes live outside
    # the custom vjp so autodiff transposes them
    oz = _zz_core(qz, kz, vz, axis_name, block_q, block_k, interpret)
    o1, o2 = jnp.split(oz, 2, axis=1)
    return _zigzag_exit(o1, o2, axis_name)


def make_ring_attention_local(impl: str, *, axis: str = "sp",
                              causal: bool = True, block_q: int = 128,
                              block_k: int = 128,
                              interpret: bool | None = None):
    """The shard_map-INNER ring body for *impl* — shared by
    :func:`make_ring_attention` (which wraps it in its own shard_map) and
    the pipelined step (already inside a shard_map). One dispatch, one
    interpret default, one place to tune block sizes."""
    if impl not in ("dense", "flash", "zigzag"):
        raise ValueError(
            f"impl must be 'dense', 'flash' or 'zigzag', got {impl!r}")
    if impl == "zigzag" and not causal:
        raise ValueError("zigzag balances the CAUSAL ring; use impl='flash' "
                         "for non-causal attention")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if impl == "zigzag":
        return lambda q, k, v: zigzag_ring_flash_local(
            q, k, v, axis, block_q, block_k, interpret)
    if impl == "flash":
        return lambda q, k, v: ring_flash_attention_local(
            q, k, v, axis, causal, block_q, block_k, interpret)
    return partial(ring_attention_local, axis_name=axis, causal=causal)


def make_ring_attention(mesh: Mesh, *, axis: str = "sp",
                        batch_axis: str = "dp", head_axis: str = "tp",
                        causal: bool = True, impl: str = "dense",
                        block_q: int = 128, block_k: int = 128,
                        interpret: bool | None = None):
    """A drop-in replacement for `strom.models.llama.attention` that runs the
    ring algorithm over *axis*: q,k,v sequence-sharded on it, output likewise.

    The specs also carry the mesh's batch/head axes when present, so entering
    the shard_map reshards nothing: batch stays dp-sharded, heads stay
    tp-sharded (n_kv_heads must divide by the tp size), and only the sequence
    axis participates in the ring.

    impl="flash" runs the Pallas flash kernels per ring block (forward AND
    blockwise backward — the long-context training path); "dense" is the
    pure-jax online-softmax ring (parity oracle, short sequences);
    "zigzag" is the load-balanced causal flash ring (internal zigzag
    relayout; causal only — the imbalance it fixes is causality's).
    """
    local = make_ring_attention_local(impl, axis=axis, causal=causal,
                                      block_q=block_q, block_k=block_k,
                                      interpret=interpret)
    b = batch_axis if batch_axis in mesh.axis_names else None
    h = head_axis if head_axis in mesh.axis_names else None
    spec = P(b, axis, h, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def ring_attn(q, k, v):
        return local(q, k, v)

    return ring_attn
