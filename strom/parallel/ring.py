"""Ring attention: exact causal attention over sequence-sharded activations
(long-context / context parallelism, first-class per the build brief; the
loader side already delivers sequence-sharded batches — SURVEY.md §5
"Long-context" row).

TPU-first mechanics: `shard_map` over the mesh's sequence axis; each step
computes a local q-block × kv-block partial with flash-style online-softmax
accumulation, then rotates the kv block one hop around the ring with
`lax.ppermute` — the collective rides ICI neighbor links, and XLA overlaps
the permute with the current block's matmuls.  Communication is O(S/n) per
step, n steps: total bytes ≈ one all-gather, but peak memory stays at one
kv block per device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)  # finite "-inf": masked rows stay nan-free


def _block_attn(q: jax.Array, k: jax.Array, v: jax.Array,
                q_pos0: jax.Array, k_pos0: jax.Array, causal: bool
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One q-block × kv-block partial: returns (scores_max [B,KV,G,Sq],
    exp-scores @ v [B,Sq,KV,G,Dh], row denominators) in float32."""
    B, Sq, H, Dh = q.shape
    KV = k.shape[2]
    G = H // KV
    qg = q.reshape(B, Sq, KV, G, Dh)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(Dh))
    if causal:
        qpos = q_pos0 + jnp.arange(Sq)
        kpos = k_pos0 + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None, None], scores, _NEG_BIG)
    m = jnp.max(scores, axis=-1)                         # [B,KV,G,Sq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)                              # [B,KV,G,Sq]
    pv = jnp.einsum("bkgqs,bskd->bqkgd", p.astype(v.dtype), v).astype(jnp.float32)
    return m, pv, l


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis_name: str, causal: bool = True) -> jax.Array:
    """The shard_map-inner body: q,k,v are this device's sequence block
    ([B,Sq,H,Dh] / [B,Sk,KV,Dh]); returns the exact attention output for the
    local q block against the full (ring-assembled) sequence."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, Sq, H, Dh = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV

    q_pos0 = idx * Sq

    def update(m, l, acc, k_blk, v_blk, src):
        bm, pv, bl = _block_attn(q, k_blk, v_blk, q_pos0, src * Sk, causal)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)      # m starts at finite _NEG_BIG: no nan
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * alpha.transpose(0, 3, 1, 2)[..., None] \
            + pv * beta.transpose(0, 3, 1, 2)[..., None]
        return m_new, l, acc

    def step(carry, s):
        m, l, acc, k_blk, v_blk = carry
        # rotate one hop, THEN compute: n-1 rotations total, none wasted
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (idx - s) % n            # whose kv block we hold at step s
        m, l, acc = update(m, l, acc, k_blk, v_blk, src)
        return (m, l, acc, k_blk, v_blk), None

    m0 = jnp.full((B, KV, G, Sq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((B, KV, G, Sq), jnp.float32)
    acc0 = jnp.zeros((B, Sq, KV, G, Dh), jnp.float32)
    # step 0: our own kv block, no rotation needed
    m1, l1, acc1 = update(m0, l0, acc0, k, v, idx)
    (m, l, acc, _, _), _ = lax.scan(step, (m1, l1, acc1, k, v),
                                    jnp.arange(1, n))
    denom = l.transpose(0, 3, 1, 2)[..., None]
    out = acc / jnp.maximum(denom, 1e-30)
    return out.reshape(B, Sq, H, Dh).astype(q.dtype)


def make_ring_attention(mesh: Mesh, *, axis: str = "sp",
                        batch_axis: str = "dp", head_axis: str = "tp",
                        causal: bool = True):
    """A drop-in replacement for `strom.models.llama.attention` that runs the
    ring algorithm over *axis*: q,k,v sequence-sharded on it, output likewise.

    The specs also carry the mesh's batch/head axes when present, so entering
    the shard_map reshards nothing: batch stays dp-sharded, heads stay
    tp-sharded (n_kv_heads must divide by the tp size), and only the sequence
    axis participates in the ring.
    """
    b = batch_axis if batch_axis in mesh.axis_names else None
    h = head_axis if head_axis in mesh.axis_names else None
    spec = P(b, axis, h, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def ring_attn(q, k, v):
        return ring_attention_local(q, k, v, axis_name=axis, causal=causal)

    return ring_attn
