"""Sharding rules for the flagship model: Megatron-style tensor parallelism
expressed as PartitionSpecs; XLA inserts the ICI collectives (scaling-book
recipe: pick a mesh, annotate shardings, let the compiler do the rest)."""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Per-leaf PartitionSpecs for strom.models.llama params. The stacked layers'
# LEADING axis carries "pp" (pipeline stages each hold n_layers/pp layers);
# on meshes without a pp axis, param_shardings' restrict() degrades it to
# replicated, so non-pipeline steps are unaffected. Column-parallel (output
# dim on tp) feeding row-parallel (input dim on tp) pairs keep activations
# tp-local between the two matmuls; XLA adds the reduce-scatter/all-reduce
# at the end.
_LLAMA_RULES = {
    ("embed",): P(None, "tp"),
    ("layers", "attn_norm"): P("pp"),
    ("layers", "wq"): P("pp", None, "tp"),
    ("layers", "wk"): P("pp", None, "tp"),
    ("layers", "wv"): P("pp", None, "tp"),
    ("layers", "wo"): P("pp", "tp", None),
    ("layers", "mlp_norm"): P("pp"),
    ("layers", "w_gate"): P("pp", None, "tp"),
    ("layers", "w_up"): P("pp", None, "tp"),
    ("layers", "w_down"): P("pp", "tp", None),
    ("final_norm",): P(),
    ("lm_head",): P(None, "tp"),
}


def _path_key(path) -> tuple:
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append(p.key)
        elif hasattr(p, "idx"):
            out.append(p.idx)
    return tuple(out)


def param_specs(params: dict) -> dict:
    """PartitionSpec pytree matching the llama param pytree."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: _LLAMA_RULES.get(_path_key(path), P()), params)


def param_shardings(params: dict, mesh: Mesh) -> dict:
    """Specs restricted to the mesh's axes: a rule axis the mesh doesn't have
    (e.g. tp on a dp×sp mesh) degrades to replicated on that dim."""

    def restrict(spec: P) -> P:
        return P(*(ax if ax in mesh.axis_names else None for ax in spec))

    return jax.tree.map(lambda spec: NamedSharding(mesh, restrict(spec)),
                        param_specs(params),
                        is_leaf=lambda x: isinstance(x, P))


# MoE variant: attention rules shared with llama; expert FFN stacks carry the
# ep axis on the expert dim (tokens all-to-all into expert shards is XLA's to
# place), tp on the hidden dim within each expert.
_MOE_RULES = {
    **_LLAMA_RULES,
    ("layers", "router"): P("pp"),
    ("layers", "w_gate"): P("pp", "ep", None, "tp"),
    ("layers", "w_up"): P("pp", "ep", None, "tp"),
    ("layers", "w_down"): P("pp", "ep", "tp", None),
}


def moe_param_specs(params: dict) -> dict:
    return jax.tree_util.tree_map_with_path(
        lambda path, _: _MOE_RULES.get(_path_key(path), P()), params)


def moe_param_shardings(params: dict, mesh: Mesh) -> dict:
    def restrict(spec: P) -> P:
        return P(*(ax if ax in mesh.axis_names else None for ax in spec))

    return jax.tree.map(lambda spec: NamedSharding(mesh, restrict(spec)),
                        moe_param_specs(params),
                        is_leaf=lambda x: isinstance(x, P))


def batch_spec(*, sp: bool = False) -> P:
    """Token batches: batch on dp, optionally sequence on sp (long-context
    loaders deliver sequence-sharded batches, SURVEY.md §5)."""
    return P("dp", "sp") if sp else P("dp")
