"""Multichip dry-run: full sharded training step + strom sharded delivery on
an n-device mesh (driver runs this with virtual CPU devices)."""

from __future__ import annotations

import os
import tempfile

import numpy as np


def _engine_desc(ctx) -> str:
    """Engine identity line for the dryrun tail (VERDICT.md r3 next #3: the
    virtual-mesh matrix must say which engine each config exercised)."""
    eng = ctx.engine
    rings = getattr(eng, "num_rings", None)
    return f"{eng.name}(rings={rings})" if rings is not None else eng.name


def _deliver_tokens(tokens_host: np.ndarray, mesh, spec,
                    engine: str) -> tuple:
    """Deliver a token batch through the REAL data path: write it to disk,
    then memcpy_ssd2tpu it onto *mesh* with the given PartitionSpec.
    Returns (sharded tokens, engine description) — the shared shape of
    every non-striped delivery-fed matrix config (VERDICT.md r4 next #4)."""
    from jax.sharding import NamedSharding

    from strom.config import StromConfig
    from strom.delivery.core import StromContext

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "tokens.bin")
        tokens_host.tofile(path)
        ctx = StromContext(StromConfig(engine=engine, queue_depth=8,
                                       num_buffers=8))
        try:
            desc = _engine_desc(ctx)
            tokens = ctx.memcpy_ssd2tpu(
                path, shape=tokens_host.shape, dtype=tokens_host.dtype,
                sharding=NamedSharding(mesh, spec))
        finally:
            ctx.close()
    return tokens, desc


def run_dryrun(n_devices: int) -> None:
    import jax

    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from strom.config import StromConfig
    from strom.delivery.core import StromContext
    from strom.models.llama import LlamaConfig
    from strom.parallel.mesh import factor_mesh, make_mesh
    from strom.parallel.train import init_train_state, make_optimizer, make_train_step

    devs = jax.devices()[:n_devices]
    axes = factor_mesh(n_devices, want_tp=min(4, n_devices))
    mesh = make_mesh(axes, devices=devs)

    cfg = LlamaConfig.tiny()
    optimizer = make_optimizer()
    state = init_train_state(jax.random.PRNGKey(0), cfg, mesh, optimizer)
    # flash: the default TPU training path — Pallas kernels run in interpret
    # mode on the virtual CPU mesh, so the dryrun compiles the same graph
    step = make_train_step(cfg, mesh, optimizer, attn="flash")

    # Deliver the token batch through the real data path: packed-token .bin on
    # disk -> memcpy_ssd2tpu -> jax.Array sharded P("dp") over the mesh.
    # Flagship config rides the PRODUCTION engine (engine="auto": the C++
    # io_uring engine when it initializes, else the Python fallback —
    # VERDICT.md r3 next #3): the virtual-mesh correctness matrix must
    # exercise the same data path the benches run.
    B, S = 2 * axes["dp"], 64
    rng = np.random.default_rng(0)
    tokens_host = rng.integers(0, cfg.vocab, size=(B, S + 1), dtype=np.int32)
    batch, eng_desc = _deliver_tokens(tokens_host, mesh, P("dp", None),
                                      "auto")
    state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), f"non-finite loss {loss}"
    assert int(state.step) == 1
    print(f"dryrun ok: mesh={axes}, devices={n_devices}, loss={loss:.4f}, "
          f"engine={eng_desc}")

    # Long-context path: dp×sp mesh, SEQUENCE-SHARDED delivery — the batch
    # arrives P("dp", "sp") through the real data path, so the shard
    # planner runs on a non-batch axis inside the matrix (each device's
    # byte ranges are row FRAGMENTS of the packed records, not whole rows —
    # VERDICT.md r4 next #4), then ring attention consumes it.
    if n_devices >= 2 and n_devices % 2 == 0:
        # keep dp ≥ 2 when possible so both axes are exercised
        sp = 2
        while sp * 2 <= min(max(n_devices // 2, 2), 8) and n_devices % (sp * 2) == 0:
            sp *= 2
        sp_axes = {"dp": n_devices // sp, "sp": sp}
        sp_mesh = make_mesh(sp_axes, devices=devs)
        state = init_train_state(jax.random.PRNGKey(0), cfg, sp_mesh, optimizer)
        # ring × flash: the Pallas kernels run inside the ring (interpret
        # mode on the virtual mesh) — the flagship long-context combination
        sp_step = make_train_step(cfg, sp_mesh, optimizer, sp=True,
                                  attn="flash")
        B, L = 2 * sp_axes["dp"], 64  # record length divisible by sp
        tokens_host = np.random.default_rng(1).integers(
            0, cfg.vocab, (B, L), dtype=np.int32)
        tokens, eng_desc = _deliver_tokens(tokens_host, sp_mesh,
                                           P("dp", "sp"), "auto")
        state, metrics = sp_step(state, tokens)
        sp_loss = float(metrics["loss"])
        assert np.isfinite(sp_loss), f"non-finite sp loss {sp_loss}"
        print(f"dryrun ok: mesh={sp_axes} (ring attention), loss={sp_loss:.4f}, "
              f"engine={eng_desc}, delivery=P('dp','sp') sequence-sharded")

    # Expert-parallel path: dp×ep mesh, MoE model, ep-sharded expert stacks
    if n_devices >= 2 and n_devices % 2 == 0:
        from strom.models.moe import MoEConfig
        from strom.parallel.train import init_moe_train_state, make_moe_train_step

        ep = 2
        while ep * 2 <= min(max(n_devices // 2, 2), 8) and n_devices % (ep * 2) == 0:
            ep *= 2
        ep_axes = {"dp": n_devices // ep, "ep": ep}
        ep_mesh = make_mesh(ep_axes, devices=devs)
        mcfg = MoEConfig.tiny(n_experts=max(ep, 4))
        state = init_moe_train_state(jax.random.PRNGKey(0), mcfg, ep_mesh, optimizer)
        ep_step = make_moe_train_step(mcfg, ep_mesh, optimizer)
        B = 2 * ep_axes["dp"]
        tokens_host = np.random.default_rng(2).integers(
            0, mcfg.base.vocab, (B, 64), dtype=np.int32)
        # delivery-fed (VERDICT.md r4 next #4): dp-sharded batch through the
        # real data path on the Python engine (engine diversity across the
        # matrix; the flagship/sp configs ride uring)
        tokens, eng_desc = _deliver_tokens(tokens_host, ep_mesh,
                                           P("dp", None), "python")
        state, metrics = ep_step(state, tokens)
        ep_loss = float(metrics["loss"])
        assert np.isfinite(ep_loss), f"non-finite ep loss {ep_loss}"
        print(f"dryrun ok: mesh={ep_axes} (MoE expert parallel), "
              f"loss={ep_loss:.4f}, engine={eng_desc}")

    # MoE × long-context: dp×ep×sp — expert parallelism composed with ring
    # attention (flash inside the ring) over a sequence-sharded batch; the
    # expert all-to-alls and the ring's kv ppermutes coexist on one mesh
    if n_devices >= 8 and n_devices % 4 == 0:
        from strom.models.moe import MoEConfig
        from strom.parallel.train import init_moe_train_state, make_moe_train_step

        mix_axes = {"dp": n_devices // 4, "ep": 2, "sp": 2}
        mix_mesh = make_mesh(mix_axes, devices=devs)
        mcfg = MoEConfig.tiny(n_experts=4)
        state = init_moe_train_state(jax.random.PRNGKey(3), mcfg, mix_mesh,
                                     optimizer)
        mix_step = make_moe_train_step(mcfg, mix_mesh, optimizer, sp=True,
                                       attn="flash")
        B, L = 2 * mix_axes["dp"], 64
        tokens_host = np.random.default_rng(5).integers(
            0, mcfg.base.vocab, (B, L), dtype=np.int32)
        # delivery-fed, sequence-sharded on a THREE-axis mesh: the planner
        # splits rows over dp and row fragments over sp while ep stays
        # replicated for the batch (VERDICT.md r4 next #4)
        tokens, eng_desc = _deliver_tokens(tokens_host, mix_mesh,
                                           P("dp", "sp"), "python")
        state, metrics = mix_step(state, tokens)
        mix_loss = float(metrics["loss"])
        assert np.isfinite(mix_loss), f"non-finite dp×ep×sp loss {mix_loss}"
        print(f"dryrun ok: mesh={mix_axes} (dp×ep×sp MoE ring×flash), "
              f"loss={mix_loss:.4f}, engine={eng_desc}")

    # Pipeline parallelism: dp×pp — layer stacks pp-sharded, microbatches
    # pumped through the stages via ppermute, fed by the real delivery path
    if n_devices >= 2 and n_devices % 2 == 0 and cfg.n_layers % 2 == 0:
        from strom.parallel.pipeline import make_pp_train_step

        pp_axes = {"dp": n_devices // 2, "pp": 2}
        pp_mesh = make_mesh(pp_axes, devices=devs)
        state = init_train_state(jax.random.PRNGKey(0), cfg, pp_mesh, optimizer)
        pp_step = make_pp_train_step(cfg, pp_mesh, optimizer, microbatches=2)
        B = 4 * pp_axes["dp"]  # local batch 4 → 2 microbatches of 2
        rng_pp = np.random.default_rng(4)
        tokens_host = rng_pp.integers(0, cfg.vocab, size=(B, 65), dtype=np.int32)
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "pp_tokens.bin")
            tokens_host.tofile(path)
            # this config rides the MULTI-RING production path (VERDICT.md
            # r3 next #3): engine="auto" + engine_rings=2, tokens striped
            # RAID0 over two members so the per-file ring fan-out actually
            # engages (member i → ring i mod N) under sharded delivery
            from strom.engine.raid0 import stripe_file

            members = [os.path.join(td, f"pp_m{i}.bin") for i in range(2)]
            stripe_file(path, members, 1024)
            ctx = StromContext(StromConfig(engine="auto", engine_rings=2,
                                           queue_depth=8, num_buffers=8))
            try:
                eng_desc = _engine_desc(ctx)
                virt = path + ".raid0"
                ctx.register_striped(virt, members, 1024,
                                     size=os.path.getsize(path))
                batch = ctx.memcpy_ssd2tpu(
                    virt, shape=(B, 65), dtype=np.int32,
                    sharding=NamedSharding(pp_mesh, P("dp", None)))
                state, metrics = pp_step(state, batch)
                pp_loss = float(metrics["loss"])
                ring_stats = ctx.engine.stats().get("ring_stats")
                if ring_stats is not None:
                    traffic = [int(r.get("bytes_read", 0)) for r in ring_stats]
                    assert all(t > 0 for t in traffic), \
                        f"a ring carried no bytes: {traffic}"
            finally:
                ctx.close()
        assert np.isfinite(pp_loss), f"non-finite pp loss {pp_loss}"
        print(f"dryrun ok: mesh={pp_axes} (pipeline parallelism), "
              f"loss={pp_loss:.4f}, engine={eng_desc}")

    # Full 3-axis composition with the pipe: dp×tp×pp — manual-collective
    # Megatron blocks inside each stage, microbatches over ppermute
    if n_devices >= 8 and n_devices % 4 == 0 and cfg.n_layers % 2 == 0:
        from strom.parallel.pipeline import make_pp_train_step

        axes_tpp = {"dp": n_devices // 4, "tp": 2, "pp": 2}
        mesh_tpp = make_mesh(axes_tpp, devices=devs)
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh_tpp,
                                 optimizer)
        step_tpp = make_pp_train_step(cfg, mesh_tpp, optimizer,
                                      microbatches=2)
        B = 4 * axes_tpp["dp"]
        tokens_host = np.random.default_rng(5).integers(
            0, cfg.vocab, size=(B, 64), dtype=np.int32)
        # through the real delivery path, like the other pipeline case
        tokens, eng_desc = _deliver_tokens(tokens_host, mesh_tpp,
                                           P("dp", None), "python")
        state, metrics = step_tpp(state, tokens)
        tpp_loss = float(metrics["loss"])
        assert np.isfinite(tpp_loss), f"non-finite dp×tp×pp loss {tpp_loss}"
        print(f"dryrun ok: mesh={axes_tpp} (dp×tp×pp pipeline), "
              f"loss={tpp_loss:.4f}, engine={eng_desc}")

    # Deepest composition: tp×sp×pp in ONE step — manual-tp Megatron blocks,
    # ring×flash attention over sp inside every pipeline stage
    if n_devices >= 8 and n_devices % 8 == 0 and cfg.n_layers % 2 == 0:
        from strom.parallel.pipeline import make_pp_train_step

        axes4 = {"tp": 2, "sp": 2, "pp": n_devices // 4}
        mesh4 = make_mesh(axes4, devices=devs)
        if cfg.n_layers % axes4["pp"] == 0:
            state = init_train_state(jax.random.PRNGKey(0), cfg, mesh4,
                                     optimizer)
            step4 = make_pp_train_step(cfg, mesh4, optimizer,
                                       microbatches=2, attn="flash")
            tokens_host = np.random.default_rng(6).integers(
                0, cfg.vocab, size=(4, 64), dtype=np.int32)
            # sequence-sharded delivery through the real data path
            tokens, eng_desc = _deliver_tokens(tokens_host, mesh4,
                                               P(None, "sp"), "python")
            state, metrics = step4(state, tokens)
            loss4 = float(metrics["loss"])
            assert np.isfinite(loss4), f"non-finite tp×sp×pp loss {loss4}"
            print(f"dryrun ok: mesh={axes4} (tp×sp×pp, flash ring in-pipe), "
                  f"loss={loss4:.4f}, engine={eng_desc}")

    # Composed 3-axis mesh: dp×tp×sp — ring×flash attention over sp with
    # tp-sharded heads (n_kv_heads divides tp) and dp-sharded batch, all in
    # one step: the full parallelism composition the loaders must feed.
    if n_devices >= 8 and n_devices % 4 == 0:
        axes3 = {"dp": n_devices // 4, "tp": 2, "sp": 2}
        mesh3 = make_mesh(axes3, devices=devs)
        state = init_train_state(jax.random.PRNGKey(0), cfg, mesh3, optimizer)
        step3 = make_train_step(cfg, mesh3, optimizer, sp=True, attn="flash")
        B = 2 * axes3["dp"]
        tokens_host = np.random.default_rng(3).integers(
            0, cfg.vocab, (B, 64), dtype=np.int32)
        # delivery-fed, sequence-sharded, production engine (VERDICT.md r4
        # next #4): the full dp×tp×sp composition eats a planner-delivered
        # P("dp","sp") batch off the C++ engine
        tokens, eng_desc = _deliver_tokens(tokens_host, mesh3,
                                           P("dp", "sp"), "auto")
        state, metrics = step3(state, tokens)
        loss3 = float(metrics["loss"])
        assert np.isfinite(loss3), f"non-finite 3-axis loss {loss3}"
        print(f"dryrun ok: mesh={axes3} (dp×tp×sp ring×flash), "
              f"loss={loss3:.4f}, engine={eng_desc}")

    # Llama-3-8B at its REAL shape (BASELINE.json:10 names Llama-3-8B; every
    # executed config above runs tiny shapes — VERDICT.md r3 next #7): lower
    # the full sharded train step on the virtual mesh. Lowering only — no
    # execution, no 16GiB of parameters materialized: the state is abstract
    # ShapeDtypeStructs carrying the real dp×tp×sp shardings.
    if n_devices >= 8 and n_devices % 4 == 0:
        from functools import partial

        from strom.models.llama import init_params
        from strom.parallel.sharding import param_shardings
        from strom.parallel.train import TrainState

        cfg8 = LlamaConfig.llama3_8b()
        n_params = cfg8.param_count()
        assert n_params == 8_030_261_248, n_params  # the 8B family size
        mesh8 = make_mesh({"dp": n_devices // 4, "tp": 2, "sp": 2},
                          devices=devs)
        shapes = jax.eval_shape(partial(init_params, cfg=cfg8),
                                jax.random.key(0))
        shardings8 = param_shardings(shapes, mesh8)
        # spot-check the Megatron column/row pairs landed on tp at 8B shapes
        wq_spec = shardings8["layers"]["wq"].spec
        wo_spec = shardings8["layers"]["wo"].spec
        assert "tp" in wq_spec and "tp" in wo_spec, (wq_spec, wo_spec)
        assert wq_spec.index("tp") == 2 and wo_spec.index("tp") == 1, \
            "column-parallel wq must split its output dim, row-parallel wo its input dim"
        params_s = jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            shapes, shardings8)
        opt_s = jax.eval_shape(optimizer.init, params_s)
        state_s = TrainState(params=params_s, opt_state=opt_s,
                             step=jax.ShapeDtypeStruct((), jnp.int32))
        step8 = make_train_step(cfg8, mesh8, optimizer, attn="flash", sp=True)
        toks_s = jax.ShapeDtypeStruct(
            (2 * (n_devices // 4), 4096), jnp.int32,
            sharding=NamedSharding(mesh8, P("dp", "sp")))
        lowered = step8.lower(state_s, toks_s)
        assert lowered.as_text()  # the HLO exists; compilation is the pods' job
        print(f"dryrun ok: Llama-3-8B real shape lowered on "
              f"{dict(dp=n_devices // 4, tp=2, sp=2)} "
              f"(params={n_params:,}, seq=4096, ring×flash, lowering only)")

    # 16/32-device lowering (VERDICT.md r4 next #5): this process's backend
    # is pinned at n_devices, so the bigger virtual meshes run in a
    # subprocess that forces its own device count. Lowering-only — catches
    # axis-factorization and sharding-spec bugs the 8-device shape can't
    # express (e.g. dp×tp×sp×pp all ≥2 at once). STROM_DRYRUN_AT_SCALE=0
    # opts out: the pytest suite reaches run_dryrun(8) through the driver
    # entry and must not pay a second jax cold-start + an 8B pp lowering
    # on the 1-core box (conftest sets it; the driver leaves it on).
    if n_devices >= 8 and os.environ.get("STROM_DRYRUN_AT_SCALE", "1") != "0":
        import subprocess
        import sys

        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        # mirror the __main__ entry: strip only the device-count flag and
        # keep any other inherited XLA_FLAGS (a wholesale overwrite would
        # drop e.g. a caller's memory/debug flags — ADVICE.md r5)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=32")
        env["XLA_FLAGS"] = " ".join(flags)
        env["JAX_PLATFORMS"] = "cpu"
        res = subprocess.run(
            [sys.executable, "-m", "strom.parallel.dryrun",
             "--lower-at-scale"],
            env=env, capture_output=True, text=True, timeout=900,
            cwd=repo_root)
        sys.stdout.write(res.stdout)
        if res.returncode != 0:
            raise RuntimeError(
                f"--lower-at-scale subprocess failed (rc={res.returncode}):\n"
                f"{res.stderr[-2000:]}")

    # measured multi-process ingest (ISSUE 15): the MULTICHIP artifact
    # graduates from "lowered OK" to a MEASURED 2-process data-plane rate
    # with a peer-hit ratio — jax-free worker subprocesses (host-mode
    # assembly), so this costs seconds, not two jax cold-starts. The line
    # is parsed out of the artifact tail by tools/bench_sentinel.py
    # (load_multichip); any failure prints "dist skipped" instead of
    # sinking the lowering sweep. STROM_DRYRUN_DIST=0 opts out (the
    # pytest suite path — tests/test_dist.py covers the plane directly).
    if os.environ.get("STROM_DRYRUN_DIST", "1") != "0":
        import tempfile as _tempfile

        try:
            from strom.dist.launch import measure_ingest

            with _tempfile.TemporaryDirectory() as dwd:
                dres = measure_ingest(2, dwd, steps=4, batch=8,
                                      seq_len=64, timeout_s=120)
            print(f"dist ok: procs={dres['dist_procs']} "
                  f"items_per_s={dres['dist_items_per_s']} "
                  f"peer_hit_ratio={dres['dist_peer_hit_ratio']} "
                  f"(engine_ingest_bytes={dres['dist_engine_ingest_bytes']}"
                  f", bit_identical={dres['dist_ok']})"
                  if dres.get("dist_ok") else
                  f"dist skipped: workers diverged "
                  f"({[w.get('rc') for w in dres.get('workers', [])]})")
        # stromlint: ignore[swallowed-exceptions] -- the printed "dist
        # skipped" line IS the error marker: it lands in the MULTICHIP
        # artifact tail the sentinel reads, which is this entry point's
        # whole observability surface (no live registry outlives the run)
        except Exception as e:  # advisory: never sink the lowering sweep
            print(f"dist skipped: {type(e).__name__}: {e}")


def lower_at_scale() -> None:
    """Lowering-only validation past the executed matrix's 8 devices
    (VERDICT.md r4 next #5): the Llama-3-8B training step on a 16-device
    dp×tp×sp×pp mesh (every axis ≥ 2 simultaneously — the composition an
    8-device mesh cannot factor) and the scan-mesh all-reduce on 32
    devices. No execution, no parameters materialized: abstract state with
    the real shardings, exactly like run_dryrun's 8B section."""
    import math
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from strom.models.llama import LlamaConfig, init_params
    from strom.parallel.mesh import make_mesh
    from strom.parallel.pipeline import make_pp_train_step
    from strom.parallel.sharding import param_shardings
    from strom.parallel.train import TrainState, make_optimizer

    devs = jax.devices()
    if len(devs) < 32:
        raise RuntimeError(f"lower_at_scale needs 32 virtual devices, "
                           f"have {len(devs)}")

    # Llama-3-8B pipelined step on dp×tp×sp×pp at 16 devices
    cfg8 = LlamaConfig.llama3_8b()
    assert cfg8.param_count() == 8_030_261_248
    axes16 = {"dp": 2, "tp": 2, "sp": 2, "pp": 2}
    assert math.prod(axes16.values()) == 16  # axis factorization
    mesh16 = make_mesh(axes16, devices=devs[:16])
    optimizer = make_optimizer()
    shapes = jax.eval_shape(partial(init_params, cfg=cfg8),
                            jax.random.key(0))
    shardings16 = param_shardings(shapes, mesh16)
    # the Megatron pairs AND the pipeline stage split must all land: wq
    # column-parallel (tp on its output dim) with pp on the stacked-layer
    # dim; wo row-parallel (tp on its input dim)
    wq_spec = shardings16["layers"]["wq"].spec
    wo_spec = shardings16["layers"]["wo"].spec
    assert wq_spec.index("pp") == 0 and wq_spec.index("tp") == 2, wq_spec
    assert wo_spec.index("pp") == 0 and wo_spec.index("tp") == 1, wo_spec
    params_s = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings16)
    opt_s = jax.eval_shape(optimizer.init, params_s)
    state_s = TrainState(params=params_s, opt_state=opt_s,
                         step=jax.ShapeDtypeStruct((), jnp.int32))
    step16 = make_pp_train_step(cfg8, mesh16, optimizer, microbatches=2,
                                attn="flash")
    toks_s = jax.ShapeDtypeStruct(
        (4, 4096), jnp.int32,
        sharding=NamedSharding(mesh16, P("dp", "sp")))
    lowered = step16.lower(state_s, toks_s)
    assert lowered.as_text()
    print(f"dryrun ok: Llama-3-8B lowered on {axes16} (16 devices, "
          f"pp pipeline + ring×flash over sp, lowering only)")

    # scan-mesh collective reducer at 32 devices (the parquet fan-out's
    # cross-pod all-reduce, pipelines/parquet_scan._mesh_reducer)
    from strom.pipelines.parquet_scan import _mesh_reducer

    mesh32 = jax.sharding.Mesh(np.asarray(devs[:32]), ("scan",))
    reducer = _mesh_reducer(mesh32)
    part_s = jax.ShapeDtypeStruct(
        (32, 8), jnp.float32,
        sharding=NamedSharding(mesh32, P("scan", None)))
    lowered = reducer.lower(part_s)
    assert lowered.as_text()
    print("dryrun ok: scan-mesh all-reduce lowered on 32 devices "
          "(replicated out_sharding, lowering only)")


if __name__ == "__main__":
    import sys

    if "--lower-at-scale" in sys.argv:
        # standalone-safe: force the 32-device CPU backend ourselves. The
        # env alone is NOT enough — the sandbox re-pins JAX_PLATFORMS=axon
        # at interpreter startup, so the config update (before any backend
        # touch; this module imports no jax at module level) must win. An
        # INHERITED device-count flag (e.g. pytest's 8) is replaced, not
        # kept — this entry point means 32.
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=32")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        import jax

        jax.config.update("jax_platforms", "cpu")
        lower_at_scale()
    else:
        run_dryrun(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
