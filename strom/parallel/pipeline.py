"""GPipe-style pipeline parallelism over a mesh "pp" axis.

TPU-first mechanics, same recipe as the ring (SURVEY.md §7.1: pick a mesh,
annotate shardings, let XLA place collectives): the stacked layer params'
leading axis is sharded over "pp" (strom.parallel.sharding), so each stage
holds n_layers/pp contiguous layers. Inside one `shard_map`, the batch
splits into M microbatches and a `lax.scan` over M + pp − 1 ticks pumps them
through the stages — each tick runs the local layer stack and rotates the
activation one hop with `lax.ppermute` (neighbor ICI traffic, like the
ring's kv rotation). The backward is plain autodiff through the scan:
ppermute's transpose is the reverse rotation, so gradient activations flow
backward through the pipe with no custom vjp.

Simplifications (documented honestly):
- fill/drain bubbles and non-edge stages' embed/head computations run and
  are discarded via `where` masks — the uniform program keeps the scan body
  compiled once; a production schedule (1F1B, interleaved stages) would
  mask compute with `lax.cond`, not reduce the algorithmic bubble.
- microbatching is over the BATCH dim, so every microbatch is a full
  sequence and RoPE/causality are untouched.

Composes with dp (batch axis), tp and sp on the same mesh — sharding inside
shard_map is explicit, so each composition is manual:
- "tp": the stage body switches to :func:`_block_tp`, the Megatron block
  with MANUAL collectives — column-split qkv/gate/up, row-split wo/down,
  and the two psums closing each pair;
- "sp": activations stay sequence-sharded inside every stage, attention
  runs the ring (dense / flash / zigzag local bodies called directly —
  we're already inside shard_map), RoPE positions offset per shard, and
  next-token targets cross shard boundaries via one neighbor ppermute;
embed/lm_head stay replicated inside the pipe (every stage runs them,
edge-masked). The loss is exactly next_token_loss's: a pp step and a plain
step on the same params/tokens agree to float tolerance (tested through
dp×tp×pp, dp×sp×pp with all three ring impls, and tp×sp×pp).

The reference has no compute parallelism at all (SURVEY.md §2.3); this
exists because the build brief's multichip validation names tp/pp/dp/sp/ep
as first-class shardings the data path must feed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from strom.models.llama import (LlamaConfig, attention, block, init_params,
                                rmsnorm, rope)
from strom.parallel.sharding import param_specs
from strom.parallel.train import TrainState


def _block_tp(x, lp, cfg: LlamaConfig, positions, attn_fn, tp_axis: str):
    """Megatron block with MANUAL tensor parallelism for use inside
    shard_map (where sharding is explicit): lp's matmul weights arrive
    tp-sharded — wq/wk/wv/w_gate/w_up column-split (local output dims),
    wo/w_down row-split — so activations stay full-width and the only
    collectives are the two psums closing each column→row pair. Local heads
    attend independently (GQA ratio preserved: both n_heads and n_kv_heads
    divide by tp)."""
    tp = lax.axis_size(tp_axis)
    nh, nkv, hd = cfg.n_heads // tp, cfg.n_kv_heads // tp, cfg.head_dim
    B, S, _ = x.shape

    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = rope((h @ lp["wq"]).reshape(B, S, nh, hd), positions, cfg.rope_theta)
    k = rope((h @ lp["wk"]).reshape(B, S, nkv, hd), positions, cfg.rope_theta)
    v = (h @ lp["wv"]).reshape(B, S, nkv, hd)
    attn = (attn_fn or attention)(q, k, v)
    x = x + lax.psum(attn.reshape(B, S, nh * hd) @ lp["wo"], tp_axis)

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
    return x + lax.psum(gated @ lp["w_down"], tp_axis)


def make_pp_train_step(cfg: LlamaConfig, mesh: Mesh,
                       optimizer: optax.GradientTransformation, *,
                       microbatches: int | None = None,
                       attn: str = "dense", donate: bool = True):
    """Compile a pipelined (state, tokens) -> (state, metrics) step.

    tokens arrive P("dp", "sp") — batch on dp, sequence on sp when those
    axes exist, replicated over pp — the same batches the strom loaders
    deliver. microbatches defaults to 2×pp (bubble fraction
    (pp−1)/(M+pp−1)); the local batch must divide by it.
    """
    if "pp" not in mesh.axis_names:
        raise ValueError("make_pp_train_step needs a 'pp' mesh axis")
    has_tp = "tp" in mesh.axis_names
    if has_tp:
        tp = mesh.shape["tp"]
        if cfg.n_heads % tp or cfg.n_kv_heads % tp or cfg.d_ff % tp:
            raise ValueError(
                f"n_heads {cfg.n_heads}, n_kv_heads {cfg.n_kv_heads} and "
                f"d_ff {cfg.d_ff} must divide by tp {tp} for manual tensor "
                "parallelism inside the pipelined step")
    n_stage = mesh.shape["pp"]
    if cfg.n_layers % n_stage:
        raise ValueError(f"n_layers {cfg.n_layers} must divide by pp {n_stage}")
    M = microbatches if microbatches is not None else max(2 * n_stage, 2)
    if M < 1:
        raise ValueError(f"microbatches must be >= 1, got {M}")
    has_dp = "dp" in mesh.axis_names
    has_sp = "sp" in mesh.axis_names
    tok_spec = P("dp" if has_dp else None, "sp" if has_sp else None)

    if attn not in ("dense", "flash", "zigzag"):
        raise ValueError(
            f"attn must be 'dense', 'flash' or 'zigzag', got {attn!r}")
    if attn == "zigzag" and not has_sp:
        raise ValueError("attn='zigzag' is a ring variant; it needs an 'sp' "
                         "mesh axis")
    attn_fn = None
    if has_sp:
        # sequence parallelism INSIDE each pipeline stage: activations stay
        # sp-sharded and attention runs the ring over the sp axis (we are
        # already inside shard_map, so take the local ring body directly)
        from strom.parallel.ring import make_ring_attention_local

        attn_fn = make_ring_attention_local(attn, axis="sp")
    elif attn == "flash":
        from strom.ops.flash_attention import make_flash_attention

        attn_fn = make_flash_attention()

    # manual sharding covers the pipeline axis everywhere, plus tp on the
    # LAYER matmuls (the _block_tp collectives close those). embed/lm_head
    # stay replicated inside the pipe: every stage runs them (discarded off
    # the edge stages), so a tp-sharded vocab dim would need its own
    # gather/psum plumbing for no bubble-math benefit. On tp meshes whose
    # params were initialized tp-sharded, jit inserts the entry all-gather.
    def restrict_layers(spec: P) -> P:
        # keep pp always; keep tp only when the mesh has a tp axis
        return P(*(ax if ax == "pp" or (ax == "tp" and has_tp) else None
                   for ax in spec))

    def restrict_edge(spec: P) -> P:
        return P(*(ax if ax == "pp" else None for ax in spec))

    shapes = jax.eval_shape(partial(init_params, cfg=cfg), jax.random.key(0))
    base_specs = param_specs(shapes)
    is_p = lambda x: isinstance(x, P)  # noqa: E731
    pspecs = {
        k: jax.tree.map(restrict_layers if k == "layers" else restrict_edge,
                        v, is_leaf=is_p)
        for k, v in base_specs.items()
    }

    if has_tp:
        blk = jax.checkpoint(partial(_block_tp, tp_axis="tp"),
                             static_argnums=(2, 4))
    else:
        blk = jax.checkpoint(block, static_argnums=(2, 4))

    def pp_loss_local(params, tokens):
        # params["layers"] leaves carry this stage's n_layers/pp layers
        stage = lax.axis_index("pp")
        Bl, S = tokens.shape
        if Bl % M:
            raise ValueError(f"local batch {Bl} must divide by "
                             f"microbatches {M}")
        mb = Bl // M
        toks_mb = tokens.reshape(M, mb, S)
        # S here is the LOCAL sequence slice; absolute positions offset by
        # this sp shard's start (RoPE + ring causality both key on them)
        pos0 = lax.axis_index("sp") * S if has_sp else 0
        sp_n = lax.axis_size("sp") if has_sp else 1
        positions = jnp.broadcast_to(pos0 + jnp.arange(S, dtype=jnp.int32),
                                     (mb, S))
        dt = cfg.jdtype
        if has_sp:
            # cross-shard next-token targets: fetch every microbatch's
            # NEXT-shard first token with ONE neighbor ppermute, hoisted out
            # of the tick scan (per-tick permutes would issue M+pp−1
            # collectives for static data)
            nxt_mb = lax.ppermute(toks_mb[:, :, :1], "sp",
                                  [(i, (i - 1) % sp_n) for i in range(sp_n)])

        def stage_fwd(x):
            def body(c, lp):
                return blk(c, lp, cfg, positions, attn_fn), None

            y, _ = lax.scan(body, x, params["layers"])
            return y

        def tick(carry, t):
            recv, loss_sum = carry
            # stage 0 injects microbatch t (clipped garbage past the fill)
            toks_in = toks_mb[jnp.clip(t, 0, M - 1)]
            x0 = params["embed"][toks_in].astype(dt)
            x = jnp.where(stage == 0, x0, recv)
            y = stage_fwd(x)
            # the LAST stage completes microbatch t − (pp−1) this tick
            m_out = t - (n_stage - 1)
            toks_out = toks_mb[jnp.clip(m_out, 0, M - 1)]
            logits = (rmsnorm(y, params["final_norm"], cfg.norm_eps)
                      @ params["lm_head"]).astype(jnp.float32)
            if has_sp:
                # stitch the pre-fetched next-shard first token on; only the
                # globally-last column has no target
                nxt = nxt_mb[jnp.clip(m_out, 0, M - 1)]
                targets = jnp.concatenate([toks_out[:, 1:], nxt], axis=1)
                is_last_shard = lax.axis_index("sp") == sp_n - 1
                mask = jnp.where(is_last_shard,
                                 (jnp.arange(S) < S - 1), True
                                 ).astype(jnp.float32)
            else:
                targets = jnp.roll(toks_out, -1, axis=1)
                mask = (jnp.arange(S) < S - 1).astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, targets[..., None],
                                       axis=-1)[..., 0]
            l = jnp.sum((logz - gold) * mask)
            valid = jnp.logical_and(stage == n_stage - 1,
                                    jnp.logical_and(m_out >= 0, m_out < M))
            loss_sum = loss_sum + jnp.where(valid, l, 0.0)
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            return (lax.ppermute(y, "pp", perm), loss_sum), None

        recv0 = jnp.zeros((mb, S, cfg.d_model), dt)
        (_, loss_sum), _ = lax.scan(tick, (recv0, jnp.float32(0.0)),
                                    jnp.arange(M + n_stage - 1))
        loss = lax.psum(loss_sum, "pp")  # only the last stage contributed
        b_total = Bl
        s_total = S
        if has_sp:
            loss = lax.psum(loss, "sp")  # per-shard partial sums
            s_total = S * sp_n
        if has_dp:
            loss = lax.psum(loss, "dp")
            b_total = Bl * lax.axis_size("dp")
        return loss / (b_total * (s_total - 1))

    loss_fn = partial(jax.shard_map, mesh=mesh,
                      in_specs=(pspecs, tok_spec), out_specs=P(),
                      check_vma=False)(pp_loss_local)

    def step(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
        return TrainState(new_params, new_opt, state.step + 1), metrics

    donate_argnums = (0,) if donate else ()
    return jax.jit(step,
                   in_shardings=(None, NamedSharding(mesh, tok_spec)),
                   donate_argnums=donate_argnums)
