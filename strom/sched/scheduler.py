"""Shared I/O scheduler: many tenants, one engine fleet (ISSUE 7 tentpole).

Before this module, a `StromContext` assumed one consumer: every gather
took the delivery engine lock for its whole duration (`StreamingGather`
held it construction→finish), so a second pipeline's 2KB metadata read
queued behind a first pipeline's 100MB epoch gather. The paper frames the
DMA engine as a *shared, kernel-managed* resource — per-process locks are
exactly what it replaces — and PR 5's async `submit_vectored`/`poll` plus
PR 6's tenant-labeled telemetry are the substrate a shared arbiter needs.

:class:`IoScheduler` is that arbiter. The per-transfer engine lock stops
existing for scheduled contexts; in its place:

- **Per-tenant queues, priority classes.** Tenants register (or are
  auto-registered on first use); each grant request enters its tenant's
  FIFO. Classes are strict among budget-ready work — ``interactive`` >
  ``training`` > ``background`` — so a live client's op never waits out
  training backlog, and readahead (always ``background``) never delays
  either. A class whose every queued tenant is budget-throttled yields
  the engine to lower classes rather than idling it (work conservation);
  it is picked first again the moment its budget refills.

- **Weighted fair drain (deficit round-robin over queued ops).** Within
  a class, the tenant furthest *behind* its weighted fair share drains
  next: every grant charges ``nbytes / weight`` of virtual service time,
  and ``_pick_locked`` always picks the queued tenant with the minimum.
  A newly-active tenant joins at the current service baseline (no
  infinite catch-up), which is DRR with byte quanta in its
  limit: a weight-2 tenant gets 2 bytes drained for every 1 of a
  weight-1 tenant, and a light tenant's deficit keeps it at the head.

- **Engine queue-depth slots as the shared currency.** Exclusive grants
  hand the engine's whole in-flight window to one request at a time, and
  the delivery layer splits big gathers into slices of a few in-flight
  budgets (``sched_slice_bytes``, see :meth:`read_chunks`) so ownership
  turns over every few queue-depth windows — a greedy tenant's gather is
  preemptible at slice boundaries, bounding any other tenant's queue
  wait at ~one slice instead of one epoch. Engines that already
  arbitrate internally (``concurrent_gathers``: the multi-ring engine's
  per-ring locks) keep their concurrency: grants there are
  non-exclusive — budgets and accounting still apply, queueing doesn't.

- **Budgets + admission control** (:mod:`strom.sched.budget`): byte/IOPS
  token buckets peeked while picking (a throttled tenant is skipped, not
  billed) and taken at grant; slab-pool admission queues background
  allocations while the pool is past the high-water mark.

Observability: every grant lands ``sched_granted_ops/bytes`` and a
``sched_queue_wait_us`` histogram in the tenant's scope (labeled on
/metrics, PR 6) plus the unlabeled aggregate; ``sched_throttle_waits``
counts throttled grant episodes (one per grant that waited on budget
refill); the live server's ``/tenants`` route renders
:meth:`tenants_info`.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Sequence

from strom.engine.base import DeadlineExceeded
from strom.utils.locks import make_condition
from strom.sched.budget import AdmissionGate
from strom.sched.tenant import PRIORITIES, PRIORITY_ORDER, Tenant

# bench-JSON column suffixes the multitenant bench arm emits per tenant
# (cli.py bench_multitenant, prefixed mt_<tenant>_), single-sourced so the
# driver's copy loop (bench.py) and the compare_rounds "multi-tenant"
# section cannot drift from the producer — the same contract STALL_FIELDS /
# CACHE_BENCH_FIELDS / STREAM_FIELDS enforce.
SCHED_FIELDS = (
    "items_per_s",
    "vs_solo",
    "sched_queue_wait_p50_us",
    "sched_queue_wait_p99_us",
    "sched_granted_ops",
    "sched_granted_bytes",
    "sched_throttle_waits",
    "engine_op_lat_p99_us",
)

_DEFAULT_TENANT = "default"


class _Waiter:
    """One queued grant request (scheduler-lock-owned)."""

    __slots__ = ("tenant", "nbytes", "prio", "enq_t", "granted", "wait_s",
                 "throttled", "owner_ident")

    def __init__(self, tenant: Tenant, nbytes: int, prio: int, enq_t: float):
        self.tenant = tenant
        self.nbytes = nbytes
        self.prio = prio
        self.enq_t = enq_t
        self.granted = False
        self.wait_s = 0.0
        # one sched_throttle_waits tick per throttled grant EPISODE: the
        # flag keeps repeated dispatch passes / poll ticks over the same
        # still-throttled head-of-queue from re-counting it
        self.throttled = False
        # thread that ACQUIRED the grant (release may land on another — a
        # streamed gather releases at drain on the pump side): the key the
        # held_by_me() re-entrancy probe charges/refunds (ISSUE 14)
        self.owner_ident = 0


class IoScheduler:
    """Fair arbiter over one engine's transfer path.

    *clock* / throttle waiting are injectable for deterministic tests.
    """

    def __init__(self, engine, config, *, pool=None, scope=None,
                 clock: Callable[[], float] = time.monotonic):
        from strom.utils.stats import global_stats

        self.engine = engine
        self.config = config
        self._scope = scope if scope is not None else global_stats
        self._clock = clock
        # engines with internal per-ring arbitration keep their concurrency:
        # grants are non-exclusive there (budgets/accounting still apply)
        self.exclusive = not getattr(engine, "concurrent_gathers", False)
        # live slice-size override (ISSUE 16 autotuner); None = config/auto
        self.slice_bytes_override: int | None = None
        self._cond = make_condition("sched.arbiter")
        self._tenants: dict[str, Tenant] = {}
        self._current: _Waiter | None = None
        # grants outstanding per ACQUIRING thread ident (ISSUE 14): the
        # re-entrancy probe the engine-routed spill I/O consults before
        # queueing — a thread already holding (or working under) a grant
        # must never enqueue a nested one (self-deadlock on an exclusive
        # engine). Cross-thread releases refund the acquirer's entry.
        self._held_by: dict[int, int] = {}
        # service baseline: a tenant going active joins at this vtime, so
        # an idle tenant can't bank unbounded credit (classic WFQ rule)
        self._vbase = 0.0
        self.admission = AdmissionGate(
            pool, getattr(config, "sched_high_water", 0.9),
            scope=self._scope, clock=clock)
        # SLO hook (ISSUE 8): a callable name -> bool set by the owning
        # context (ctx.slo.burning) so /tenants rows flag tenants that are
        # burning their error budget — the scheduler stays SLO-agnostic
        self.slo_hook: "Callable[[str], bool] | None" = None
        # resilience hook (ISSUE 9): a callable () -> dict set by the
        # owning context (ctx.resilience.stats) so /tenants shows the
        # breaker/failover degraded state next to the queue rows — the
        # scheduler stays failure-policy-agnostic
        self.resilience_info: "Callable[[], dict] | None" = None
        self._default = self.register(_DEFAULT_TENANT, _label=False)

    # -- tenant registry ----------------------------------------------------
    def register(self, name: str, *, priority: str = "training",
                 weight: int = 1, byte_rate: float = 0,
                 byte_burst: float | None = None, iops: float = 0,
                 hot_cache_bytes: int = 0, _label: bool = True) -> Tenant:
        """Register (or fetch) tenant *name*. Re-registering an existing
        name returns the live handle unchanged — queue state and budget
        balances survive, so a daemon client reconnecting can't zero a
        tenant's debt. ``_label=False`` keeps the context's own scope
        (the default tenant: single-tenant metrics stay unlabeled)."""
        with self._cond:
            t = self._tenants.get(name)
            if t is not None:
                return t
            scope = self._scope.scoped(tenant=name) if _label else self._scope
            t = Tenant(name, priority=priority, weight=weight, scope=scope,
                       byte_rate=byte_rate, byte_burst=byte_burst, iops=iops,
                       hot_cache_bytes=hot_cache_bytes, clock=self._clock)
            t.vtime = self._vbase
            self._tenants[name] = t
            return t

    def is_registered(self, name: str) -> bool:
        with self._cond:
            return name in self._tenants

    def tenant(self, name: str | None = None) -> Tenant:
        if name is None:
            return self._default
        with self._cond:
            t = self._tenants.get(name)
        # auto-register on first use: a pipeline labeled tenant="t7" just
        # works (default class/weight, no budgets); explicit register()
        # beforehand is how budgets/priorities are customized
        return t if t is not None else self.register(name)

    def resolve(self, tenant: "Tenant | str | None") -> Tenant:
        if isinstance(tenant, Tenant):
            return tenant
        return self.tenant(tenant)

    def tenants_info(self) -> dict:
        """{name: row} for every registered tenant plus the admission
        gate's state — the /tenants route body."""
        with self._cond:
            tenants = list(self._tenants.values())
        rows = {}
        for t in tenants:
            row = t.info()
            if self.slo_hook is not None:
                # burn-rate flag from the SLO engine (ISSUE 8): a throttled
                # / slow tenant is visible where the operator already looks
                with contextlib.suppress(Exception):
                    row["slo_burning"] = bool(self.slo_hook(t.name))
            rows[t.name] = row
        out = {"tenants": rows,
               "admission": self.admission.state(),
               "exclusive": self.exclusive,
               "engine": getattr(self.engine, "name", "?")}
        if self.resilience_info is not None:
            # degraded-state visibility (ISSUE 9): breaker state, failover
            # availability and hedge threshold, on the page the operator
            # already watches for tenant health
            with contextlib.suppress(Exception):
                out["resilience"] = self.resilience_info()
        return out

    # -- the fair-drain core ------------------------------------------------
    def _enqueue_locked(self, w: _Waiter) -> None:
        """Append a waiter to its tenant's queue. A tenant (re)activating
        from idle joins at the current service baseline — idle time banks
        no credit (the WFQ start-time rule): deficit accrues only while
        queued, so a long-idle tenant can't return and monopolize."""
        t = w.tenant
        if not t.queue and not t.active and t.vtime < self._vbase:
            t.vtime = self._vbase
        t.queue.append(w)
        t.queued_bytes += w.nbytes

    def _pick_locked(self) -> tuple[_Waiter | None, float | None]:
        """(next grantable waiter, earliest budget-ready delay). Strict
        priority between classes; min virtual service time (weighted fair /
        deficit) within one. Budgets are PEEKED here — a throttled tenant
        is skipped this pass and its ready time bounds the retry wait —
        and taken only by the caller for the waiter actually granted."""
        min_delay: float | None = None
        for cls in range(len(PRIORITIES)):
            cand = [t for t in self._tenants.values()
                    if t.queue and t.queue[0].prio == cls]
            # furthest behind its weighted share first
            for t in sorted(cand, key=lambda t: (t.vtime, t.name)):
                w = t.queue[0]
                d = max(t.byte_bucket.peek(w.nbytes),
                        t.iops_bucket.peek(1))
                if d > 0:
                    self._note_throttled_locked(w)
                    min_delay = d if min_delay is None else min(min_delay, d)
                    continue
                return w, min_delay
            # every queued tenant of this class is budget-throttled: fall
            # through to the next class. Strict priority orders RUNNABLE
            # work; a budget-exhausted class must not idle the engine while
            # ready lower-class work queues (work conservation). min_delay
            # bounds the dispatch retry, so the moment the budget refills
            # the higher class is picked first again.
        return None, min_delay

    @staticmethod
    def _note_throttled_locked(w: _Waiter) -> None:
        """Count a throttled grant episode exactly once per waiter —
        sched_throttle_waits is a bench column (SCHED_FIELDS) compared
        round-over-round, so it must measure budget pressure, not how many
        dispatch passes happened to observe it."""
        if w.throttled:
            return
        w.throttled = True
        w.tenant.throttle_waits += 1
        w.tenant.scope.add("sched_throttle_waits")

    def _commit_grant_locked(self, w: _Waiter) -> None:
        """Grant bookkeeping shared by the exclusive dispatcher and the
        non-exclusive (internally-arbitrated engine) path: dequeue, take
        the budgets peeked earlier, charge weighted virtual service (the
        global baseline tracks the max so newly-active tenants join behind
        nobody), count."""
        t = w.tenant
        t.queue.popleft()
        t.queued_bytes -= w.nbytes
        t.byte_bucket.take(w.nbytes)
        t.iops_bucket.take(1)
        t.vtime += w.nbytes / t.weight
        if t.vtime > self._vbase:
            self._vbase = t.vtime
        t.active += 1
        t.granted_ops += 1
        t.granted_bytes += w.nbytes
        w.granted = True

    def _dispatch_locked(self) -> float | None:
        """Grant the next waiter if the engine is free. Returns the retry
        delay when everything grantable is budget-throttled."""
        if self._current is not None:
            return None
        w, delay = self._pick_locked()
        if w is None:
            return delay
        self._commit_grant_locked(w)
        self._current = w
        self._cond.notify_all()
        return None

    def acquire(self, tenant: "Tenant | str | None" = None,
                nbytes: int = 0, *, priority: str | None = None) -> _Waiter:
        """Queue for (and block until) an engine grant. Returns the waiter
        handle to pass to :meth:`release`. Non-exclusive engines grant
        immediately (budgets still charged, waits still possible)."""
        from strom.obs import request as _request
        from strom.obs.events import ring as _ring

        t = self.resolve(tenant)
        prio = PRIORITY_ORDER[priority] if priority is not None \
            else PRIORITY_ORDER[t.priority]
        w = _Waiter(t, max(int(nbytes), 0), prio, self._clock())
        enq_us = _ring.now_us()
        # deadline propagation (ISSUE 9): a queue wait that cannot grant
        # before the request deadline dequeues and fails fast — a gather
        # nobody is still waiting for must not consume a grant. Deadlines
        # ride time.monotonic (the engine's clock), not the injectable
        # scheduler clock — fake-clock tests don't mint deadlines.
        req0 = _request.current()
        req_deadline = getattr(req0, "deadline", None) \
            if req0 is not None else None

        def _expired() -> bool:
            return req_deadline is not None \
                and time.monotonic() >= req_deadline

        def _abort_locked() -> None:
            try:
                t.queue.remove(w)
                t.queued_bytes -= w.nbytes
            except ValueError:
                pass
            t.scope.set_gauge("sched_queue_depth", len(t.queue))
            t.scope.add("deadline_exceeded")
            self._cond.notify_all()

        with self._cond:
            self._enqueue_locked(w)
            t.scope.set_gauge("sched_queue_depth", len(t.queue))
            if not self.exclusive:
                # internal-arbitration engines: charge budgets in queue
                # order but don't serialize — budget throttles still wait
                while t.queue[0] is not w or \
                        max(t.byte_bucket.peek(w.nbytes),
                            t.iops_bucket.peek(1)) > 0:
                    if _expired():
                        _abort_locked()
                        raise DeadlineExceeded(
                            f"queued on tenant '{t.name}' (throttled)")
                    if t.queue[0] is w:
                        d = max(t.byte_bucket.peek(w.nbytes),
                                t.iops_bucket.peek(1))
                        self._note_throttled_locked(w)
                        self._cond.wait(min(d, 0.05))
                    else:
                        self._cond.wait(0.01)
                self._commit_grant_locked(w)
                self._cond.notify_all()
            else:
                delay = self._dispatch_locked()
                while self._current is not w:
                    if _expired():
                        _abort_locked()
                        raise DeadlineExceeded(
                            f"queued on tenant '{t.name}' behind "
                            f"{len(t.queue)} op(s)")
                    wait_s = delay
                    if req_deadline is not None:
                        left = max(req_deadline - time.monotonic(), 0.001)
                        wait_s = left if wait_s is None \
                            else min(wait_s, left)
                    self._cond.wait(wait_s)
                    delay = self._dispatch_locked()
            t.scope.set_gauge("sched_queue_depth", len(t.queue))
            w.owner_ident = threading.get_ident()
            self._held_by[w.owner_ident] = \
                self._held_by.get(w.owner_ident, 0) + 1
        w.wait_s = max(self._clock() - w.enq_t, 0.0)
        t.scope.observe_us("sched_queue_wait", w.wait_s * 1e6)
        t.scope.add("sched_granted_ops")
        if w.nbytes:
            t.scope.add("sched_granted_bytes", w.nbytes)
        # causal request tracing (ISSUE 8): the queue wait becomes a span
        # on the request's lane (throttled verdict included — the exemplar
        # store and SLO engine key off it), billed to the request that
        # queued, not just the tenant aggregate
        req = _request.current()
        if req is not None:
            req.note_queue_wait(w.wait_s * 1e6, throttled=w.throttled)
            req.record("sched.queue", "sched", enq_us,
                       _ring.now_us() - enq_us,
                       args={"tenant": t.name, "bytes": w.nbytes,
                             "throttled": w.throttled},
                       parent=req.parent_of())
        if self.exclusive and t.scope is not self._scope:
            # exclusive ownership means no concurrent submitter: steer the
            # engine's per-op accounting (engine_op_lat_us histogram,
            # engine_inflight gauge — PR 6) through the TENANT's scope for
            # the grant, so per-tenant engine latency lands labeled on
            # /metrics with zero per-op plumbing; restored at release
            self.engine.set_scope(t.scope)
        return w

    def release(self, w: _Waiter) -> None:
        if self.exclusive:
            self.engine.set_scope(self._scope)
        with self._cond:
            w.tenant.active -= 1
            left = self._held_by.get(w.owner_ident, 0) - 1
            if left > 0:
                self._held_by[w.owner_ident] = left
            else:
                self._held_by.pop(w.owner_ident, None)
            if self.exclusive and self._current is w:
                self._current = None
                self._dispatch_locked()
            self._cond.notify_all()

    # -- re-entrancy probes (ISSUE 14: engine-routed spill I/O) -------------
    def held_by_me(self) -> bool:
        """True when the CALLING thread acquired a grant that is still
        outstanding — a nested enqueue from it would self-deadlock on an
        exclusive engine."""
        with self._cond:
            return self._held_by.get(threading.get_ident(), 0) > 0

    def engine_idle(self) -> bool:
        """Advisory: no exclusive grant outstanding right now. The
        engine-routed spill WRITE path requires it — a demote fired from a
        mid-gather admission (the streamed pump thread, whose gather's
        grant is held by ANOTHER thread) must fall back to the buffered fd
        rather than queue behind a grant its own progress is supposed to
        release. Races are safe in both directions: a stale True just
        queues normally; a stale False takes the fallback."""
        if not self.exclusive:
            return True
        return self._current is None

    @contextlib.contextmanager
    def grant(self, tenant: "Tenant | str | None" = None, nbytes: int = 0,
              *, priority: str | None = None):
        """``with sched.grant(tenant, nbytes):`` — the scheduler-era
        spelling of ``with ctx._engine_lock:``."""
        from strom.obs import request as _request
        from strom.obs.events import ring as _ring

        # request AND parent captured at ENTRY: the exit may run on another
        # thread (a streamed gather releases at drain, on the pump side)
        # where the contextvar isn't set and parent_of() would read the
        # wrong thread's open-span stack
        req = _request.current()
        parent = req.parent_of() if req is not None else None
        w = self.acquire(tenant, nbytes, priority=priority)
        grant_us = _ring.now_us()
        try:
            yield w
        finally:
            self.release(w)
            if req is not None:
                # the engine-ownership window on the request's lane: how
                # long this request held (its share of) the arbiter
                req.record("sched.grant", "sched", grant_us,
                           _ring.now_us() - grant_us,
                           args={"tenant": w.tenant.name,
                                 "bytes": w.nbytes},
                           parent=parent)

    # -- sliced gather execution (the delivery hot path) --------------------
    def _slice_bytes(self) -> int:
        # live-tunable override (ISSUE 16 autotuner): the config is frozen,
        # so the tuner writes here; None defers to config/auto. Read fresh
        # per call — a move takes effect on the next slice boundary.
        ov = getattr(self, "slice_bytes_override", None)
        if ov is not None and ov >= 0:
            return int(ov)
        sb = getattr(self.config, "sched_slice_bytes", -1)
        if sb >= 0:
            return sb
        # auto: a few in-flight budgets per grant — deep enough that the
        # queue-depth pipeline amortizes the grant handoff, shallow enough
        # that engine ownership turns over at interactive timescales
        return 4 * self.config.queue_depth * self.config.block_size

    def iter_slices(self, chunks: Sequence[tuple[int, int, int, int]]):
        """Split a gather's chunk list into slices of ~``sched_slice_bytes``
        (grant granularity). Chunk order is preserved and chunks are never
        split, so the engine sees the exact ops the plan produced — only
        the lock-ownership boundaries move."""
        limit = self._slice_bytes()
        if limit <= 0:
            yield list(chunks)
            return
        batch: list[tuple[int, int, int, int]] = []
        b = 0
        for c in chunks:
            batch.append(c)
            b += c[3]
            if b >= limit:
                yield batch
                batch, b = [], 0
        if batch:
            yield batch

    def read_chunks(self, chunks: Sequence[tuple[int, int, int, int]],
                    dest, *, tenant: "Tenant | str | None" = None,
                    retries: int = 1, priority: str | None = None) -> int:
        """Execute a planned gather under fair scheduling: one engine
        grant per slice, so a concurrent tenant's op queues behind at most
        ~``sched_slice_bytes`` of this gather instead of all of it.
        Byte-identical to ``engine.read_vectored(chunks, dest)`` (slices
        preserve chunk order; dest ranges are disjoint)."""
        from strom.obs import request as _request

        t = self.resolve(tenant)
        req = _request.current()
        req_deadline = getattr(req, "deadline", None) \
            if req is not None else None
        total = 0
        for si, sl in enumerate(self.iter_slices(chunks)):
            if req_deadline is not None \
                    and time.monotonic() >= req_deadline:
                # deadline between slices (ISSUE 9): the gather stops at a
                # slice boundary — it is never more than ~one slice late
                # past its deadline, and the engine is handed straight to
                # the next tenant in the fair drain
                t.scope.add("deadline_exceeded")
                raise DeadlineExceeded(
                    f"gather stopped at slice {si} "
                    f"({total} bytes landed)")
            nbytes = sum(ln for (_, _, _, ln) in sl)
            with self.grant(t, nbytes, priority=priority), \
                    _request.span("engine.slice", cat="read",
                                  args={"slice": si, "ops": len(sl),
                                        "bytes": nbytes}):
                total += self.engine.read_vectored(sl, dest, retries=retries)
        return total

    def write_chunks(self, chunks: Sequence[tuple[int, int, int, int]],
                     src, *, tenant: "Tenant | str | None" = None,
                     retries: int = 1, priority: str | None = None) -> int:
        """Write twin of :meth:`read_chunks` (ISSUE 13): execute a planned
        scatter — (file_index, file_offset, src_offset, length) chunks out
        of *src* — under the same fair scheduling. One grant per slice, so
        a checkpoint save's multi-GiB write stream is preemptible at slice
        boundaries exactly like an epoch gather: a concurrent tenant's read
        queues behind at most ~``sched_slice_bytes`` of it. Budgets and
        priorities apply unchanged (bytes are bytes to the token buckets,
        whichever direction they flow)."""
        from strom.obs import request as _request

        t = self.resolve(tenant)
        req = _request.current()
        req_deadline = getattr(req, "deadline", None) \
            if req is not None else None
        total = 0
        for si, sl in enumerate(self.iter_slices(chunks)):
            if req_deadline is not None \
                    and time.monotonic() >= req_deadline:
                t.scope.add("deadline_exceeded")
                raise DeadlineExceeded(
                    f"write stopped at slice {si} ({total} bytes landed)")
            nbytes = sum(ln for (_, _, _, ln) in sl)
            with self.grant(t, nbytes, priority=priority), \
                    _request.span("engine.slice", cat="write",
                                  args={"slice": si, "ops": len(sl),
                                        "bytes": nbytes}):
                total += self.engine.write_vectored(sl, src, retries=retries)
        return total

    # -- drain (daemon shutdown / tenant teardown) --------------------------
    def drain(self, tenant: "Tenant | str | None" = None,
              timeout_s: float = 30.0) -> bool:
        """Wait until *tenant* has no queued requests and no active
        grants. True when drained, False on timeout."""
        t = self.resolve(tenant)
        deadline = self._clock() + timeout_s
        with self._cond:
            while t.queue or t.active:
                left = deadline - self._clock()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.05))
        return True

    def drain_all(self, timeout_s: float = 30.0) -> list[str]:
        """Drain every registered tenant; returns the names that did NOT
        drain in time (empty = clean). The daemon's graceful-shutdown
        path runs this before the flight recorder's handler chain."""
        with self._cond:
            names = list(self._tenants)
        deadline = self._clock() + timeout_s
        stuck = []
        for name in names:
            left = max(deadline - self._clock(), 0.01)
            if not self.drain(name, timeout_s=left):
                stuck.append(name)
        return stuck

    def stats(self) -> dict:
        """Flat numeric leaves for the ``sched`` section of
        ``StromContext.stats()`` (→ /metrics via sections_prometheus)."""
        with self._cond:
            tenants = list(self._tenants.values())
        return {
            "sched_tenants": len(tenants),
            "sched_queued_ops": sum(len(t.queue) for t in tenants),
            "sched_queued_bytes": sum(t.queued_bytes for t in tenants),
            "sched_active_grants": sum(t.active for t in tenants),
            "sched_granted_ops": sum(t.granted_ops for t in tenants),
            "sched_granted_bytes": sum(t.granted_bytes for t in tenants),
            "sched_throttle_waits": sum(t.throttle_waits for t in tenants),
            "sched_exclusive": self.exclusive,
            "slab_pool_admission_waits": self.admission.waits,
        }
