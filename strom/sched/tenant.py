"""Tenant handles for the shared I/O scheduler (ISSUE 7 tentpole).

A :class:`Tenant` is one consumer of the shared engine fleet: a pipeline,
an external daemon client, or the readahead thread. It carries

- a **priority class** (``interactive`` > ``training`` > ``background``):
  strict between classes — an interactive op never queues behind training
  backlog — with weighted fair drain *within* a class;
- a **telemetry scope** (the PR-6 substrate): a ``tenant=<name>`` label
  refined over the context's scope, so per-tenant ``engine_op_lat_us``,
  ``sched_queue_wait_us``, bytes and queue-depth land on /metrics as
  labeled series for free, aggregate = sum of tenants by construction;
- optional **budgets**: byte/s and IOPS token buckets
  (:mod:`strom.sched.budget`) the scheduler enforces at grant time;
- an optional **hot-cache partition**: a per-tenant byte cap inside the
  shared :class:`~strom.delivery.hotcache.HotCache`, so one tenant's
  working set can't evict every other tenant's.

Queue state (``queue``, ``deficit``/virtual-time, active grants) is OWNED
by the scheduler and mutated only under its lock; the fields live here so
``info()`` can render one coherent row per tenant for the /tenants route.
"""

from __future__ import annotations

from collections import deque
from typing import Any

from strom.sched.budget import TokenBucket

# strict-priority classes, drained in this order; within a class the
# weighted fair drain (scheduler._pick_locked) arbitrates. Readahead /
# cache warming always demotes to "background" (the paper's framing:
# opportunistic work yields the shared DMA engine to demand work).
PRIORITIES = ("interactive", "training", "background")
PRIORITY_ORDER = {name: i for i, name in enumerate(PRIORITIES)}


class Tenant:
    """One registered consumer of the shared engine fleet."""

    def __init__(self, name: str, *, priority: str = "training",
                 weight: int = 1, scope: Any = None,
                 byte_rate: float = 0, byte_burst: float | None = None,
                 iops: float = 0, hot_cache_bytes: int = 0,
                 clock=None):
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        if weight < 1:
            raise ValueError(f"weight must be >= 1, got {weight}")
        from strom.utils.stats import global_stats

        self.name = name
        self.priority = priority
        self.weight = int(weight)
        self.scope = scope if scope is not None else global_stats
        kw = {} if clock is None else {"clock": clock}
        self.byte_bucket = TokenBucket(byte_rate, byte_burst, **kw)
        self.iops_bucket = TokenBucket(iops, **kw)
        self.hot_cache_bytes = int(hot_cache_bytes)
        # -- scheduler-owned state (mutated under the scheduler lock) -------
        self.queue: deque = deque()          # queued _Waiters, FIFO
        self.queued_bytes = 0
        self.active = 0                      # grants currently held
        self.vtime = 0.0                     # weighted service received
        # lifetime accounting (also mirrored into the scope for /metrics)
        self.granted_ops = 0
        self.granted_bytes = 0
        self.throttle_waits = 0

    # -- introspection (the /tenants route row) -----------------------------
    def info(self) -> dict:
        return {
            "name": self.name,
            "priority": self.priority,
            "weight": self.weight,
            "queued_ops": len(self.queue),
            "queued_bytes": self.queued_bytes,
            "active_grants": self.active,
            "granted_ops": self.granted_ops,
            "granted_bytes": self.granted_bytes,
            "throttle_waits": self.throttle_waits,
            "byte_budget": self.byte_bucket.state(),
            "iops_budget": self.iops_bucket.state(),
            "hot_cache_bytes": self.hot_cache_bytes,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Tenant({self.name!r}, priority={self.priority!r}, "
                f"weight={self.weight}, queued={len(self.queue)})")
