"""Per-tenant budgets + slab-pool admission control (ISSUE 7 tentpole).

Two enforcement primitives the shared I/O scheduler
(:mod:`strom.sched.scheduler`) applies at grant time:

- :class:`TokenBucket` — the classic rate limiter, one per budgeted axis
  (bytes/s, IOPS). The scheduler *peeks* a bucket while choosing the next
  grant (a throttled tenant is simply skipped this pass, its earliest
  ready time bounding the dispatch retry wait) and *takes* only when the
  grant is actually issued — peek-then-take keeps a tenant that lost the
  fairness race from being billed for work it never ran. Oversized ops
  (larger than the burst) are allowed through a debt balance: the take
  drives the bucket negative and later ops wait for recovery, so the
  long-run rate holds for any op size instead of deadlocking on ops that
  could never fit the burst.

- :class:`AdmissionGate` — slab-pool admission control. The pool is the
  shared staging memory every tenant's gathers (and the hot cache) live
  in; a BACKGROUND-class allocation that would push occupancy past the
  high-water mark queues here instead of OOM-ing the demand tenants out
  of slabs. Demand classes are never gated (their dest slabs are already
  allocated by the time the gather reaches the scheduler — gating them
  would deadlock on their own allocation), which is exactly the paper's
  asymmetry: opportunistic work yields, foreground work proceeds.

Both take an injectable clock/sleep so the fairness tests run
deterministically (tests/test_sched.py).

Observability (satellite): ``slab_pool_bytes_in_use`` (gauge, written by
the pool itself on every acquire/release) and ``slab_pool_admission_waits``
(counter, one per wait episode here) land in the global registry →
/metrics, so the scheduler's admission decisions are scrapeable.
"""

from __future__ import annotations

import threading
import time
from typing import Callable
from strom.utils.locks import make_condition, make_lock


class TokenBucket:
    """Token bucket over an arbitrary unit (bytes, ops).

    ``rate`` units/second refill, ``burst`` units capacity. ``rate <= 0``
    means unlimited (every ``peek`` is 0, ``take`` is free) so callers can
    construct one unconditionally. Thread-safe.
    """

    def __init__(self, rate: float, burst: float | None = None, *,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        # default burst: one second's worth — deep enough that steady
        # traffic at the configured rate never stutters, shallow enough
        # that a cold bucket can't front-load multiples of the budget
        self.burst = float(burst) if burst is not None else max(self.rate, 1.0)
        self._clock = clock
        self._tokens = self.burst
        self._t = clock()
        self._lock = make_lock("budget.bucket")

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0

    def _refill_locked(self) -> None:
        now = self._clock()
        if now > self._t:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
        self._t = now

    def peek(self, n: float) -> float:
        """Seconds until *n* units could be taken (0.0 = now). Never
        consumes. Ops larger than the burst are ready as soon as the
        balance is non-negative (see class docstring: debt model)."""
        if self.unlimited or n <= 0:
            return 0.0
        with self._lock:
            self._refill_locked()
            need = min(float(n), self.burst)
            if self._tokens >= need:
                return 0.0
            return (need - self._tokens) / self.rate

    def take(self, n: float) -> None:
        """Unconditionally charge *n* units (may drive the balance
        negative — the debt future takes wait out). Callers peek first;
        the scheduler only takes for the grant it actually issues."""
        if self.unlimited or n <= 0:
            return
        with self._lock:
            self._refill_locked()
            self._tokens -= float(n)

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens

    def state(self) -> dict:
        """Introspection for the /tenants route."""
        return {"rate": self.rate, "burst": self.burst,
                "tokens": round(self.tokens, 1),
                "unlimited": self.unlimited}


class AdmissionGate:
    """Slab-pool high-water admission for opportunistic allocations.

    ``admit(nbytes)`` returns immediately while the pool (plus the
    request) stays at or under ``high_water * pool.max_bytes``; above it,
    the caller queues on a condition the pool's release hook notifies —
    one ``slab_pool_admission_waits`` tick per wait episode, so pressure
    queueing is visible on /metrics rather than showing up only as
    mystery latency. A pool of None (or ``high_water <= 0``) disables the
    gate entirely.
    """

    def __init__(self, pool, high_water: float = 0.9, *, scope=None,
                 clock: Callable[[], float] = time.monotonic):
        from strom.utils.stats import global_stats

        self._pool = pool
        self.high_water = float(high_water)
        self._scope = scope if scope is not None else global_stats
        self._clock = clock
        self._cond = make_condition("sched.admission")
        self.waits = 0
        if pool is not None:
            # the pool pokes the gate on every release so queued admits
            # re-check occupancy without polling
            pool.add_change_hook(self._on_pool_change)

    @property
    def enabled(self) -> bool:
        return self._pool is not None and self.high_water > 0

    def _limit(self) -> int:
        return int(self.high_water * self._pool.max_bytes)

    def has_room(self, nbytes: int) -> bool:
        if not self.enabled:
            return True
        return self._pool.in_use_bytes + max(int(nbytes), 0) <= self._limit()

    def _on_pool_change(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def admit(self, nbytes: int, *, timeout_s: float | None = None) -> bool:
        """Block until *nbytes* of pool headroom exists below the
        high-water mark (True) or *timeout_s* elapses (False). A request
        larger than the whole high-water budget is admitted once the pool
        is otherwise idle — never deadlocks on its own size."""
        if self.has_room(nbytes):
            return True
        deadline = None if timeout_s is None else self._clock() + timeout_s
        self.waits += 1
        self._scope.add("slab_pool_admission_waits")
        with self._cond:
            while True:
                if self.has_room(nbytes) or \
                        self._pool.in_use_bytes == 0:
                    return True
                wait = 0.05 if deadline is None \
                    else min(0.05, deadline - self._clock())
                if wait <= 0:
                    return False
                self._cond.wait(wait)

    def state(self) -> dict:
        if not self.enabled:
            return {"enabled": False, "waits": self.waits}
        return {"enabled": True, "high_water": self.high_water,
                "limit_bytes": self._limit(),
                "in_use_bytes": self._pool.in_use_bytes,
                "waits": self.waits}
