"""Multi-tenant I/O scheduler (ISSUE 7 tentpole).

One engine fleet, many consumers: per-tenant queues with priority
classes, weighted fair drain at engine-slice granularity, byte/IOPS
budgets, and slab-pool admission control. See
:mod:`strom.sched.scheduler` for the arbiter,
:mod:`strom.sched.budget` for the enforcement primitives, and
:mod:`strom.sched.tenant` for the tenant handle.
"""

from strom.sched.budget import AdmissionGate, TokenBucket
from strom.sched.scheduler import SCHED_FIELDS, IoScheduler
from strom.sched.tenant import PRIORITIES, Tenant

__all__ = ["AdmissionGate", "IoScheduler", "PRIORITIES", "SCHED_FIELDS",
           "Tenant", "TokenBucket"]
