"""Sharded read planning: NamedSharding → per-device contiguous byte segments.

The reference delivers into a single pinned GPU buffer; strom-tpu's
destination is a *mesh* of TPU devices, so the plan step maps each addressable
device's shard of the global array to the byte ranges of the source file that
hold it (SURVEY.md §2.3 "Mesh-sharded delivery"; §7.2 step 6).  Rows are
row-major on disk: a shard that restricts only leading axes is a handful of
large contiguous reads; inner-axis sharding (e.g. sequence-parallel batches)
decomposes into per-row segments.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Iterator

import numpy as np


@dataclasses.dataclass(frozen=True)
class Segment:
    """Copy file[file_offset : +length] → dest[dest_offset : +length]."""

    file_offset: int
    dest_offset: int
    length: int


def _normalize_index(index: tuple, shape: tuple[int, ...]) -> list[tuple[int, int]]:
    out = []
    for sl, dim in itertools.zip_longest(index, shape, fillvalue=slice(None)):
        if dim is None:
            raise ValueError("index longer than shape")
        if not isinstance(sl, slice):
            raise ValueError(f"only slice indices supported, got {sl!r}")
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError("strided shards not supported")
        out.append((start, stop))
    return out


def contiguous_segments(shape: tuple[int, ...], itemsize: int,
                        index: tuple) -> Iterator[Segment]:
    """Decompose a rectangular sub-block (tuple of slices) of a row-major array
    into contiguous (file_offset, dest_offset, length) segments."""
    if not shape:
        yield Segment(0, 0, itemsize)
        return
    bounds = _normalize_index(index, shape)
    # byte strides, row-major
    strides = [0] * len(shape)
    acc = itemsize
    for i in range(len(shape) - 1, -1, -1):
        strides[i] = acc
        acc *= shape[i]
    # k = number of leading dims that are NOT part of the trailing full block
    k = len(shape)
    while k > 0 and bounds[k - 1] == (0, shape[k - 1]):
        k -= 1
    if k == 0:
        total = math.prod(shape) * itemsize
        yield Segment(0, 0, total)
        return
    inner = strides[k - 1]  # bytes per index step along dim k-1
    start_k, stop_k = bounds[k - 1]
    run = (stop_k - start_k) * inner
    outer = [range(lo, hi) for lo, hi in bounds[: k - 1]]
    dest = 0
    for combo in itertools.product(*outer):
        off = sum(c * strides[i] for i, c in enumerate(combo)) + start_k * inner
        yield Segment(off, dest, run)
        dest += run


@dataclasses.dataclass(frozen=True)
class DevicePlan:
    device: Any                    # jax.Device
    local_shape: tuple[int, ...]
    nbytes: int
    segments: tuple[Segment, ...]  # file offsets relative to array start


def plan_sharded_read(global_shape: tuple[int, ...], dtype,
                      sharding) -> list[DevicePlan]:
    """Per-addressable-device read plans for a global array laid out row-major
    in the source at byte offset 0 (callers add their own base offset)."""
    itemsize = np.dtype(dtype).itemsize
    idx_map = sharding.addressable_devices_indices_map(tuple(global_shape))
    plans: list[DevicePlan] = []
    for device, index in idx_map.items():
        bounds = _normalize_index(index if index is not None else (), tuple(global_shape))
        local_shape = tuple(hi - lo for lo, hi in bounds)
        segs = tuple(contiguous_segments(tuple(global_shape), itemsize, index))
        nbytes = math.prod(local_shape) * itemsize if local_shape else itemsize
        assert sum(s.length for s in segs) == nbytes, "segment plan disagrees with shard size"
        plans.append(DevicePlan(device, local_shape, nbytes, segs))
    return plans


def dedupe_plans(plans: list[DevicePlan]) -> dict[tuple[Segment, ...], list[DevicePlan]]:
    """Group plans by identical segment sets (replicated shards are read once
    and device_put to every replica)."""
    groups: dict[tuple[Segment, ...], list[DevicePlan]] = {}
    for p in plans:
        groups.setdefault(p.segments, []).append(p)
    return groups
