from strom.delivery.buffers import alloc_aligned  # noqa: F401
from strom.delivery.coalesce import coalesce_chunks, coalesce_segments  # noqa: F401
from strom.delivery.handle import DMAHandle  # noqa: F401
from strom.delivery.hotcache import HotCache, Readahead  # noqa: F401
from strom.delivery.prefetch import Prefetcher, bound_depth  # noqa: F401
from strom.delivery.shard import contiguous_segments, plan_sharded_read  # noqa: F401
from strom.delivery.stream import STREAM_FIELDS, StreamingGather  # noqa: F401
