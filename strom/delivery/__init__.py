from strom.delivery.buffers import alloc_aligned  # noqa: F401
from strom.delivery.handle import DMAHandle  # noqa: F401
from strom.delivery.prefetch import Prefetcher  # noqa: F401
from strom.delivery.shard import contiguous_segments, plan_sharded_read  # noqa: F401
