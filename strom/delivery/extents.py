"""ExtentList: a logical byte stream gather-composed from file ranges.

The reference's MEMCPY_SSD2GPU ioctl takes a *chunk list* — a vector of file
ranges DMA'd into one destination buffer (SURVEY.md §3.3; reference cite
UNVERIFIED — empty mount, SURVEY.md §0).  ExtentList is the strom-tpu twin:
format readers (packed-token records, tar members, Parquet column chunks)
compile their record layout into an ExtentList, and the delivery layer treats
it as a virtual contiguous file — so sharded reads (`NamedSharding` →
per-device byte ranges) compose with scatter-gather for free: each device
reads only the physical ranges backing its shard.
"""

from __future__ import annotations

import bisect
import dataclasses
import itertools
from typing import Iterator, Sequence


@dataclasses.dataclass(frozen=True)
class Extent:
    """One physical file range contributing to the logical stream."""

    path: str
    offset: int
    length: int

    def __post_init__(self) -> None:
        if self.length <= 0:
            raise ValueError(f"extent length must be positive, got {self.length}")
        if self.offset < 0:
            raise ValueError(f"extent offset must be >= 0, got {self.offset}")


@dataclasses.dataclass(frozen=True)
class PhysicalRun:
    """A physical read serving part of a logical range."""

    path: str
    offset: int        # physical byte offset in path
    length: int
    dest_offset: int   # where in the caller's destination this run lands


class ExtentList:
    """Immutable ordered list of extents forming one logical byte stream.

    Logical offset 0 is the first byte of extents[0]; extents concatenate.
    """

    __slots__ = ("extents", "_starts", "size")

    def __init__(self, extents: Sequence[Extent | tuple]):
        ext = tuple(e if isinstance(e, Extent) else Extent(*e) for e in extents)
        self.extents: tuple[Extent, ...] = ext  # may be empty: a 0-byte stream
        # prefix sums: _starts[i] = logical offset of extents[i]
        starts = list(itertools.accumulate((e.length for e in ext), initial=0))
        self.size: int = starts.pop()
        self._starts: list[int] = starts

    def __len__(self) -> int:
        return len(self.extents)

    def __repr__(self) -> str:
        return f"ExtentList({len(self.extents)} extents, {self.size} bytes)"

    def locate(self, logical_offset: int, length: int,
               dest_offset: int = 0) -> Iterator[PhysicalRun]:
        """Map logical [logical_offset, +length) to physical runs.

        Runs are emitted in logical order; dest offsets advance from
        *dest_offset* so they can be fed straight into a gather read.
        """
        if logical_offset < 0 or length < 0:
            raise ValueError("offset/length must be >= 0")
        if logical_offset + length > self.size:
            raise ValueError(
                f"range [{logical_offset}, +{length}) beyond stream size {self.size}")
        remaining = length
        pos = logical_offset
        dest = dest_offset
        # index of the extent containing `pos`
        i = bisect.bisect_right(self._starts, pos) - 1
        while remaining > 0:
            e = self.extents[i]
            within = pos - self._starts[i]
            take = min(e.length - within, remaining)
            yield PhysicalRun(e.path, e.offset + within, take, dest)
            pos += take
            dest += take
            remaining -= take
            i += 1
        return

    def slice(self, logical_offset: int, length: int) -> "ExtentList":
        """A new ExtentList viewing logical [logical_offset, +length)."""
        runs = list(self.locate(logical_offset, length))
        return ExtentList([Extent(r.path, r.offset, r.length) for r in runs])

    def paths(self) -> tuple[str, ...]:
        """Distinct backing paths, in first-appearance order."""
        seen: dict[str, None] = {}
        for e in self.extents:
            seen.setdefault(e.path)
        return tuple(seen)

    @staticmethod
    def concat(parts: Sequence["ExtentList"]) -> "ExtentList":
        out: list[Extent] = []
        for p in parts:
            out.extend(p.extents)
        return ExtentList(out)
