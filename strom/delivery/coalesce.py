"""Segment/op coalescing for the delivery scheduler.

Shard plans, WDS batch extents and format readers hand the delivery layer
fine-grained segment lists — one per tar member, per record run, per column
chunk — and many of those are ADJACENT on disk and in the destination (sorted
batch indices over a packed shard, consecutive samples in a tar, column
chunks laid out back to back). Submitting them as-is costs one engine op
(and, on the native engine, one residency probe and one vec-seg bookkeeping
entry) per fragment, plus an unaligned sub-block tail per fragment on the
O_DIRECT path. Coalescing merges runs that are contiguous in BOTH file and
dest space into fewer, larger ops before submission — the reference builds
its NVMe requests the same way, from extent-resolved runs rather than caller
fragments (SURVEY.md §2.1 "Extent resolver"; cite UNVERIFIED, §0).

A split threshold caps the merged op length so a coalesced run still
pipelines (and, through the stripe planner, still stripes across RAID0
members) instead of becoming one monolithic op.  Pure functions,
unit-tested in tests/test_coalesce.py; observability lives with the caller
(strom.utils.stats "coalesce_*" counters/gauges set by the delivery layer).
"""

from __future__ import annotations

from typing import Sequence

from strom.delivery.shard import Segment

# an engine gather op: (file_idx, file_offset, dest_offset, length)
Chunk = tuple[int, int, int, int]


def _merge_runs(runs: list[tuple[int, int, int]],
                max_bytes: int) -> list[tuple[int, int, int]]:
    """Merge (file_off, dest_off, length) runs that share one file/dest
    delta: input sorted by file_off, overlap/adjacency merges to the union,
    then each merged run splits at *max_bytes* (0 = no split)."""
    merged: list[list[int]] = []
    for fo, do, ln in runs:
        if merged:
            p = merged[-1]
            if fo <= p[0] + p[2]:  # adjacent or overlapping (same delta)
                p[2] = max(p[2], fo + ln - p[0])
                continue
        merged.append([fo, do, ln])
    if max_bytes <= 0:
        return [(fo, do, ln) for fo, do, ln in merged]
    out: list[tuple[int, int, int]] = []
    for fo, do, ln in merged:
        pos = 0
        while ln - pos > max_bytes:
            out.append((fo + pos, do + pos, max_bytes))
            pos += max_bytes
        out.append((fo + pos, do + pos, ln - pos))
    return out


def coalesce_segments(segments: Sequence[Segment],
                      max_bytes: int = 0) -> list[Segment]:
    """Merge segments that are contiguous (or overlapping) in both file and
    dest space; split merged runs longer than *max_bytes* (0 = unlimited).

    Segments with the same file↔dest delta whose ranges touch describe one
    larger copy; overlapping same-delta ranges are deduplicated to the union
    (same bytes land in the same place either way). Segments with different
    deltas never merge — they move different dest bytes. Output is sorted by
    dest offset (the order :func:`split_segments` normalizes to anyway).
    """
    groups: dict[int, list[tuple[int, int, int]]] = {}
    for s in segments:
        groups.setdefault(s.file_offset - s.dest_offset, []).append(
            (s.file_offset, s.dest_offset, s.length))
    out: list[Segment] = []
    for runs in groups.values():
        runs.sort()
        out.extend(Segment(fo, do, ln)
                   for fo, do, ln in _merge_runs(runs, max_bytes))
    out.sort(key=lambda s: s.dest_offset)
    return out


def coalesce_chunks(chunks: Sequence[Chunk], max_bytes: int = 0) -> list[Chunk]:
    """:func:`coalesce_segments` for engine op lists: merge ops on the same
    file that are contiguous/overlapping in both file and dest space, split
    at *max_bytes*. Ops on different files (RAID0 members, multi-shard
    extents) never merge. Output order: grouped by file in first-appearance
    order, dest-sorted within a file — any order is valid for the engine
    (dest offsets are explicit); this one preserves the input's file
    locality."""
    # file -> delta -> runs: insertion-ordered dicts give first-appearance
    # file order and one linear pass over each file's own delta groups
    by_file: dict[int, dict[int, list[tuple[int, int, int]]]] = {}
    for fi, fo, do, ln in chunks:
        by_file.setdefault(fi, {}).setdefault(fo - do, []).append(
            (fo, do, ln))
    out: list[Chunk] = []
    for fi, groups in by_file.items():
        per_file: list[tuple[int, int, int]] = []
        for runs in groups.values():
            runs.sort()
            per_file.extend(_merge_runs(runs, max_bytes))
        per_file.sort(key=lambda r: r[1])  # dest order within the file
        out.extend((fi, fo, do, ln) for fo, do, ln in per_file)
    return out
