"""Completion-driven intra-batch streaming (ISSUE 5 tentpole).

The blocking hot path runs each batch as gather-ALL → decode-ALL → put-ALL:
every sample in the batch waits for the slowest extent before the first
decode starts, so completions sit idle in the ring while decode workers
starve (the ingest-wait bucket the stall attribution keeps billing the JPEG
arm). :class:`StreamingGather` removes that barrier: it plans a batch gather
exactly like ``StromContext._read_segments`` (same striped-alias resolution,
coalescing, stripe windows, extent-aware ordering — shared via
``_plan_chunks``), submits it through the engine's async vectored API
(``submit_vectored``/``poll``/``drain``, ISSUE 5 engine layer), and surfaces
CHUNK-granular dest-range completions the moment they land — hot-cache hits
count as INSTANT completions (served before the engine sees a single op).
The vision pipelines map completed ranges onto samples and hand each sample
to the decode pool the moment its extents are in, so read, decode, and
device_put overlap at extent granularity *within* one batch, not just
across batches.

Ordering / lifecycle rules (documented in ARCHITECTURE.md "Intra-batch
streaming"):

- Completions are UNORDERED across chunks (the whole point); each dest byte
  completes exactly once — ranges from distinct completions never overlap,
  so per-sample byte accounting is a plain countdown.
- The gather owns the engine's transfer path from construction to
  close/finish: the delivery engine lock (per-ring locks on the multi
  engine) is held for the token's lifetime, and the demand gate is entered
  so readahead yields exactly as it does to a blocking gather.
- Hot-cache pins taken while serving hits are dropped before construction
  returns (the bytes are already memcpy'd into *dest*); admission offers
  for miss chunks happen per-completion, so an early extent can serve the
  NEXT batch's lookup while this batch's tail is still in flight.
- ``close()`` is idempotent and safe mid-flight: the engine token is
  cancelled (every in-flight piece reaped — no completion may outlive the
  gather), locks and the demand gate release, and no slab pin survives.
"""

from __future__ import annotations

import contextlib
import errno
from typing import Sequence

import numpy as np

from strom.delivery.shard import Segment
from strom.engine.base import EngineError
from strom.obs import request as _request
from strom.obs.events import ring as _events_ring

# bench-JSON columns the streaming arms emit (cli.py _stream_stats_delta),
# single-sourced so the driver's per-arm copy loop (bench.py) and the
# compare_rounds "streaming" section cannot drift from the producer — the
# same contract STALL_FIELDS / CACHE_BENCH_FIELDS enforce.
STREAM_FIELDS = (
    "stream_batches",
    "stream_inflight_peak",
    "stream_instant_bytes",
    "stream_samples_early",
    "stream_first_decode_lat_p50_us",
    "stream_first_decode_lat_mean_us",
    "stream_tail_extent_p50_us",
    "stream_tail_extent_mean_us",
)


class StreamingGather:
    """One completion-driven gather of *segments* from *source* into *dest*.

    Protocol::

        g = ctx.stream_segments(source, segments, dest)
        try:
            while not g.done:
                for lo, hi in g.poll():   # dest byte ranges, landed
                    ...dispatch work on dest[lo:hi]...
            g.finish()                    # integrity check + stats
        finally:
            g.close()                     # idempotent; cancels if unfinished

    ``poll`` first returns the cache-served (instant) ranges, then engine
    completions as chunks retire. ``finish`` raises the gather's error (the
    same EngineError surface as ``_read_segments``) only after every
    in-flight piece has retired.
    """

    def __init__(self, ctx, source, segments: Sequence[Segment],
                 dest: np.ndarray, base_offset: int = 0, *, scope=None,
                 tenant: str | None = None):
        self._ctx = ctx
        # telemetry scope (ISSUE 6): pipelines pass their label scope so two
        # tenants' streamed gathers surface distinguishable stream_* series;
        # default: the context's scope (single-tenant behavior unchanged)
        self._scope = scope if scope is not None else ctx.scope
        # scheduler tenant (ISSUE 7): explicit name wins, else the scope's
        # tenant label — so a pipeline built with scope={"tenant": "t0"}
        # queues (and bills cache partitions) as t0 with no extra plumbing
        if tenant is None:
            tenant = getattr(self._scope, "labels", {}).get("tenant")
        self._tenant = tenant
        self._dflat = dest if dest.ndim == 1 and dest.dtype == np.uint8 \
            else dest.reshape(-1).view(np.uint8)
        self._closed = False
        self._finished = False
        self._token = None
        self._admitted = 0
        self.t0_us = _events_ring.now_us()
        self._first_c_us: int | None = None
        self._last_c_us: int | None = None
        # engine-path resources: demand gate (readahead yields to us) +
        # the engine arbiter — a scheduler grant for the miss bytes when a
        # scheduler exists (queued/budgeted like any tenant op), else the
        # legacy delivery engine lock. Held while the token is live and
        # RELEASED AT GATHER DRAIN (the moment the last piece retires, in
        # poll/finish/close — ISSUE 7 satellite): finish-side bookkeeping
        # and admission offers never extend engine ownership, matching the
        # release point the streamed pipeline path already had.
        self._stack = contextlib.ExitStack()
        self._engine_released = False
        # causal request tracing (ISSUE 8): join the enclosing request
        # (the streamed batch assembly mints one around make_batch) or
        # mint our own for direct stream_segments callers — the sched
        # grant, engine token, cache serve/admit and stream spans below
        # all carry its req_id; an owned request finishes at release.
        req = _request.current()
        self._own_req = req is None
        self.req = req if req is not None \
            else _request.Request("gather", self._tenant)
        try:
            with _request.attach(self.req):
                chunks, idx_paths = ctx._plan_chunks(source, segments,
                                                     base_offset)
                self._idx_paths = idx_paths
                cache = ctx._hot_cache
                if cache is not None and not cache.enabled:
                    cache = None
                self._cache = cache
                self._instant: list[tuple[int, int]] = []
                hit_bytes = 0
                if cache is not None and chunks:
                    chunks, hit_bytes, self._instant = ctx._consult_cache(
                        cache, chunks, idx_paths, self._dflat)
                self._chunks = chunks
                self._miss_planned = sum(ln for (_, _, _, ln) in chunks)
                self.total_bytes = self._miss_planned + hit_bytes
                self.instant_bytes = hit_bytes
                if hit_bytes:
                    self._scope.add("stream_instant_bytes", hit_bytes)
                if chunks:
                    self._stack.enter_context(ctx._demand_gate())
                    if ctx.scheduler is not None:
                        self._stack.enter_context(
                            ctx.scheduler.grant(self._tenant,
                                                self._miss_planned))
                    else:
                        self._stack.enter_context(ctx._engine_lock)
                    self._token = ctx.engine.submit_vectored(
                        chunks, dest, retries=ctx.config.io_retries,
                        req_id=self.req.id)
                self._scope.add("stream_batches")
        except BaseException as e:
            self._stack.close()
            self._closed = True
            if self._own_req:
                self.req.mark_error(e)
                self.req.finish()
            raise

    @property
    def done(self) -> bool:
        """Every byte accounted for: instants drained and the engine token
        (if any) retired. ``finish`` must still be called."""
        return not self._instant \
            and (self._token is None or self._token.done)

    def poll(self, min_completions: int = 1,
             timeout_s: float | None = None) -> list[tuple[int, int]]:
        """Landed dest ranges since the last call. The first call returns
        the cache-served ranges immediately (instant completions); later
        calls reap the engine. ``min_completions=0`` never blocks."""
        if self._closed:
            return []
        if self._instant:
            out, self._instant = self._instant, []
            now = _events_ring.now_us()
            if self._first_c_us is None:
                self._first_c_us = now
            self._last_c_us = now
            return out
        if self._token is None or self._token.done:
            return []
        out: list[tuple[int, int]] = []
        for c in self._ctx.engine.poll(self._token, min_completions,
                                       timeout_s):
            if c.result < 0:
                continue  # error chunk: surfaced by finish() after drain
            fi, fo, do, ln = self._chunks[c.index]
            now = _events_ring.now_us()
            if self._first_c_us is None:
                self._first_c_us = now
            self._last_c_us = now
            out.append((do, do + ln))
            if self._cache is not None:
                # admission offer per completion (second-touch policy
                # decides): the bytes just landed in dest — one memcpy,
                # never an extra read, and an early extent can serve the
                # next batch's lookup while this batch's tail is in flight
                path = self._idx_paths.get(fi)
                if path is not None:
                    self._admitted += self._cache.admit(
                        path, fo, fo + ln, self._dflat[do: do + ln],
                        tenant=self._tenant)
        if self._token.done:
            # gather drained: hand the engine back NOW — the caller may
            # keep polling instants / defer finish() without holding the
            # arbiter against other tenants (ISSUE 7 satellite)
            self._release_engine()
        return out

    def finish(self) -> int:
        """Drain the token, verify byte accounting, emit the stream span +
        counters, release the engine lock/demand gate. Returns total bytes
        (cache hits included). Raises the gather's first error — only after
        every in-flight piece has retired (no write can race the caller's
        reaction)."""
        if self._finished:
            return self.total_bytes
        total = self._miss_planned
        try:
            if self._token is not None:
                total = self._ctx.engine.drain(self._token)
        except EngineError as e:
            self.req.mark_error(e)
            self._release()
            raise EngineError(e.errno, f"ssd2tpu {e.strerror}") from None
        self._release_engine()
        if total != self._miss_planned:
            # cheap insurance, same as _read_segments: any engine
            # accounting bug surfaces loudly, not as a zero-tailed batch
            err = EngineError(
                errno.EIO, f"ssd2tpu streamed read {total} bytes, "
                           f"planned {self._miss_planned}")
            self.req.mark_error(err)
            self._release()
            raise err
        self._release()
        self._scope.add("ssd2tpu_bytes", self.total_bytes)
        return self.total_bytes

    def _release_engine(self) -> None:
        """Drop the engine-path resources (demand gate + scheduler grant /
        engine lock). Idempotent; called the moment the token drains —
        every in-flight piece retired — so the stats/span/admission tail
        of finish() runs with the engine already handed to the next
        tenant in the fair drain."""
        if self._engine_released:
            return
        self._engine_released = True
        self._stack.close()

    def _release(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._closed = True
        tok = self._token
        if tok is not None:
            self._scope.gauge("stream_inflight_peak").max(tok.inflight_peak)
            # keep the stall attribution's `read` bucket lit on streamed
            # batches: the async token never passes through read_vectored's
            # instrumented wrappers, so the engine window is billed here
            end = self._last_c_us if self._last_c_us is not None \
                else _events_ring.now_us()
            self.req.record("stream.read", "read", self.t0_us,
                            max(end - self.t0_us, 0),
                            {"ops": len(self._chunks),
                             "bytes": self._miss_planned})
        if self._first_c_us is not None and self._last_c_us is not None:
            # the spread the old barrier serialized on: how long the
            # slowest extent lagged the first completion — with streaming,
            # work done during this window is the win
            self._scope.observe_us("stream_tail_extent",
                                   self._last_c_us - self._first_c_us)
        if self._admitted:
            self.req.record("cache.admit", "cache", self.t0_us,
                            _events_ring.now_us() - self.t0_us,
                            {"bytes": self._admitted})
        self.req.record("stream.gather", "stream", self.t0_us,
                        _events_ring.now_us() - self.t0_us,
                        {"bytes": self.total_bytes,
                         "instant_bytes": self.instant_bytes,
                         "ops": len(self._chunks)})
        self._release_engine()
        if self._own_req:
            self.req.finish()

    def close(self) -> None:
        """Idempotent teardown. A live token is CANCELLED: every in-flight
        piece is reaped before the engine lock releases, so no completion
        (and no engine write into *dest*) outlives the gather — the
        leaked-pin/leaked-completion contract tests assert this."""
        if self._finished:
            return
        if self._token is not None and not self._token.done:
            with contextlib.suppress(Exception):
                self._ctx.engine.cancel(self._token)
        self._release_engine()
        self._release()

    def __enter__(self) -> "StreamingGather":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
