"""Completion-driven intra-batch streaming (ISSUE 5 tentpole).

The blocking hot path runs each batch as gather-ALL → decode-ALL → put-ALL:
every sample in the batch waits for the slowest extent before the first
decode starts, so completions sit idle in the ring while decode workers
starve (the ingest-wait bucket the stall attribution keeps billing the JPEG
arm). :class:`StreamingGather` removes that barrier: it plans a batch gather
exactly like ``StromContext._read_segments`` (same striped-alias resolution,
coalescing, stripe windows, extent-aware ordering — shared via
``_plan_chunks``), submits it through the engine's async vectored API
(``submit_vectored``/``poll``/``drain``, ISSUE 5 engine layer), and surfaces
CHUNK-granular dest-range completions the moment they land — hot-cache hits
count as INSTANT completions (served before the engine sees a single op).
The vision pipelines map completed ranges onto samples and hand each sample
to the decode pool the moment its extents are in, so read, decode, and
device_put overlap at extent granularity *within* one batch, not just
across batches.

Ordering / lifecycle rules (documented in ARCHITECTURE.md "Intra-batch
streaming"):

- Completions are UNORDERED across chunks (the whole point); each dest byte
  completes exactly once — ranges from distinct completions never overlap,
  so per-sample byte accounting is a plain countdown.
- The gather owns the engine's transfer path from construction to
  close/finish: the delivery engine lock (per-ring locks on the multi
  engine) is held for the token's lifetime, and the demand gate is entered
  so readahead yields exactly as it does to a blocking gather.
- Hot-cache pins taken while serving hits are dropped before construction
  returns (the bytes are already memcpy'd into *dest*); admission offers
  for miss chunks happen per-completion, so an early extent can serve the
  NEXT batch's lookup while this batch's tail is still in flight.
- ``close()`` is idempotent and safe mid-flight: the engine token is
  cancelled (every in-flight piece reaped — no completion may outlive the
  gather), locks and the demand gate release, and no slab pin survives.
"""

from __future__ import annotations

import contextlib
import errno
import time
from typing import Sequence

import numpy as np

from strom.delivery.shard import Segment
from strom.engine.base import (DeadlineExceeded, EngineError,
                               EngineStallError)
from strom.obs import request as _request
from strom.obs.events import ring as _events_ring

# bench-JSON columns the streaming arms emit (cli.py _stream_stats_delta),
# single-sourced so the driver's per-arm copy loop (bench.py) and the
# compare_rounds "streaming" section cannot drift from the producer — the
# same contract STALL_FIELDS / CACHE_BENCH_FIELDS enforce.
STREAM_FIELDS = (
    "stream_batches",
    "stream_inflight_peak",
    "stream_instant_bytes",
    "stream_samples_early",
    "stream_first_decode_lat_p50_us",
    "stream_first_decode_lat_mean_us",
    "stream_tail_extent_p50_us",
    "stream_tail_extent_mean_us",
)


class StreamingGather:
    """One completion-driven gather of *segments* from *source* into *dest*.

    Protocol::

        g = ctx.stream_segments(source, segments, dest)
        try:
            while not g.done:
                for lo, hi in g.poll():   # dest byte ranges, landed
                    ...dispatch work on dest[lo:hi]...
            g.finish()                    # integrity check + stats
        finally:
            g.close()                     # idempotent; cancels if unfinished

    ``poll`` first returns the cache-served (instant) ranges, then engine
    completions as chunks retire. ``finish`` raises the gather's error (the
    same EngineError surface as ``_read_segments``) only after every
    in-flight piece has retired.
    """

    def __init__(self, ctx, source, segments: Sequence[Segment],
                 dest: np.ndarray, base_offset: int = 0, *, scope=None,
                 tenant: str | None = None):
        self._ctx = ctx
        # telemetry scope (ISSUE 6): pipelines pass their label scope so two
        # tenants' streamed gathers surface distinguishable stream_* series;
        # default: the context's scope (single-tenant behavior unchanged)
        self._scope = scope if scope is not None else ctx.scope
        # scheduler tenant (ISSUE 7): explicit name wins, else the scope's
        # tenant label — so a pipeline built with scope={"tenant": "t0"}
        # queues (and bills cache partitions) as t0 with no extra plumbing
        if tenant is None:
            tenant = getattr(self._scope, "labels", {}).get("tenant")
        self._tenant = tenant
        self._dflat = dest if dest.ndim == 1 and dest.dtype == np.uint8 \
            else dest.reshape(-1).view(np.uint8)
        self._closed = False
        self._finished = False
        self._token = None
        self._admitted = 0
        self.t0_us = _events_ring.now_us()
        self._first_c_us: int | None = None
        self._last_c_us: int | None = None
        # engine-path resources: demand gate (readahead yields to us) +
        # the engine arbiter — a scheduler grant for the miss bytes when a
        # scheduler exists (queued/budgeted like any tenant op), else the
        # legacy delivery engine lock. Held while the token is live and
        # RELEASED AT GATHER DRAIN (the moment the last piece retires, in
        # poll/finish/close — ISSUE 7 satellite): finish-side bookkeeping
        # and admission offers never extend engine ownership, matching the
        # release point the streamed pipeline path already had.
        self._stack = contextlib.ExitStack()
        self._engine_released = False
        # causal request tracing (ISSUE 8): join the enclosing request
        # (the streamed batch assembly mints one around make_batch) or
        # mint our own for direct stream_segments callers — the sched
        # grant, engine token, cache serve/admit and stream spans below
        # all carry its req_id; an owned request finishes at release.
        req = _request.current()
        self._own_req = req is None
        self.req = req if req is not None \
            else _request.Request("gather", self._tenant)
        if self._own_req:
            self.req.set_deadline_s(ctx.config.request_deadline_s or None)
        # resilience (ISSUE 9): per-chunk failure recovery + hedged reads
        # ride the context's breaker/failover layer. The token runs
        # fail_fast=False — a failed chunk retires as a negative
        # completion (recovered below on the fallback path) while the
        # REST of the gather keeps flowing, instead of one bad extent
        # killing the whole batch.
        self._resil = getattr(ctx, "_resilience", None)
        self._completed: set[int] = set()   # engine-served chunk indices
        self._recovered: set[int] = set()   # fallback-served after failure
        self._hedged: set[int] = set()      # fallback-served by a hedge
        self._hedge_tried: set[int] = set()  # one hedge per chunk, ever
        self._breaker_fed = False  # one breaker outcome per gather
        self._failed: dict[int, int] = {}   # unrecovered: ci -> errno
        self._recovery_attempted = False
        self._last_prog_t = time.monotonic()  # hedge quiet clock
        self._stall_t0 = time.monotonic()     # watchdog clock: REAL progress
        self._stall_bytes = -1                # piece progress the clock saw
        try:
            with _request.attach(self.req):
                chunks, idx_paths = ctx._plan_chunks(source, segments,
                                                     base_offset)
                self._idx_paths = idx_paths
                cache = ctx._hot_cache
                if cache is not None and not cache.enabled:
                    cache = None
                self._cache = cache
                self._instant: list[tuple[int, int]] = []
                hit_bytes = 0
                # peer tier included (ISSUE 15): peer-served ranges surface
                # as INSTANT completions exactly like cache hits — the
                # consult handles cache=None for peered cacheless contexts
                if (cache is not None or ctx._peer_tier is not None) \
                        and chunks:
                    chunks, hit_bytes, self._instant = ctx._consult_cache(
                        cache, chunks, idx_paths, self._dflat,
                        tenant=self._tenant)
                self._chunks = chunks
                self._miss_planned = sum(ln for (_, _, _, ln) in chunks)
                self.total_bytes = self._miss_planned + hit_bytes
                self.instant_bytes = hit_bytes
                if hit_bytes:
                    self._scope.add("stream_instant_bytes", hit_bytes)
                if chunks:
                    self._stack.enter_context(ctx._demand_gate())
                    if ctx.scheduler is not None:
                        self._stack.enter_context(
                            ctx.scheduler.grant(self._tenant,
                                                self._miss_planned))
                    else:
                        # stromlint: ignore[lock-order] -- engine ownership
                        # intentionally spans the token's lifetime (the
                        # gather owns the transfer path construction ->
                        # drain); released at _release_engine the moment
                        # the last piece retires, and every wait under it
                        # is bounded by the gather watchdog
                        # (EngineStallError in poll/finish)
                        self._stack.enter_context(ctx._engine_lock)
                    self._token = ctx.engine.submit_vectored(
                        chunks, dest, retries=ctx.config.io_retries,
                        req_id=self.req.id,
                        deadline=getattr(self.req, "deadline", None),
                        fail_fast=False)
                self._scope.add("stream_batches")
        except BaseException as e:
            self._stack.close()
            self._closed = True
            if self._own_req:
                self.req.mark_error(e)
                self.req.finish()
            raise

    @property
    def done(self) -> bool:
        """Every byte accounted for: instants drained and every chunk
        served — by the engine token, a fallback recovery, or a winning
        hedge. ``finish`` must still be called."""
        if self._instant:
            return False
        tok = self._token
        if tok is None:
            return True
        if tok.done:
            # a token that died at submit (engine death) leaves chunks
            # unaccounted: one fallback-recovery pass still owes ranges
            return self._recovery_attempted or tok._err is None \
                or self._resil is None or not self._unaccounted()
        # token still live (e.g. a stuck loser): done once every chunk is
        # individually accounted — finish() cancels the remainder
        return not self._unaccounted() and not self._failed

    def _unaccounted(self) -> list[int]:
        served = self._completed | self._recovered | self._hedged
        return [ci for ci in range(len(self._chunks))
                if ci not in served and ci not in self._failed]

    def _mark_progress(self) -> None:
        now = _events_ring.now_us()
        if self._first_c_us is None:
            self._first_c_us = now
        self._last_c_us = now
        self._last_prog_t = time.monotonic()
        self._stall_t0 = self._last_prog_t

    def _chunk_fallback(self, ci: int) -> bool:
        """Read chunk *ci* on the fallback path straight into dest.
        True on success. (Recovered bytes are NOT offered for cache
        admission: the primary path just failed around them — proving
        them stable is the next clean read's job.)"""
        if self._resil is None:
            return False
        fi, fo, do, ln = self._chunks[ci]
        path = self._idx_paths.get(fi)
        if path is None:
            return False
        ok = self._resil.read_chunk_fallback(
            path, fo, ln, self._dflat[do: do + ln])
        if ok:
            self._scope.add("failover_reads")
            self._scope.add("failover_bytes", ln)
        return ok

    def _feed_breaker(self, *, ok: bool) -> None:
        """One breaker outcome per GATHER, not per chunk: the demand path
        records per-gather too, and a streamed batch serving 10^4 chunks
        with a handful recovered must not read as a 100% error rate to
        the rolling window (a failure-count trip, not an error-rate
        trip). First outcome wins; failures are fed at recovery time,
        the success at finish."""
        if self._resil is None or self._resil.breaker is None \
                or self._breaker_fed:
            return
        self._breaker_fed = True
        if ok:
            self._resil.breaker.record_success()
        else:
            self._resil.breaker.record_failure()

    def poll(self, min_completions: int = 1,
             timeout_s: float | None = None) -> list[tuple[int, int]]:
        """Landed dest ranges since the last call. The first call returns
        the cache-served ranges immediately (instant completions); later
        calls reap the engine — failed chunks are recovered on the
        fallback path inline, and a gather quiet past the adaptive hedge
        threshold re-reads its stragglers there too (first completion
        wins). ``min_completions=0`` never blocks."""
        if self._closed:
            return []
        if self._instant:
            out, self._instant = self._instant, []
            self._mark_progress()
            return out
        tok = self._token
        if tok is None:
            return []
        out: list[tuple[int, int]] = []
        if not tok.done:
            hedge = self._resil.hedge if self._resil is not None else None
            wait_s = timeout_s
            if min_completions > 0 and hedge is not None:
                # wake at the hedge threshold: a quiet gather must get its
                # hedge decision even when the caller asked for a long wait
                quiet = time.monotonic() - self._last_prog_t
                to_hedge = max(hedge.threshold_s() - quiet, 0.005)
                wait_s = to_hedge if wait_s is None \
                    else min(wait_s, to_hedge)
            for c in self._ctx.engine.poll(tok, min_completions, wait_s):
                fi, fo, do, ln = self._chunks[c.index]
                if c.index in self._hedged or c.index in self._recovered:
                    # the fallback already served (and emitted) this chunk:
                    # this late primary completion is the race's loser —
                    # its range must not reach the consumer twice (a
                    # duplicate would double-decrement the pump's
                    # per-sample byte countdown) and its bytes are not
                    # offered for cache admission
                    if c.index in self._hedged and c.result >= 0:
                        # both sides of the hedge race moved the bytes:
                        # the loser's are the waste, whoever they belong to
                        self._scope.add("hedge_wasted_bytes", ln)
                    continue
                if c.result < 0:
                    # per-chunk failover (ISSUE 9): one bad extent no
                    # longer kills the batch — unless the deadline already
                    # expired (a late lifeboat honors nothing)
                    if not isinstance(tok._err, DeadlineExceeded) \
                            and self._chunk_fallback(c.index):
                        self._recovered.add(c.index)
                        self._mark_progress()
                        out.append((do, do + ln))
                        self._feed_breaker(ok=False)
                    else:
                        self._failed[c.index] = -c.result
                        # an unrecovered failure is a breaker outcome too
                        # (a deadline miss is the REQUEST's contract, not
                        # evidence about engine health)
                        if not isinstance(tok._err, DeadlineExceeded):
                            self._feed_breaker(ok=False)
                    continue
                self._completed.add(c.index)
                if hedge is not None:
                    hedge.observe(time.monotonic() - self._last_prog_t)
                self._mark_progress()
                out.append((do, do + ln))
                if self._cache is not None:
                    # admission offer per completion (second-touch policy
                    # decides): the bytes just landed in dest — one
                    # memcpy, never an extra read, and an early extent can
                    # serve the next batch's lookup while this batch's
                    # tail is still in flight
                    path = self._idx_paths.get(fi)
                    if path is not None:
                        self._admitted += self._cache.admit(
                            path, fo, fo + ln, self._dflat[do: do + ln],
                            tenant=self._tenant)
            if not out and min_completions > 0 and hedge is not None \
                    and not tok.done:
                quiet = time.monotonic() - self._last_prog_t
                if quiet >= hedge.threshold_s():
                    out.extend(self._fire_hedges())
        if tok.done and tok._err is not None and self._resil is not None \
                and not self._recovery_attempted:
            # token died at submit (engine death mid-gather): one
            # fallback pass over the never-completed chunks
            out.extend(self._recover_unaccounted())
        if not out and min_completions > 0 and not tok.done:
            # the pump loop (`while not g.done: g.poll(...)`) caps every
            # engine wait at its own short slices, so the ENGINE-level
            # watchdog can never fire from here — this gather-level one
            # turns a silent forever-hang into the diagnosable error
            # (finish()'s watchdog only covers callers that reach finish).
            # PIECE progress resets the clock: one huge chunk streaming
            # at full speed retires no chunk for minutes and must not
            # read as a stall.
            if tok.bytes_done != self._stall_bytes:
                self._stall_bytes = tok.bytes_done
                self._stall_t0 = time.monotonic()
            elif time.monotonic() - self._stall_t0 \
                    >= self._ctx.config.engine_wait_timeout_s:
                self._ctx.engine._note_stall("stream.poll")
                raise EngineStallError(
                    self._ctx.config.engine_wait_timeout_s,
                    list(tok._pending), "stream.poll")
        if tok.done:
            # gather drained: hand the engine back NOW — the caller may
            # keep polling instants / defer finish() without holding the
            # arbiter against other tenants (ISSUE 7 satellite)
            self._release_engine()
        elif self.done:
            # every chunk served but the token still owns in-flight loser
            # pieces (hedge winners over a wedged primary): cancel FIRST —
            # a live token owns the engine's gather path, and handing the
            # grant to the next tenant would let its gather consume the
            # losers' completions while their dest writes are still
            # kernel-owned. cancel's reap is bounded by the watchdog.
            with contextlib.suppress(Exception):
                self._ctx.engine.cancel(tok)
            self._release_engine()
        return out

    def _fire_hedges(self) -> list[tuple[int, int]]:
        """Hedge the straggler chunks on the fallback path (ISSUE 9
        tentpole #4): each incomplete chunk is re-read into a scratch
        buffer and the scratch copy wins (counted hedges_won; poll reaps
        completions on this same thread, so a chunk unaccounted here
        cannot have a delivered primary). The losing primary pieces are
        cancelled at finish(); a loser completing before that is
        discarded in poll, where its bytes count hedge_wasted_bytes —
        the race's double-moved bytes.
        Each chunk is hedged AT MOST ONCE per gather — a straggler whose
        fallback read also fails must not refire on every poll (a hedge
        storm through the serialized lifeboat, and a meaningless
        hedges_fired count).

        The winner's paste can overlap a still-in-flight loser write only
        for a wedged-but-landing primary piece, and both sides read the
        same immutable file range — byte-identical content, so the overlap
        cannot tear a value; the loser's COMPLETION (the only thing that
        could re-publish the range) is discarded above."""
        if self._resil is None:
            return []
        from strom.delivery.buffers import alloc_aligned

        scope = self._scope
        out: list[tuple[int, int]] = []
        # hedge only chunks with primary pieces IN FLIGHT: a quiet gap
        # must not serially re-read the whole not-yet-submitted gather
        # tail on the fallback (the primary will still submit all of it)
        try:
            live = self._token.pending_chunk_indices()
        # stromlint: ignore[swallowed-exceptions] -- a token without
        # pending-index support just disables hedge TARGETING this round
        # (zero chunks hedge, visible as hedges_fired staying flat); it is
        # a capability probe, not an error channel
        except Exception:
            live = set()
        for ci in self._unaccounted():
            if ci not in live or ci in self._hedge_tried:
                continue
            fi, fo, do, ln = self._chunks[ci]
            path = self._idx_paths.get(fi)
            if path is None:
                continue
            self._hedge_tried.add(ci)
            scope.add("hedges_fired")
            scratch = alloc_aligned(ln)
            if not self._resil.read_chunk_fallback(path, fo, ln,
                                                   scratch[:ln]):
                continue
            self._dflat[do: do + ln] = scratch[:ln]
            self._hedged.add(ci)
            scope.add("hedges_won")
            self._mark_progress()
            out.append((do, do + ln))
        # even an all-miss pass resets the quiet clock: the next hedge
        # decision waits a full threshold instead of re-entering per poll
        self._last_prog_t = time.monotonic()
        return out

    def _recover_unaccounted(self) -> list[tuple[int, int]]:
        self._recovery_attempted = True
        out: list[tuple[int, int]] = []
        if isinstance(self._token._err, DeadlineExceeded):
            for ci in self._unaccounted():
                self._failed[ci] = errno.ETIMEDOUT
            return out
        for ci in self._unaccounted():
            fi, fo, do, ln = self._chunks[ci]
            if self._chunk_fallback(ci):
                self._recovered.add(ci)
                self._mark_progress()
                out.append((do, do + ln))
                self._feed_breaker(ok=False)
            else:
                self._failed[ci] = self._token._err.errno or errno.EIO
                self._feed_breaker(ok=False)
        return out

    def finish(self) -> int:
        """Run the gather to full accounting, verify it, emit the stream
        span + counters, release the engine lock/demand gate. Returns
        total bytes (cache hits included). Raises the gather's first
        UNRECOVERED error — after every in-flight piece has retired,
        except for hedge losers and deadline expiry, where the remainder
        is CANCELLED (reaped bounded) before this returns."""
        if self._finished:
            return self.total_bytes
        tok = self._token
        stall_s = self._ctx.config.engine_wait_timeout_s
        last_prog = time.monotonic()
        key = None
        try:
            while tok is not None and not self.done:
                if isinstance(tok._err, DeadlineExceeded):
                    break  # fail fast: the cancel below reaps in-flight
                got = self.poll(min_completions=1, timeout_s=1.0)
                # bytes_done included: a single long chunk making steady
                # PIECE progress (reap/resubmit at constant queue depth)
                # must not read as a stall just because no CHUNK retires
                # within the watchdog
                now_key = (len(self._completed), len(self._recovered),
                           len(self._hedged), len(self._failed),
                           len(tok._pending), tok.bytes_done)
                if got or now_key != key:
                    key = now_key
                    last_prog = time.monotonic()
                elif time.monotonic() - last_prog >= stall_s:
                    self._ctx.engine._note_stall("stream.finish")
                    raise EngineStallError(stall_s, list(tok._pending),
                                           "stream.finish")
        except EngineError as e:
            # cancel BEFORE the caller can react: the kernel/worker owns
            # the in-flight pieces' dest bytes, and an abandoned wedged
            # token unwedging later would land writes into a recycled
            # batch slab (cancel's reap is itself bounded by the watchdog)
            if tok is not None and not tok.done:
                with contextlib.suppress(Exception):
                    self._ctx.engine.cancel(tok)
            self.req.mark_error(e)
            self._release()
            if isinstance(e, (DeadlineExceeded, EngineStallError)):
                raise
            raise EngineError(e.errno, f"ssd2tpu {e.strerror}") from None
        if tok is not None and not tok.done:
            # hedge losers / deadline leftovers: first completion won, the
            # primary's still-in-flight pieces are cancelled (reaped
            # bounded — no engine write outlives the gather)
            with contextlib.suppress(Exception):
                self._ctx.engine.cancel(tok)
        self._release_engine()
        deadline_miss = tok is not None \
            and isinstance(tok._err, DeadlineExceeded) \
            and (self._failed or self._unaccounted())
        if self._failed or deadline_miss:
            err = tok._err if tok is not None and tok._err is not None \
                else EngineError(errno.EIO,
                                 f"{len(self._failed)} chunk(s) failed")
            self.req.mark_error(err)
            self._release()
            if isinstance(err, (DeadlineExceeded, EngineStallError)):
                raise err
            raise EngineError(err.errno or errno.EIO,
                              f"ssd2tpu {err.strerror}") from None
        if tok is not None:
            if self._hedged or self._recovered:
                missing = self._unaccounted()
                if missing:
                    err = EngineError(
                        errno.EIO, f"ssd2tpu streamed gather left "
                                   f"{len(missing)} chunk(s) unserved")
                    self.req.mark_error(err)
                    self._release()
                    raise err
            elif tok.bytes_done != self._miss_planned:
                # cheap insurance, same as _read_segments: any engine
                # accounting bug surfaces loudly, not as a zero-tailed
                # batch (byte-exact only when every chunk was engine-
                # served; fallback-served chunks are accounted per chunk)
                err = EngineError(
                    errno.EIO, f"ssd2tpu streamed read {tok.bytes_done} "
                               f"bytes, planned {self._miss_planned}")
                self.req.mark_error(err)
                self._release()
                raise err
        # the gather served every chunk: the breaker hears the success
        # (recoveries already fed their failure above — first outcome wins)
        if tok is not None:
            self._feed_breaker(ok=True)
        self._release()
        self._scope.add("ssd2tpu_bytes", self.total_bytes)
        return self.total_bytes

    def _release_engine(self) -> None:
        """Drop the engine-path resources (demand gate + scheduler grant /
        engine lock). Idempotent; called the moment the token drains —
        every in-flight piece retired — so the stats/span/admission tail
        of finish() runs with the engine already handed to the next
        tenant in the fair drain."""
        if self._engine_released:
            return
        self._engine_released = True
        self._stack.close()

    def _release(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._closed = True
        tok = self._token
        if tok is not None:
            self._scope.gauge("stream_inflight_peak").max(tok.inflight_peak)
            # keep the stall attribution's `read` bucket lit on streamed
            # batches: the async token never passes through read_vectored's
            # instrumented wrappers, so the engine window is billed here
            end = self._last_c_us if self._last_c_us is not None \
                else _events_ring.now_us()
            self.req.record("stream.read", "read", self.t0_us,
                            max(end - self.t0_us, 0),
                            {"ops": len(self._chunks),
                             "bytes": self._miss_planned})
        if self._first_c_us is not None and self._last_c_us is not None:
            # the spread the old barrier serialized on: how long the
            # slowest extent lagged the first completion — with streaming,
            # work done during this window is the win
            self._scope.observe_us("stream_tail_extent",
                                   self._last_c_us - self._first_c_us)
        if self._admitted:
            self.req.record("cache.admit", "cache", self.t0_us,
                            _events_ring.now_us() - self.t0_us,
                            {"bytes": self._admitted})
        self.req.record("stream.gather", "stream", self.t0_us,
                        _events_ring.now_us() - self.t0_us,
                        {"bytes": self.total_bytes,
                         "instant_bytes": self.instant_bytes,
                         "ops": len(self._chunks)})
        self._release_engine()
        if self._own_req:
            self.req.finish()

    def close(self) -> None:
        """Idempotent teardown. A live token is CANCELLED: every in-flight
        piece is reaped before the engine lock releases, so no completion
        (and no engine write into *dest*) outlives the gather — the
        leaked-pin/leaked-completion contract tests assert this."""
        if self._finished:
            return
        if self._token is not None and not self._token.done:
            with contextlib.suppress(Exception):
                self._ctx.engine.cancel(self._token)
        self._release_engine()
        self._release()

    def __enter__(self) -> "StreamingGather":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
