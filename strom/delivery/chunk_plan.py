"""Extent-aware gather planning: make the FIEMAP map load-bearing.

The reference resolves file offset → device LBA in-kernel and builds NVMe
requests in physical terms (SURVEY.md §2.1 "Extent resolver", §3.3; reference
cite UNVERIFIED — empty mount, SURVEY.md §0). A userspace io_uring engine
submits in (fd, logical offset) terms, but the physical map still buys
something on fragmented files: splitting gather chunks at extent boundaries
and issuing them in PHYSICAL-address order turns a logically-sequential read
of a fragmented file — which the device sees as random LBA hops — into a
near-sequential LBA stream. On a contiguous file (the common case) the plan
is byte-identical to the naive one and costs one cached FIEMAP per file.
"""

from __future__ import annotations

import bisect
from typing import Sequence

from strom.probe.fiemap import Extent

# an engine gather chunk: (file_idx, file_offset, dest_offset, length)
Chunk = tuple[int, int, int, int]


def plan_chunks_multi(chunks: Sequence[Chunk],
                      extent_maps: dict[int, Sequence[Extent]]) -> list[Chunk]:
    """Extent-aware planning over a gather spanning several files (format
    readers' ExtentLists, striped members): chunks group by file — stable in
    first-appearance order, so a per-sample interleaving becomes per-file
    runs — and each group is planned against its file's FIEMAP map when one
    is available. Any submission order is valid (dest offsets are explicit);
    only locality changes."""
    groups: dict[int, list[Chunk]] = {}  # insertion-ordered
    for c in chunks:
        groups.setdefault(c[0], []).append(c)
    out: list[Chunk] = []
    for fi, g in groups.items():
        em = extent_maps.get(fi)
        out.extend(plan_chunks(g, em) if em else g)
    return out


def plan_chunks(chunks: Sequence[Chunk], extents: Sequence[Extent]
                ) -> list[Chunk]:
    """Split *chunks* (all for one file, mapped by *extents*) at extent
    boundaries and order them by physical address.

    Correctness invariant (property-tested): the output covers exactly the
    same file_offset→dest_offset byte mapping as the input — only the split
    points and submission order change, and the engine's vectored gather
    carries explicit dest offsets, so any order is valid.

    Bytes not covered by a reliable extent (holes, delalloc, unknown) keep
    logical order after all physically-mapped bytes.
    """
    ext = [e for e in extents if e.is_reliable and e.length > 0]
    if len(ext) <= 1:
        return list(chunks)
    ext.sort(key=lambda e: e.logical)
    starts = [e.logical for e in ext]

    # (physical_or_None, file_idx, file_off, dest_off, len)
    tagged: list[tuple[int | None, int, int, int, int]] = []
    for fi, off, doff, ln in chunks:
        pos, end = off, off + ln
        while pos < end:
            i = bisect.bisect_right(starts, pos) - 1
            phys: int | None = None
            if i >= 0 and pos < ext[i].logical + ext[i].length:
                e = ext[i]
                seg_end = min(end, e.logical + e.length)
                phys = e.physical + (pos - e.logical)
            elif i + 1 < len(starts):
                seg_end = min(end, starts[i + 1])  # gap before next extent
            else:
                seg_end = end                      # past the last extent
            tagged.append((phys, fi, pos, doff + (pos - off), seg_end - pos))
            pos = seg_end

    tagged.sort(key=lambda t: (t[0] is None,
                               t[0] if t[0] is not None else t[2]))

    # merge neighbors that are contiguous in file, dest AND physical terms —
    # re-joins the splits inside one extent run so chunk count only grows
    # where the file is actually fragmented
    out: list[tuple[int | None, int, int, int, int]] = []
    for phys, fi, off, doff, ln in tagged:
        if out:
            p0, f0, o0, d0, l0 = out[-1]
            if (f0 == fi and o0 + l0 == off and d0 + l0 == doff
                    and p0 is not None and phys is not None
                    and p0 + l0 == phys):
                out[-1] = (p0, f0, o0, d0, l0 + ln)
                continue
        out.append((phys, fi, off, doff, ln))
    return [(fi, off, doff, ln) for (_, fi, off, doff, ln) in out]
