"""memcpy_ssd2tpu — the hot path.

Reference hot path (SURVEY.md §3.3; reference cite UNVERIFIED — empty mount,
SURVEY.md §0): MEMCPY_SSD2GPU_ASYNC chunks a file range, resolves extents
(raid0 math included), submits NVMe READs whose PRPs point at pinned GPU
pages, and MEMCPY_WAIT joins the completion countdown.  strom-tpu equivalent,
per BASELINE.json:5: plan per-device byte ranges from the requested
`NamedSharding`, io_uring-read them O_DIRECT into page-aligned host slabs
(zero bounce), `jax.device_put` each slab to its device (host→HBM DMA owned
by the TPU runtime), and assemble the global `jax.Array` with
`jax.make_array_from_single_device_arrays`.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import dataclasses
import errno
import io
import math
import os
import queue
import threading
import time
from typing import Any, Sequence

import numpy as np

from strom.config import StromConfig
from strom.delivery.buffers import SlabPool, alloc_aligned
from strom.delivery.coalesce import coalesce_chunks, coalesce_segments
from strom.delivery.extents import ExtentList
from strom.delivery.handle import DMAHandle, deferred_handle
from strom.delivery.hotcache import HotCache
from strom.delivery.shard import DevicePlan, Segment, dedupe_plans, plan_sharded_read
from strom.engine import make_engine
from strom.engine.base import (DeadlineExceeded, Engine, EngineError,
                               EngineStallError)
from strom.engine.raid0 import (count_stripe_windows, plan_stripe_reads,
                                plan_stripe_windows)
from strom.obs import request as _request
from strom.obs.events import ring as _events_ring
from strom.utils.locks import make_lock
from strom.utils.stats import global_stats


@dataclasses.dataclass(frozen=True)
class StripedFile:
    """A logical file striped RAID0-style over member files/devices.

    Userspace twin of the reference's in-kernel md-raid0 decode: identical
    chunk math, applied before submission instead of inside the kmod
    (SURVEY.md §2.2 "md-raid0 decode").
    """

    members: tuple[str, ...]
    chunk: int
    # logical size override: a file striped with zero padding to a full
    # stripe width (engine/raid0.stripe_file) reports its TRUE size here, so
    # formats with trailing metadata (parquet footers) see the real EOF and
    # record counting (rawbin) never counts padding as data
    size_bytes: int | None = None

    @property
    def size(self) -> int:
        if self.size_bytes is not None:
            return self.size_bytes
        # cached: size is consulted per pread/memcpy (via source_size), and
        # re-opening the sidecar each time is both a syscall tax and a window
        # for a mid-run rewrite to shift the perceived EOF
        cached = getattr(self, "_size_cache", None)
        if cached is not None:
            return cached
        sizes = [os.stat(m).st_size for m in self.members]
        usable = min(sizes) // self.chunk * self.chunk
        capacity = usable * len(self.members)
        size = capacity
        # sets written by stripe_file carry their true size in a sidecar;
        # honoring it here closes the silent-zero-pad trap even when the
        # caller forgot to pass size= at registration
        from strom.engine.raid0 import SIZE_SIDECAR_SUFFIX

        try:
            with open(self.members[0] + SIZE_SIDECAR_SUFFIX) as f:
                claimed = int(f.read())
            # a stale sidecar (members re-striped underneath it) could claim
            # anything; only trust a value the members can actually hold
            if 0 < claimed <= capacity:
                size = claimed
        except (OSError, ValueError):
            pass
        object.__setattr__(self, "_size_cache", size)
        return size


# anything memcpy_ssd2tpu / pread can read from
Source = str | StripedFile | ExtentList


# jitted helpers for streamed assembly, created lazily (this module must not
# import jax at import time) and cached so jax's compile cache keys stay
# stable across calls
_jit_cache: dict = {}


def _alloc_on_device(n_elems: int, dtype, device):
    """Allocate a zeroed device buffer WITHOUT host->device traffic (the
    zeros kernel runs on the device)."""
    import jax
    import jax.numpy as jnp

    fn = _jit_cache.get(("zeros", device))
    if fn is None:
        sharding = jax.sharding.SingleDeviceSharding(device) \
            if device is not None else None
        fn = jax.jit(lambda n, dt: jnp.zeros((n,), dt),
                     static_argnums=(0, 1), out_shardings=sharding)
        _jit_cache[("zeros", device)] = fn
    return fn(n_elems, jnp.dtype(dtype))


def _paste(buf, piece, off: int):
    """Donated in-place paste: XLA aliases the donated buffer, so assembling
    N bytes from pieces peaks at ~N + piece_size on device — an on-device
    jnp.concatenate of the pieces would peak at ~2N."""
    import jax
    from jax import lax

    fn = _jit_cache.get("paste")
    if fn is None:
        fn = jax.jit(lambda b, p, o: lax.dynamic_update_slice(b, p, (o,)),
                     donate_argnums=(0,))
        _jit_cache["paste"] = fn
    return fn(buf, piece, off)


def _reshape_donated(buf, shape: tuple):
    import jax

    fn = _jit_cache.get("reshape")
    if fn is None:
        fn = jax.jit(lambda b, s: b.reshape(s), static_argnums=(1,),
                     donate_argnums=(0,))
        _jit_cache["reshape"] = fn
    return fn(buf, tuple(shape))


def split_segments(segments: Sequence[Segment], chunk: int
                   ) -> list[tuple[int, int, list[Segment]]]:
    """Cut a dest-contiguous segment list into pieces of <= *chunk* dest
    bytes: [(piece_dest_base, piece_nbytes, [Segment(dest rebased to 0)])].

    The pieces tile the dest space in order, so a streamed transfer can read
    piece k+1 while piece k's host->HBM transfer is in flight and concatenate
    the delivered pieces back into the full array. Pure function (unit-tested
    in tests/test_streaming.py)."""
    segs = sorted(segments, key=lambda s: s.dest_offset)
    total = sum(s.length for s in segs)
    pieces: list[tuple[int, int, list[Segment]]] = []
    base = 0
    si = 0
    within = 0  # consumed bytes of segs[si]
    while base < total:
        take = min(chunk, total - base)
        out: list[Segment] = []
        need = take
        while need > 0:
            s = segs[si]
            part = min(need, s.length - within)
            out.append(Segment(s.file_offset + within,
                               (s.dest_offset + within) - base, part))
            within += part
            need -= part
            if within == s.length:
                si += 1
                within = 0
        pieces.append((base, take, out))
        base += take
    return pieces


def source_size(source: Source) -> int:
    return source.size if isinstance(source, (StripedFile, ExtentList)) \
        else os.stat(source).st_size


class SourceIO(io.RawIOBase):
    """Minimal seekable file-like over any delivery Source (StripedFile,
    ExtentList, or path), reading through ``ctx.pread``. For library code
    that wants a file object against engine-backed sources — e.g. indexing a
    tar or reading Parquet metadata on a striped set.

    Small reads are served from a *readahead* window (one engine round-trip
    per window, not per read): a tar header walk issues one 512-byte read
    per member, which naively costs an engine submit/wait + fresh slab each
    — ~100k round-trips to index a 50k-sample shard. Bulk payload bytes
    should still flow through gather reads, not this adapter."""

    def __init__(self, ctx: "StromContext", source: Source,
                 readahead: int = 1 << 20):
        self._ctx = ctx
        self._source = source
        self._size = source_size(ctx.resolve_source(source))
        self._pos = 0
        self._ra = max(readahead, 1)
        self._buf = b""
        self._buf_off = 0  # source offset of _buf[0]

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = io.SEEK_SET) -> int:
        try:
            base = {io.SEEK_SET: 0, io.SEEK_CUR: self._pos,
                    io.SEEK_END: self._size}[whence]
        except KeyError:
            raise ValueError(f"unsupported whence {whence}") from None
        pos = base + offset
        if pos < 0:
            # io.IOBase semantics: fail here, not as a confusing EngineError
            # from a later pread at a negative offset
            raise ValueError(f"negative seek position {pos}")
        self._pos = pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self._size - self._pos
        n = min(n, self._size - self._pos)
        if n <= 0:
            return b""
        lo = self._pos - self._buf_off
        if not (0 <= lo and lo + n <= len(self._buf)):
            fetch = min(max(n, self._ra), self._size - self._pos)
            self._buf = self._ctx.pread(self._source, self._pos,
                                        fetch).tobytes()
            self._buf_off = self._pos
            lo = 0
        data = self._buf[lo: lo + n]
        self._pos += len(data)
        return data


class _SpillEngineIo:
    """Engine router for spill-tier I/O (ISSUE 14 satellite, ROADMAP item
    2 residual b): demotion writes and spill-serve reads ride the
    context's engine path — O_DIRECT on the spill file, scheduler-granted
    as the BACKGROUND class, billed to the "spill" tenant — instead of
    page-cache pread/pwrite. ``write``/``read`` return False whenever
    enqueueing is unsafe or fails, and the tier falls back to its buffered
    fd (counted: ``spill_fallback_ops``): unsafe means the calling thread
    already holds a scheduler grant, or — writes only — ANY exclusive
    grant is outstanding (a demote fired from a mid-gather admission on
    the pump thread must not queue behind a grant its own progress
    releases). The two-phase allocate/publish discipline is unchanged:
    none of this runs under the tier lock."""

    def __init__(self, ctx, path: str):
        self._ctx = ctx
        self._path = path
        self._closed = False
        # registered EAGERLY (the file exists — the tier created it), so
        # no lazy-registration lock is needed. O_DIRECT preferred,
        # probed down PER REGISTRATION to buffered where the spill dir's
        # fs refuses it (tmpfs) — never to the context's configured
        # o_direct, which may itself be a hard True the spill fs can't
        # honor, and never leaving a half-registered pair behind.
        def _reg(writable: bool) -> int:
            try:
                return ctx.engine.register_file(path, o_direct=True,
                                                writable=writable)
            except OSError:
                return ctx.engine.register_file(path, o_direct=False,
                                                writable=writable)

        self._wfi = _reg(True)
        try:
            self._rfi = _reg(False)
        except BaseException:
            with contextlib.suppress(Exception):
                ctx.engine.unregister_file(self._wfi)
            raise

    def _safe(self, *, write: bool) -> bool:
        sched = self._ctx._scheduler
        if sched is None or self._closed or self._ctx._closed:
            return False
        if sched.held_by_me():
            return False
        return not write or sched.engine_idle()

    def write(self, data: np.ndarray, off: int) -> bool:
        if not self._safe(write=True):
            return False
        try:
            self._ctx._scheduler.write_chunks(
                [(self._wfi, off, 0, data.nbytes)], data, tenant="spill",
                retries=self._ctx.config.io_retries, priority="background")
            return True
        # stromlint: ignore[swallowed-exceptions] -- advisory route: any
        # engine-path failure degrades to the buffered-fd fallback (the
        # bytes still land) and is counted below
        except Exception:
            self._ctx.scope.add("spill_errors")
            return False

    def read(self, dest: np.ndarray, off: int, n: int) -> bool:
        if not self._safe(write=False):
            return False
        try:
            got = self._ctx._scheduler.read_chunks(
                [(self._rfi, off, 0, n)], dest, tenant="spill",
                retries=self._ctx.config.io_retries, priority="background")
            return got == n
        # stromlint: ignore[swallowed-exceptions] -- advisory route, same
        # degrade-to-fallback contract as write(); counted
        except Exception:
            self._ctx.scope.add("spill_errors")
            return False

    def close(self) -> None:
        self._closed = True
        for fi in (self._wfi, self._rfi):
            with contextlib.suppress(Exception):
                self._ctx.engine.unregister_file(fi)


class StromContext:
    """Owns the engine, file-registration cache and delivery executor.

    One per process is typical (module-level default, see strom/__init__.py);
    tests create isolated instances.
    """

    def __init__(self, config: StromConfig | None = None,
                 engine: Engine | None = None, *,
                 metrics_port: int | None = None,
                 scope: "dict | None | object" = None):
        self.config = config or StromConfig.from_env()
        self._witness_enabled_here = False
        if self.config.debug_locks:
            # enable BEFORE the engine and every subsystem lock below is
            # constructed, so their make_lock calls return WitnessLocks
            # (ISSUE 11; module-level locks created at import time need
            # STROM_DEBUG_LOCKS=1 instead). close() reverts — a
            # diagnostic context must not leave every later context in
            # the process paying witness overhead it never asked for.
            from strom.utils import locks as _locks

            self._witness_enabled_here = not _locks.witness_enabled()
            _locks.enable_witness(True)
            if self.config.flight_dir:
                # a cycle's bundle lands where the operator already asked
                # crash bundles to go (env STROM_FLIGHT_DIR still wins
                # for recorder-less runs — it seeded locks at import)
                _locks.set_flight_dir(self.config.flight_dir)
        self.engine = engine or make_engine(self.config)
        # fault injection (ISSUE 9 tentpole, strom/faults): a configured
        # fault plan wraps the engine in the FaultyEngine proxy BEFORE
        # anything (scheduler, resilience, locks) binds it — every read
        # this context issues runs under the plan's deterministic chaos
        if self.config.fault_plan:
            from strom.faults import FaultPlan, FaultyEngine

            if not isinstance(self.engine, FaultyEngine):
                self.engine = FaultyEngine(
                    self.engine, FaultPlan.from_spec(self.config.fault_plan))
        # telemetry scope (ISSUE 6 tentpole): a dict of labels becomes a
        # label-scoped child view of the global registry — every delivery
        # counter/histogram written through it lands in BOTH the scoped
        # series (a Prometheus-labeled twin on /metrics) and the unlabeled
        # aggregate. None = the identity scope (global registry, the
        # single-tenant behavior). A prebuilt ScopedStats passes through so
        # several contexts can share one tenant scope.
        if scope is None:
            self.scope = global_stats
        elif isinstance(scope, dict):
            self.scope = global_stats.scoped(**scope)
        else:
            self.scope = scope
        self.engine.set_scope(self.scope)
        self._files: dict[str, int] = {}
        # writable registrations (ISSUE 13 write path): separate indexes —
        # the read side keeps its O_RDONLY fds and probe state
        self._wfiles: dict[str, int] = {}
        # path → StripedFile aliases (register_striped): lets format readers
        # that traffic in path-keyed extents (tar members, Parquet column
        # chunks) ride RAID0 without knowing about striping
        self._striped: dict[str, StripedFile] = {}
        # FIEMAP extent map per registered file: list[Extent] when mapped,
        # None when the fs can't say (tmpfs, old kernels) — probed once
        self._extent_maps: dict[str, list | None] = {}
        self._files_lock = make_lock("app.files")
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, self.config.delivery_workers),
            thread_name_prefix="strom-delivery")
        # per-device-group tasks within ONE sharded transfer; a separate pool
        # from _executor because async transfers run their whole run() there —
        # submitting group tasks to the same pool could deadlock with every
        # worker occupied by a transfer waiting on its own groups
        self._group_executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, self.config.delivery_workers),
            thread_name_prefix="strom-groups")
        # engine ops are pipelined internally; serialize whole-transfer use of
        # the engine so concurrent handles don't interleave queue-depth
        # budgets. Multi-ring engines serialize internally PER RING instead
        # (concurrent_gathers) — locking here would re-serialize the very
        # transfers the rings exist to interleave.
        self._engine_lock = contextlib.nullcontext() \
            if getattr(self.engine, "concurrent_gathers", False) \
            else make_lock("engine.transfer")
        # process-lifetime unique tags: stale completions from a failed
        # transfer can never alias a later transfer's ops
        self._tag_counter = 0
        if self.config.numa_affinity:
            from strom.utils.numa import NumaAffinity

            self._numa = NumaAffinity(node=self.config.numa_node,
                                      steer_irqs=self.config.irq_affinity)
        else:
            self._numa = None
        self._slab_pool = SlabPool(
            self.config.slab_pool_bytes,
            pin=self.config.slab_mlock_bytes > 0,
            max_mlock_bytes=self.config.slab_mlock_bytes,
            huge=self.config.huge_pages,
            on_alloc=self._on_slab_alloc) \
            if self.config.slab_pool_bytes > 0 else None
        # multi-tenant I/O scheduler (ISSUE 7 tentpole, strom/sched): the
        # shared arbiter that replaces the per-transfer engine lock —
        # per-tenant queues with priority classes, weighted fair drain at
        # slice granularity, byte/IOPS budgets, slab-pool admission
        # control. Every scheduled gather below (_read_segments slices,
        # StreamingGather grants, the readahead's background-class warm
        # reads) routes through it; sched_enabled=False keeps the
        # pre-scheduler lock-per-transfer behavior.
        self._scheduler = None
        self._tenant_reg_lock = make_lock("app.tenant_reg")
        if self.config.sched_enabled:
            from strom.sched.scheduler import IoScheduler

            self._scheduler = IoScheduler(self.engine, self.config,
                                          pool=self._slab_pool,
                                          scope=self.scope)
        # resilience layer (ISSUE 9 tentpole, strom/delivery/resilient.py):
        # per-engine circuit breaker + python-engine failover + hedge
        # control. Every demand gather's outcome feeds the breaker; while
        # it is open, reads reroute to the fallback path and the degraded
        # state is visible on /stats["resilience"], /tenants and the
        # breaker_* gauges. The flight-recorder trip hook is attached
        # after the recorder exists (below).
        from strom.delivery.resilient import ResilientIo

        self._resilience = ResilientIo(self.config, self.engine,
                                       scope=self.scope)
        if self._scheduler is not None:
            self._scheduler.resilience_info = self._resilience.stats
        # per-tenant SLO engine (ISSUE 8 tentpole, strom/obs/slo.py):
        # every finished traced request feeds good/bad window accounting;
        # burn rates surface on /slo, as slo_* gauges per tenant scope,
        # and as the slo_burning flag on /tenants rows (scheduler hook).
        from strom.obs.slo import SloEngine

        self._slo = SloEngine(goodput_fn=self._current_goodput)
        # requests minted by THIS context carry this token; the observer
        # list is process-global, so without the filter two live contexts
        # would feed each other's SLO engines (phantom tenant rows, a
        # healthy context's slo_ok flipped by its neighbor's slow gathers)
        self._req_owner: object = object()

        def _observe(req, _slo=self._slo, _own=self._req_owner):
            if req.owner is None or req.owner is _own:
                _slo.observe_request(req)

        self._slo_observer = _observe
        if self._scheduler is not None:
            self._scheduler.slo_hook = self._slo.burning
        # hot-set host cache (ISSUE 4 tentpole, strom/delivery/hotcache.py):
        # repeat traffic serves from RAM instead of re-gathering from NVMe.
        # Buffers come from the slab pool (NUMA-placed, engine-registered);
        # bound_depth subtracts hot_cache_bytes from the pool budget so
        # prefetch auto-depth and the cache never double-commit slab memory.
        self._hot_cache = HotCache(
            self.config.hot_cache_bytes, pool=self._slab_pool,
            admit=self.config.hot_cache_admit,
            block_bytes=self.config.hot_cache_block_bytes,
            scope=self.scope) \
            if self.config.hot_cache_bytes > 0 else None
        # NVMe spill tier (ISSUE 13 tentpole, strom/delivery/spill.py):
        # evicted-but-warm cache entries demote to a dedicated spill file
        # instead of vanishing; the cache consult serves them back with
        # zero source-engine reads (RAM -> NVMe -> source hierarchy).
        self._spill = None
        if self.config.spill_bytes > 0 and self._hot_cache is not None:
            import tempfile

            from strom.delivery.spill import SpillTier

            sdir = self.config.spill_dir or tempfile.gettempdir()
            os.makedirs(sdir, exist_ok=True)
            self._spill = SpillTier(
                os.path.join(sdir,
                             f"strom-spill-{os.getpid()}-{id(self):x}.bin"),
                self.config.spill_bytes, scope=self.scope,
                compress=self.config.spill_compress)
            if self.config.spill_engine_io and self._scheduler is not None:
                # spill I/O rides the engines (ISSUE 14 satellite):
                # O_DIRECT + background-class grants; attached after the
                # tier so registration sees the created file. The router
                # is ADVISORY — if even buffered registration fails, the
                # tier keeps its legacy fd path (counted, not fatal:
                # a spill tier must never abort context construction)
                try:
                    self._spill.set_io(_SpillEngineIo(
                        self, self._spill.path))
                except OSError:
                    self.scope.add("spill_errors")
            self._hot_cache.spill = self._spill
        # distributed data plane (ISSUE 15 tentpole, strom/dist): the peer
        # extent service. serve_peers() starts the exporter (other hosts
        # read THIS host's hot extents over the socket); attach_peers()
        # wires the client tier the delivery consult probes after local
        # RAM/spill and before the engine. Both None = single-host
        # behavior unchanged.
        self._peer_tier = None
        self._peer_server = None
        self._decoded_cache = None
        # cluster observability plane (ISSUE 18, strom/obs/federation.py):
        # attach_cluster() on the coordinator polls every worker's /stats,
        # merges them and watches fleet health; None = no /cluster route
        self._cluster = None
        # closed-loop knob autotuner (ISSUE 16 tentpole, strom/tune):
        # armed below after every knob surface exists; None until
        # attach_tuner() (config.tune=False = no controller, no thread,
        # every knob byte-identical to the hand configuration)
        self._tuner = None
        # live pipeline surfaces the tuner can steer (ISSUE 19 satellite):
        # pipelines register their decode pool / readahead here at build;
        # standard_knobs() turns whatever is present into knobs. Last
        # registration of a kind wins (one live pipeline per context is
        # the common shape; a rebuilt pipeline re-registers).
        self._tunables: dict = {}
        # in-flight DEMAND gathers (not readahead): the readahead thread
        # checks this between engine-budget-sized slices and yields, so a
        # consumer's read never queues behind more than one warming slice
        self._demand_lock = make_lock("app.demand")
        self._demand_reads = 0
        # one host->HBM stream at a time (see StromConfig.serialize_device_put)
        self._put_lock = make_lock("app.put") if self.config.serialize_device_put \
            else contextlib.nullcontext()
        # live observability endpoint (strom/obs/server.py): /metrics,
        # /stats, /trace on 127.0.0.1 for the context's lifetime. Explicit
        # metrics_port overrides the config knob; 0 from config = off, an
        # explicit 0 asks the OS for an ephemeral port (server.port tells).
        self._metrics_server = None
        # stats()["steps"] attributes only events from THIS context's
        # lifetime: the ring is process-global and never cleared, so an
        # unwindowed summary in a multi-phase process would intersect a
        # later phase's spans against an EARLIER phase's step windows
        self._obs_t0_us = _events_ring.now_us()
        # steps-section cache: full-ring attribution costs ~170ms on a
        # 1-core box, so a scraper polling /metrics must not pay (and
        # steal from decode workers) more than once per TTL
        self._steps_cache: tuple[float, dict] | None = None
        self._steps_cache_lock = make_lock("app.steps_cache")
        # per-bucket stall totals already published as global counters
        # (ISSUE 18 / ROADMAP 5 residual): each steps recompute pushes the
        # window's GROWTH into stall_<bucket>_us counters, so /history's
        # rate() turns the attribution into per-second burn the autotuner
        # can steer on. Guarded by _steps_cache_lock.
        self._stall_published: dict[str, float] = {}
        # flight recorder (ISSUE 6 tentpole, strom/obs/flight.py): with a
        # flight_dir configured, a watchdog samples progress/pressure for
        # the context's lifetime and dumps an atomic crash bundle on
        # SIGTERM / unhandled exception / no-step-progress — the post-
        # mortem for runs that die the way BENCH_r05 did (rc=124, nothing
        # to diagnose). Created BEFORE the live server so /flight can
        # serve the recorder's sample history, not just a point capture.
        self._flight = None
        if self.config.flight_dir:
            from strom.obs.flight import FlightRecorder

            self._flight = FlightRecorder(
                self.config.flight_dir, ctx=self,
                stall_s=self.config.flight_stall_s)
        if self._flight is not None and self._resilience.breaker is not None:
            # breaker trip → flight bundle (ISSUE 9 satellite): the moment
            # the engine is declared sick is exactly the moment an operator
            # wants the trace/stacks/counters that led up to it
            flight = self._flight

            def _dump_on_trip(note: str, _f=flight) -> None:
                with contextlib.suppress(Exception):
                    _f.dump("breaker_trip", note=note)

            self._resilience.breaker.on_trip = _dump_on_trip
        port = self.config.metrics_port if metrics_port is None else metrics_port
        self._history = None
        if port is not None and (port > 0 or metrics_port == 0):
            from strom.obs.server import MetricsServer

            # snapshot history (ISSUE 8 tentpole, strom/obs/history.py):
            # rides with the live server — a process someone can scrape is
            # a process someone will want rates from. Created first so the
            # /history route is live the moment the port is.
            if self.config.history_interval_s > 0:
                from strom.obs.history import StatsHistory

                self._history = StatsHistory(
                    interval_s=self.config.history_interval_s)
            try:
                self._metrics_server = MetricsServer(
                    self.stats, port=port, flight=self._flight, ctx=self)
            except Exception:
                # a failed bind must not leak the sampler/watchdog threads
                # just started for a context that will never exist
                if self._history is not None:
                    self._history.close()
                if self._flight is not None:
                    self._flight.close()
                raise
        # registered LAST: a process-global observer pointing at a context
        # whose __init__ failed would pin the half-built context (and feed
        # its SLO engine from every later request) for the process lifetime
        _request.add_observer(self._slo_observer)
        # knob autotuner (ISSUE 16): armed last — every knob surface
        # (scheduler, cache) exists and the observability endpoint is
        # already live to expose stats()["tune"]. config.tune_profile
        # warm-starts the search from a previous run's converged point.
        if self.config.tune:
            self.attach_tuner(
                profile_path=self.config.tune_profile or None)
        self._closed = False

    @property
    def metrics_server(self):
        """The live endpoint when one was requested (``.port`` carries the
        bound port), else None."""
        return self._metrics_server

    @property
    def flight_recorder(self):
        """The flight recorder when ``flight_dir`` is configured, else
        None (the /flight route still captures on demand without one)."""
        return self._flight

    @property
    def hot_cache(self) -> HotCache | None:
        """The hot-set cache when ``hot_cache_bytes > 0``, else None."""
        return self._hot_cache

    @property
    def spill_tier(self):
        """The NVMe spill tier when ``spill_bytes > 0`` (and a hot cache
        exists), else None (strom/delivery/spill.py)."""
        return self._spill

    @property
    def slo(self):
        """The per-tenant SLO engine (always on — targets default loose;
        customize via ``ctx.slo.set_target(tenant, ...)``)."""
        return self._slo

    @property
    def history(self):
        """The snapshot-history ring when the live server is on (and
        ``history_interval_s > 0``), else None."""
        return self._history

    def _current_goodput(self) -> "float | None":
        """The stall-attribution goodput for SLO goodput targets (rides
        the steps section's TTL cache, so /slo scrapes stay cheap)."""
        try:
            return self.stats(sections=["steps"])["steps"].get("goodput_pct")
        # stromlint: ignore[swallowed-exceptions] -- None IS the documented
        # 'goodput unknown' value (the SLO engine skips goodput targets on
        # it); a closing context mid-scrape is a legal way to not know
        except Exception:
            return None

    def _stall_deltas_locked(self, summary: dict) -> "dict[str, int]":
        """Growth of each stall-attribution bucket's total since the last
        publication, as ``stall_<bucket>_us`` counter increments (caller
        holds ``_steps_cache_lock``; the window is the whole retained ring,
        so a drop-oldest wrap can SHRINK a total — clamp to zero growth and
        re-anchor rather than publish a negative counter delta)."""
        out: dict[str, int] = {}
        for b, v in summary.get("buckets", {}).items():
            total = float(v.get("total_us", 0.0))
            last = self._stall_published.get(b, 0.0)
            if total > last:
                out[f"stall_{b}_us"] = int(total - last)
            self._stall_published[b] = total
        return out

    @property
    def scheduler(self):
        """The multi-tenant I/O scheduler when ``sched_enabled``, else
        None (strom/sched/scheduler.py)."""
        return self._scheduler

    @property
    def resilience(self):
        """The breaker/failover/hedge layer (strom/delivery/resilient.py)."""
        return self._resilience

    def register_tenant(self, name: str, *, priority: str = "training",
                        weight: int = 1, byte_rate: float = 0,
                        byte_burst: float | None = None, iops: float = 0,
                        hot_cache_bytes: int = 0):
        """Register a tenant with the scheduler (priority class, fair-drain
        weight, byte/IOPS budgets) and, when the context has a hot cache,
        carve its per-tenant cache partition. Returns the Tenant handle;
        raises when the scheduler is disabled. Pipelines reference the
        tenant by labeling their scope: ``scope={"pipeline": "resnet",
        "tenant": name}``."""
        if self._scheduler is None:
            raise RuntimeError("sched_enabled=False: no scheduler to "
                               "register tenants with")
        # serialized: two concurrent POST /tenants registers of one name
        # must never interleave the is_registered check with the
        # scheduler-register + cache-partition pair — the loser would carve
        # a partition for a handle whose budgets the winner already
        # customized (partial registration, ISSUE 8 satellite)
        with self._tenant_reg_lock:
            if self._scheduler.is_registered(name):
                # re-register returns the live handle UNCHANGED (scheduler
                # contract: queue state and budget balances survive) — so
                # the cache partition must not silently resize either;
                # applying only the hot_cache_bytes of a new config would
                # diverge scheduler and cache state with no indication
                return self._scheduler.tenant(name)
            t = self._scheduler.register(
                name, priority=priority, weight=weight, byte_rate=byte_rate,
                byte_burst=byte_burst, iops=iops,
                hot_cache_bytes=hot_cache_bytes)
            if hot_cache_bytes and self._hot_cache is not None:
                self._hot_cache.set_partition(name, hot_cache_bytes)
                if self._spill is not None:
                    # the spill carve-out mirrors the RAM one (ISSUE 13):
                    # a tenant's demoted working set is bounded the same
                    # way its resident one is
                    self._spill.set_partition(name, hot_cache_bytes)
            return t

    # -- distributed data plane (ISSUE 15, strom/dist) ----------------------
    @property
    def peer_tier(self):
        """The peer extent client when :meth:`attach_peers` wired one,
        else None (strom/dist/peers.py)."""
        return self._peer_tier

    @property
    def peer_server(self):
        """The peer extent exporter when :meth:`serve_peers` started one,
        else None."""
        return self._peer_server

    def serve_peers(self, port: int = 0, host: str = "127.0.0.1") -> str:
        """Start the peer extent service: a bounded threaded TCP server
        exporting this context's hot-cache/spill extents by their
        ``(path, physical offset)`` keys. Served bytes are billed to a
        background-class ``"peer"`` tenant through the scheduler, so peer
        traffic can never starve local demand. Returns the bound
        ``host:port`` (port 0 = ephemeral); idempotent."""
        if self._closed:
            raise RuntimeError("StromContext is closed")
        if self._peer_server is not None:
            return self._peer_server.addr
        if self._scheduler is not None:
            self.register_tenant("peer", priority="background")
        from strom.dist.peers import PeerServer

        self._peer_server = PeerServer(
            self, host=host, port=port,
            max_conns=self.config.dist_server_max_conns)
        return self._peer_server.addr

    def register_tunable(self, kind: str, obj) -> None:
        """Expose a live pipeline surface (``"decode_pool"``,
        ``"readahead"``) so :func:`strom.tune.standard_knobs` can build a
        knob over it; last registration of a *kind* wins."""
        self._tunables[str(kind)] = obj

    def attach_peers(self, peers, owner_fn=None, directory=None) -> None:
        """Wire the peer tier of the delivery consult: *peers* maps a
        peer name to its ``host:port`` (or is a plain address list);
        *owner_fn* maps a dataset path to the peer name expected to have
        it hot (None/unknown = straight to the engine), or *directory* is
        a live :class:`~strom.dist.directory.ExtentDirectory` — the
        consistent-hash owner map that re-owns a dead host's keys across
        membership epochs (ISSUE 20; it outranks *owner_fn*). Fetch
        failures and timeouts fall back to the local engine read — never
        fatal — and a dead peer trips a per-peer circuit breaker (which
        publishes the death to the directory when one is attached).
        Replaces any previously attached tier; the tier registers itself
        as the ``"peer_tier"`` tunable so the autotuner can steer the
        batch size and pool depth."""
        from strom.dist.peers import PeerTier

        if self._peer_tier is not None:
            self._peer_tier.close()
        self._peer_tier = PeerTier(
            peers, owner_fn=owner_fn, directory=directory,
            scope=self.scope,
            timeout_s=self.config.dist_peer_timeout_s,
            plan=getattr(self.engine, "plan", None),
            compress=self.config.peer_compress,
            batch_max_extents=self.config.dist_batch_max_extents,
            conn_pool_size=self.config.dist_conn_pool_size,
            auth_key=self.config.dist_auth_key)
        self.register_tunable("peer_tier", self._peer_tier)

    @property
    def decoded_cache(self):
        """The DecodedCache registered via :meth:`attach_decoded_cache`
        (the vision pipeline's decode-once tier), else None — the peer
        server exports decoded frames from it (ISSUE 20)."""
        return self._decoded_cache

    def attach_decoded_cache(self, dcache) -> None:
        """Register the pipeline's DecodedCache so the peer server can
        serve decoded frames cluster-wide (kind-1 batch keys carrying the
        decode fingerprint); last registration wins."""
        self._decoded_cache = dcache

    def peer_decoded_fetch(self, ckey) -> "np.ndarray | None":
        """Probe the owning peer for a decoded frame by its DecodedCache
        key ``("jpegdec", path, lo, hi, fingerprint)`` → ``(h, w, 3)``
        uint8 RGB or None. The vision pipeline consults this on a local
        decoded-cache miss BEFORE planning the JPEG extent read — a frame
        decoded once is decoded once per cluster."""
        if self._peer_tier is None:
            return None
        try:
            _, path, lo, hi, fp = ckey
        except (TypeError, ValueError):
            return None
        return self._peer_tier.fetch_frame(str(path), int(lo), int(hi),
                                           str(fp))

    @property
    def cluster_view(self):
        """The metrics-federation view when :meth:`attach_cluster` wired
        one (the coordinator's /cluster route), else None
        (strom/obs/federation.py)."""
        return self._cluster

    def attach_cluster(self, hosts, *, interval_s: float = 1.0,
                       stall_s: float = 10.0, **kwargs):
        """Start the cluster observability plane (ISSUE 18): a background
        loop polling each worker's ``/stats`` endpoint (*hosts* maps host
        id → ``ip:port`` metrics address), merging the fleet into one
        aggregate (served on this context's ``/cluster`` route) and
        flagging hosts whose scrape fails or whose progress stalls —
        an unhealthy transition best-effort-triggers the remote host's
        ``/flight?dump=1`` and dumps this context's own flight recorder.
        Replaces any previous view; returns it."""
        if self._closed:
            raise RuntimeError("StromContext is closed")
        from strom.obs.federation import ClusterView

        if self._cluster is not None:
            self._cluster.close()
        self._cluster = ClusterView(
            hosts, recorder=self._flight, interval_s=interval_s,
            stall_s=stall_s, **kwargs)
        return self._cluster

    @property
    def tuner(self):
        """The closed-loop knob autotuner when ``tune=True`` (or
        :meth:`attach_tuner` was called), else None (strom/tune)."""
        return self._tuner

    def attach_tuner(self, knobs=None, *, profile_path: "str | None" = None,
                     start: bool = True):
        """Arm the closed-loop autotuner (ISSUE 16 tentpole, strom/tune)
        over this context's live knob surfaces — scheduler slice bytes and
        cache budget by default, or an explicit *knobs* list (pipelines
        add prefetch depth via :func:`strom.tune.prefetcher_knob`). The
        controller climbs the stall-attribution goodput and HOLDS whenever
        any tenant's SLO is burning or goodput is not yet measurable — it
        never experiments blind or on a tenant already missing its target.
        *profile_path* warm-starts from a saved :class:`strom.tune.Profile`;
        ``start=False`` builds the controller without the driver thread
        (the bench arms beat it manually). Idempotent."""
        if getattr(self, "_closed", False):
            raise RuntimeError("StromContext is closed")
        if self._tuner is not None:
            return self._tuner
        from strom.tune import Autotuner, Profile, standard_knobs

        ks = list(knobs) if knobs is not None else standard_knobs(self)
        name = "default"
        if profile_path:
            name = os.path.splitext(os.path.basename(profile_path))[0]
        tuner = Autotuner(
            ks, self._tune_metrics,
            interval_s=self.config.tune_interval_s,
            guard_frac=self.config.tune_guard_frac,
            scope=self.scope, profile_name=name)
        if profile_path and os.path.exists(profile_path):
            tuner.apply_profile(Profile.load(profile_path))
        self._tuner = tuner
        if start:
            tuner.start()
        return tuner

    def _tune_metrics(self) -> dict:
        """The autotuner's objective: stall-attribution goodput (rides the
        steps section's TTL cache). No goodput yet (no step windows) reads
        as a hold — the controller must never experiment without a signal
        to judge the trial by."""
        goodput = self._current_goodput()
        burning = bool(self._slo.stats().get("slo_tenants_burning", 0))
        metrics = {"objective": float(goodput or 0.0),
                   "slo_burning": burning or goodput is None}
        # windowed stall-attribution burn (ISSUE 18 satellite / ROADMAP 5
        # residual): the per-bucket counters the steps recompute publishes,
        # turned into per-second rates by the history ring — the controller
        # sees WHERE the stall time goes, not just the goodput scalar
        if self._history is not None:
            from strom.obs.stall import BUCKETS

            for b in BUCKETS:
                r = self._history.rate(f"stall_{b}_us", window_s=30.0)
                if r is not None:
                    metrics[f"stall_{b}_us_per_s"] = r
        return metrics

    @contextlib.contextmanager
    def engine_exclusive(self, nbytes: int = 0, tenant: str | None = None):
        """Exclusive use of the engine's transfer path for a raw
        engine-level caller (the stress harness, tooling): a scheduler
        grant when one exists, the legacy engine lock otherwise."""
        if self._scheduler is not None:
            with self._scheduler.grant(tenant, nbytes):
                yield
        else:
            with self._engine_lock:
                yield

    @contextlib.contextmanager
    def _demand_gate(self):
        """Marks a DEMAND engine gather in flight (readahead yields to it)."""
        with self._demand_lock:
            self._demand_reads += 1
        try:
            yield
        finally:
            with self._demand_lock:
                self._demand_reads -= 1

    def _demand_active(self) -> bool:
        with self._demand_lock:
            return self._demand_reads > 0

    # -- file registry ------------------------------------------------------
    def file_index(self, path: str, *, writable: bool = False) -> int:
        """Engine file index for *path*, registered lazily. ``writable=True``
        (ISSUE 13) registers a separate read-write index — write ops
        (``ctx.pwrite``, checkpoint saves, dataset writers) ride it; the
        read-only registration (and its o_direct probe state) is left
        untouched."""
        with self._files_lock:
            table = self._wfiles if writable else self._files
            idx = table.get(path)
            if idx is None:
                idx = self.engine.register_file(
                    path, o_direct=self.config.o_direct, writable=writable)
                table[path] = idx
            return idx

    def invalidate_file(self, path: str, *,
                        registrations: bool = True) -> None:
        """Forget everything cached about *path* (ISSUE 13): hot-cache and
        spill entries (the bytes changed — a write landed), the FIEMAP
        extent map, and (``registrations=True``) the engine file
        registrations — required when the path now names a DIFFERENT inode
        (a tmp+rename commit), where a cached fd would keep reading the
        old file forever. In-place writers (:meth:`pwrite`) keep their
        registrations: the inode is the same, only the cached bytes lie."""
        idxs: list[int] = []
        with self._files_lock:
            self._extent_maps.pop(path, None)
            if registrations:
                for table in (self._files, self._wfiles):
                    idx = table.pop(path, None)
                    if idx is not None:
                        idxs.append(idx)
        for idx in idxs:
            with contextlib.suppress(Exception):
                self.engine.unregister_file(idx)
        if self._hot_cache is not None:
            # cascades to the spill tier (a spill tier only exists under a
            # hot cache); derived tuple keys (decoded frames) drop too
            self._hot_cache.invalidate(path)

    def register_striped(self, path: str, striped: "StripedFile | Sequence[str]",
                         chunk: int | None = None,
                         size: int | None = None) -> StripedFile:
        """Alias *path* to a RAID0 striped set: every read addressed to the
        path — including extents a format reader planned against it — is
        stripe-decoded across the members. The userspace twin of mounting a
        filesystem on an md-raid0 array: files keep ordinary names while the
        block layer stripes underneath (SURVEY.md §2.2 "md-raid0 decode").
        """
        if isinstance(striped, StripedFile):
            # don't silently drop the extra args against a prebuilt instance
            if chunk is not None and chunk != striped.chunk:
                raise ValueError(
                    f"chunk={chunk} conflicts with StripedFile.chunk="
                    f"{striped.chunk}; pass one or the other")
            if size is not None:
                striped = dataclasses.replace(striped, size_bytes=size)
        else:
            if chunk is None:
                # the stripe chunk is a property of how the members were
                # WRITTEN; defaulting it (e.g. to the IO block size) would
                # de-interleave with the wrong geometry and return
                # byte-shuffled data with no error
                raise ValueError("chunk is required when registering a "
                                 "member list: it must match the chunk the "
                                 "set was striped with")
            striped = StripedFile(tuple(striped), chunk, size)
        with self._files_lock:
            self._striped[path] = striped
        return striped

    def striped_source(self, path: str) -> StripedFile | None:
        """The StripedFile aliased to *path*, if any."""
        with self._files_lock:
            return self._striped.get(path)

    def resolve_source(self, source: "Source") -> "Source":
        """*source* with any registered striped alias applied."""
        if isinstance(source, str):
            with self._files_lock:
                return self._striped.get(source, source)
        return source

    def _on_slab_alloc(self, base: np.ndarray) -> None:
        """Fresh pool slab: NUMA-place it, then register it with the engine
        so gathers into it ride READ_FIXED (pages pinned once at
        registration, not per IO — the reference pins its DMA window once at
        MAP_GPU_MEMORY for the same reason, SURVEY.md §3.2). Registration
        lives exactly as long as the slab's mmap; recycled slabs stay
        registered."""
        if self._numa is not None:
            self._numa.bind(base)
        if self.engine.register_dest(base) >= 0:
            import weakref

            from strom.delivery.buffers import buf_addr

            # finalizer args must not reference the array (a strong ref would
            # keep the mmap alive and the finalizer would never run): key the
            # unregistration by raw address, fired when the mmap dies
            weakref.finalize(base.base, self.engine.unregister_dest_addr,
                             buf_addr(base))

    @staticmethod
    def _numa_path(source: "Source") -> str | None:
        """A representative file path for NUMA node discovery."""
        if isinstance(source, str):
            return source
        if isinstance(source, StripedFile):
            return source.members[0]
        if isinstance(source, ExtentList) and len(source):
            return source.extents[0].path
        return None

    def device_put(self, arr: np.ndarray, device: Any) -> Any:
        """One host->device dispatch under the context's put policy: the
        `serialize_device_put` lock (concurrent puts interleave poorly on a
        shared host link) and the trace annotation. Pipelines route their
        per-device shard puts here — including the decode path's overlapped
        per-group puts — so every host->HBM byte obeys one policy."""
        import jax

        from strom.utils.tracing import trace_span

        with self._put_lock, \
                trace_span("strom.device_put", cat="put",
                           enabled=self.config.trace_annotations):
            return jax.device_put(arr, device)

    def extent_map(self, path: str) -> list | None:
        """Cached FIEMAP extent map for *path* (None: unavailable)."""
        with self._files_lock:
            if path in self._extent_maps:
                return self._extent_maps[path]
        from strom.probe.fiemap import fiemap

        try:
            em = fiemap(path)
        except OSError:
            em = None
        with self._files_lock:
            self._extent_maps[path] = em
        return em

    # -- raw range read into a fresh aligned slab ---------------------------
    def _plan_chunks(self, source: "Source", segments: Sequence[Segment],
                     base_offset: int = 0
                     ) -> tuple[list[tuple[int, int, int, int]],
                                dict[int, str]]:
        """Expand logical (file_offset+base_offset → dest_offset) segments
        into the physical (file_index, file_offset, dest_offset, length)
        chunk list an engine gather executes: striped-alias resolution,
        segment/op coalescing, stripe windows, and extent-aware ordering all
        applied. Shared by the blocking read path (:meth:`_read_segments`)
        and the completion-driven streaming path
        (:class:`strom.delivery.stream.StreamingGather`) so their plans can
        never drift. Returns ``(chunks, idx_paths)`` where *idx_paths* maps
        file indexes back to paths (hot-cache keys, FIEMAP lookups)."""
        cfg = self.config
        source = self.resolve_source(source)
        if self._numa is not None:
            # pin THIS thread (the engine submit path runs on it) to the
            # device's home node; once per thread, resolved from the source
            self._numa.ensure_thread(self._numa_path(source))

        if cfg.coalesce_max_bytes and len(segments) > 1:
            # merge caller fragments that are file+dest contiguous BEFORE
            # expansion: a merged logical run stripes/extent-splits as one
            # piece instead of per fragment
            segments = coalesce_segments(segments, cfg.coalesce_max_bytes)

        # member fds resolved once per transfer, not once per extent run (a
        # WDS batch produces one run per sample component)
        member_cache: dict[StripedFile, list[int]] = {}
        idx_paths: dict[int, str] = {}  # file_idx -> path (for FIEMAP lookup)

        def findex(path: str) -> int:
            idx = self.file_index(path)
            idx_paths[idx] = path
            return idx

        def stripe_chunks(sf: StripedFile, file_off: int, dest_off: int,
                          length: int) -> None:
            member_idx = member_cache.get(sf)
            if member_idx is None:
                member_idx = [findex(m) for m in sf.members]
                member_cache[sf] = member_idx
            segs = plan_stripe_reads(file_off, length, len(sf.members),
                                     sf.chunk)
            wb = cfg.resolved_stripe_window_bytes
            if wb > 0 and len(sf.members) > 1 and length > wb:
                # striped-read overlap: per-member sequential runs inside
                # windows of the in-flight budget — ops for window N+1 enter
                # the queue while window N's completions drain, instead of
                # a chunk-granular round-robin hopping members every
                # raid_chunk bytes (see plan_stripe_windows)
                self.scope.add("stripe_windows",
                                 count_stripe_windows(segs, len(sf.members),
                                                      wb))
                segs = plan_stripe_windows(segs, len(sf.members), wb)
                self.scope.set_gauge("stripe_overlap_window_bytes", wb)
            for s in segs:
                chunks.append((member_idx[s.member], s.member_offset,
                               dest_off + (s.logical_offset - file_off),
                               s.length))

        # Expand logical segments to physical (file_index, offset) chunks.
        chunks: list[tuple[int, int, int, int]] = []  # (file_idx, file_off, dest_off, len)
        if isinstance(source, StripedFile):
            for seg in segments:
                stripe_chunks(source, base_offset + seg.file_offset,
                              seg.dest_offset, seg.length)
        elif isinstance(source, ExtentList):
            # striped-alias runs buffer per StripedFile and coalesce BEFORE
            # stripe expansion: adjacent extents over one alias (consecutive
            # column chunks, back-to-back tar members) become one logical
            # run, which then stripes — and windows — as a whole instead of
            # per fragment. Plain-path runs merge later at the op level.
            striped_runs: dict[StripedFile, list[Segment]] = {}
            for seg in segments:
                for r in source.locate(base_offset + seg.file_offset, seg.length,
                                       seg.dest_offset):
                    sf = self.striped_source(r.path)
                    if sf is not None:
                        # extent planned against an aliased path: stripe-decode
                        # it here, exactly where a plain path resolves to an fd
                        striped_runs.setdefault(sf, []).append(
                            Segment(r.offset, r.dest_offset, r.length))
                    else:
                        chunks.append((findex(r.path), r.offset,
                                       r.dest_offset, r.length))
            for sf, runs in striped_runs.items():
                if cfg.coalesce_max_bytes and len(runs) > 1:
                    n_in = len(runs)
                    runs = coalesce_segments(runs, cfg.coalesce_max_bytes)
                    self.scope.add("coalesce_ops_in", n_in)
                    self.scope.add("coalesce_ops_out", len(runs))
                    self.scope.set_gauge("coalesce_ops_in_last", n_in)
                    self.scope.set_gauge("coalesce_ops_out_last", len(runs))
                for s in runs:
                    stripe_chunks(sf, s.file_offset, s.dest_offset, s.length)
        else:
            chunks = [(findex(source), base_offset + s.file_offset,
                       s.dest_offset, s.length) for s in segments]

        if cfg.coalesce_max_bytes and len(chunks) > 1 and not member_cache:
            # op-level coalescing: per-extent-run fragments (tar members,
            # column chunks, record runs) that landed adjacent in both file
            # and dest space become one engine op. Striped gathers are
            # exempt (member ops interleave by design; merging would need
            # non-contiguous dests) — their fragment merging happened at the
            # segment level above, before stripe expansion.
            n_in = len(chunks)
            chunks = coalesce_chunks(chunks, cfg.coalesce_max_bytes)
            self.scope.add("coalesce_ops_in", n_in)
            self.scope.add("coalesce_ops_out", len(chunks))
            self.scope.set_gauge("coalesce_ops_in_last", n_in)
            self.scope.set_gauge("coalesce_ops_out_last", len(chunks))

        if cfg.extent_aware and chunks and not member_cache:
            # extent-aware planning for plain-file gathers of every source
            # kind (whole-file reads AND format-reader ExtentLists): group
            # into per-file runs, each submitted in physical-address order.
            # Striped gathers are exempt: the engine submits in list order
            # within a queue-depth window, so regrouping the round-robin
            # member interleave into per-member runs would serialize the
            # very multi-device parallelism RAID0 exists for.
            from strom.delivery.chunk_plan import plan_chunks_multi

            maps = {}
            for fi, p in idx_paths.items():
                em = self.extent_map(p)
                if em:
                    maps[fi] = em
            if maps:
                chunks = plan_chunks_multi(chunks, maps)
        return chunks, idx_paths

    def _consult_cache(self, cache, chunks: list[tuple[int, int, int, int]],
                       idx_paths: dict[int, str],
                       dflat: "np.ndarray | None", *, warm: bool = False,
                       tenant: "str | None" = None
                       ) -> tuple[list[tuple[int, int, int, int]], int,
                                  list[tuple[int, int]]]:
        """Hot-set cache consult (ISSUE 4 tentpole): split every physical
        chunk into cached ranges (memcpy'd from RAM into *dflat* under a pin
        that blocks eviction) and miss runs (the only ops the engine sees).
        Full hit => the engine is skipped entirely. Returns ``(miss_chunks,
        hit_bytes, hit_ranges)`` — *hit_ranges* are the dest [lo, hi) spans
        served from RAM, which the streaming path reports as INSTANT
        completions. ``warm=True`` (readahead) records nothing and never
        copies (*dflat* may be None).

        With a spill tier attached (ISSUE 13), RAM misses probe the spill
        file next: spill-resident ranges pread from local NVMe into *dflat*
        (and re-offer themselves for RAM promotion — the hierarchy works in
        both directions), never reaching the source engine and never
        counting as ``cache_miss_bytes``; only TRUE misses (neither tier)
        do.

        With a peer tier attached (ISSUE 15, ``ctx.attach_peers``), TRUE
        misses probe the PEERS last — RAM → spill → peer → engine: an
        extent hot on another host arrives over the socket (and promotes
        into the local cache) instead of a duplicate SSD read. Peer-served
        bytes count as hits, never as ``cache_miss_bytes``; a fetch
        failure/timeout/open-breaker falls through to the engine. *cache*
        may be None here (a peered context without a hot cache still
        probes peers); ``warm=True`` never probes peers — readahead must
        not generate network traffic."""
        cache_hit = 0
        peer_hit = 0
        t0 = _events_ring.now_us()
        miss_chunks: list[tuple[int, int, int, int]] = []
        hit_ranges: list[tuple[int, int]] = []
        pinned: list = []
        spill = getattr(cache, "spill", None) if cache is not None else None
        peers = self._peer_tier if (not warm and dflat is not None) else None
        spill_served = 0

        # peer fabric v2 (ISSUE 20): true misses are COLLECTED during the
        # tier walk and resolved in one fetch_many after it — a gather's
        # worth of peer candidates rides the batched wire (one round trip
        # per owner chunk) instead of one synchronous exchange per range
        peer_pending: list = []

        def true_miss(fi: int, path, fo: int, do: int, s: int, t: int, *,
                      deferred: bool) -> None:
            """Neither RAM nor spill holds [s, t): queue it for the peer
            tier (resolved in a batch below), engine on miss. *deferred* =
            the cache lookup left miss counting to us (a peer hit must not
            read as a cache miss)."""
            if peers is not None and path is not None:
                peer_pending.append((fi, path, fo, do, s, t, deferred))
                return
            miss_chunks.append((fi, s, do + (s - fo), t - s))
            if deferred and cache is not None and not warm:
                cache.note_miss(t - s)

        for fi, fo, do, ln in chunks:
            path = idx_paths.get(fi)
            if path is None:  # untracked fd: bypass the cache
                miss_chunks.append((fi, fo, do, ln))
                continue
            if cache is None:
                # no hot cache, peers attached: every range is a RAM/spill
                # miss by construction
                true_miss(fi, path, fo, do, fo, fo + ln, deferred=False)
                continue
            hits, misses, pins = cache.lookup(
                path, fo, fo + ln, record=not warm,
                count_misses=spill is None and peers is None)
            pinned.extend(pins)
            for s, t, view in hits:
                if not warm:  # warm mode discards dest: skip the copy
                    dflat[do + (s - fo): do + (t - fo)] = view
                    hit_ranges.append((do + (s - fo), do + (t - fo)))
                cache_hit += t - s
            if spill is None:
                for s, t in misses:
                    true_miss(fi, path, fo, do, s, t,
                              deferred=peers is not None)
                continue
            for s, t in misses:
                sp_hits, sp_misses = spill.lookup(path, s, t,
                                                  record=not warm)
                try:
                    for ss, tt, ent in sp_hits:
                        if warm:
                            # readahead-driven spill→RAM promotion
                            # (ISSUE 14 satellite, ROADMAP item 2
                            # residual c): an upcoming-window range that
                            # is spill-resident promotes NOW — one local
                            # NVMe read on the warm thread instead of a
                            # demand-path serve+promote later. Still
                            # never a source-engine read; failures
                            # degrade to the old skip (the demand path
                            # serves it from spill).
                            n = tt - ss
                            tmp = np.empty(n, np.uint8)
                            try:
                                spill.read_into(ent, ss, tt, tmp)
                                promoted = cache.admit(
                                    path, ss, tt, tmp, force=True,
                                    tenant=tenant)
                            except OSError:
                                promoted = 0
                            if promoted:
                                spill.note_promote(promoted)
                            cache_hit += n
                            continue
                        d_lo = do + (ss - fo)
                        spill.read_into(ent, ss, tt,
                                        dflat[d_lo: d_lo + (tt - ss)])
                        hit_ranges.append((d_lo, d_lo + (tt - ss)))
                        cache_hit += tt - ss
                        spill_served += tt - ss
                        # promote back to RAM (admission policy applies):
                        # hot reuse graduates up the hierarchy, one memcpy
                        cache.admit(path, ss, tt,
                                    dflat[d_lo: d_lo + (tt - ss)],
                                    tenant=tenant)
                finally:
                    spill.unpin([e for _, _, e in sp_hits])
                for ss, tt in sp_misses:
                    true_miss(fi, path, fo, do, ss, tt, deferred=True)
        if peer_pending:
            results = peers.fetch_many(
                [(path, s, t) for _, path, _, _, s, t, _ in peer_pending])
            for (fi, path, fo, do, s, t, deferred), data in zip(
                    peer_pending, results):
                if data is not None:
                    d_lo = do + (s - fo)
                    dflat[d_lo: d_lo + (t - s)] = data
                    hit_ranges.append((d_lo, d_lo + (t - s)))
                    cache_hit += t - s
                    peer_hit += t - s
                    if cache is not None:
                        # promote like a spill hit: the NEXT request is a
                        # RAM hit, and this host can serve it onward
                        cache.admit(path, s, t,
                                    dflat[d_lo: d_lo + (t - s)],
                                    tenant=tenant)
                    continue
                miss_chunks.append((fi, s, do + (s - fo), t - s))
                if deferred and cache is not None and not warm:
                    cache.note_miss(t - s)
        if cache is not None:
            cache.unpin(pinned)
        if spill_served:
            _request.complete(t0, _events_ring.now_us() - t0,
                              "cache", "spill.serve",
                              {"bytes": spill_served})
        if peer_hit:
            # request-tagged (ISSUE 8 contract): which request rode the
            # peer tier instead of re-reading the SSD
            _request.complete(t0, _events_ring.now_us() - t0,
                              "dist", "peer.serve",
                              {"bytes": peer_hit})
        if cache_hit - peer_hit > 0 and not warm:
            # request-tagged (ISSUE 8): which request the RAM-served bytes
            # belonged to — cache hits are why a "slow path" request isn't
            _request.complete(t0, _events_ring.now_us() - t0,
                              "cache", "cache.serve",
                              {"bytes": cache_hit - peer_hit})
        return miss_chunks, cache_hit, hit_ranges

    def _read_segments(self, source: "Source",
                       segments: Sequence[Segment],
                       dest: "np.ndarray | None",
                       base_offset: int = 0, *, _warm: bool = False,
                       tenant: str | None = None,
                       deadline_s: "float | None" = None) -> int:
        """Read (file_offset+base_offset → dest_offset) segments, chunked at
        block_size, pipelined at queue_depth. Returns total bytes read.
        Raises EngineError on any failed or short chunk.

        The hot-set cache (when configured) is consulted AFTER physical
        expansion — (path, physical offset) is the only key that repeats
        across epochs; logical ExtentList offsets are batch-relative and
        coalescing merges differently per shuffle order — and BEFORE engine
        submission: cached ranges memcpy from RAM into *dest*, only the
        miss runs reach the engine (a full hit skips it entirely), and miss
        bytes are offered for admission once the gather lands.

        ``_warm=True`` is the readahead path: cached ranges are skipped
        (*dest* may be None — a slab is allocated only once misses exist),
        misses are read in engine-budget slices that yield to demand
        gathers, every read byte is force-admitted, and a short pass
        returns quietly instead of raising."""
        cfg = self.config
        if _warm:
            chunks, idx_paths = self._plan_chunks(source, segments,
                                                  base_offset)
            cache = self._hot_cache
            if cache is not None and not cache.enabled:
                cache = None
            if cache is not None and chunks:
                chunks, _, _ = self._consult_cache(
                    cache, chunks, idx_paths, None, warm=True,
                    tenant=tenant)
            return self._warm_read_chunks(chunks, dest, idx_paths, tenant)

        # causal request tracing (ISSUE 8): every demand gather is (or
        # joins) a traced request — the span tree below (plan, cache
        # serve, sched queue/grant, engine slices, admits) shares its
        # req_id, and finish feeds req_lat / the exemplar store / the SLO
        # engine. Nested mint sites (a streamed batch) reuse the
        # enclosing request, so this adds one contextvar read there.
        with _request.active("gather", tenant, owner=self._req_owner) as req:
            # deadline propagation (ISSUE 9): explicit per-call deadline,
            # else the config default — armed once on the request and read
            # by the scheduler's queue waits, the engine's poll loops and
            # the retry scheduler (first writer wins across nested mints).
            # Gather-kind requests only: a pipeline's enclosing batch/step
            # request must not inherit a per-GATHER bound measured from
            # its first nested read (a long healthy batch would blow it),
            # nor be poisoned by one nested pread's tight deadline.
            if req is not None and req.kind == "gather":
                req.set_deadline_s(deadline_s if deadline_s is not None
                                   else (cfg.request_deadline_s or None))
            chunks, idx_paths = self._plan_chunks(source, segments,
                                                  base_offset)
            cache = self._hot_cache
            if cache is not None and not cache.enabled:
                cache = None
            cache_hit = 0
            dflat: np.ndarray | None = None
            if (cache is not None or self._peer_tier is not None) and chunks:
                dflat = dest if dest.ndim == 1 and dest.dtype == np.uint8 \
                    else dest.reshape(-1).view(np.uint8)
                chunks, cache_hit, _ = self._consult_cache(
                    cache, chunks, idx_paths, dflat, tenant=tenant)
            return self._demand_read_chunks(chunks, dest, idx_paths, cache,
                                            dflat, cache_hit, tenant)

    def _demand_read_chunks(self, chunks, dest, idx_paths, cache, dflat,
                            cache_hit: int, tenant: str | None) -> int:
        """Demand half of :meth:`_read_segments` after planning + cache
        consult: execute the miss chunks on the engine (scheduler-arbitrated
        when one exists, billed to *tenant*'s queue/budgets), verify byte
        accounting, offer admissions."""
        # The engine executes the whole gather (block_size chunking, queue
        # -depth pipelining, per-chunk retry, EOF topup): ONE boundary
        # crossing per transfer on the C++ engine (SURVEY.md §3.3 hot loop).
        # Under the multi-tenant scheduler (ISSUE 7) the gather runs as
        # fair-drained slices — one engine grant per ~sched_slice_bytes —
        # so a concurrent tenant's op queues behind at most one slice of
        # this transfer; without it, the legacy whole-transfer lock.
        cfg = self.config
        planned = sum(ln for (_, _, _, ln) in chunks)
        total = 0
        if chunks:
            with self._demand_gate(), \
                    _request.span("strom.read_segments", cat="read",
                                  args={"ops": len(chunks),
                                        "bytes": planned}):

                def primary() -> int:
                    if self._scheduler is not None:
                        return self._scheduler.read_chunks(
                            chunks, dest, tenant=tenant,
                            retries=cfg.io_retries)
                    with self._engine_lock:
                        return self.engine.read_vectored(
                            chunks, dest, retries=cfg.io_retries)

                def arbitrate(read_slice):
                    # failover reads stay under the tenant's arbitration —
                    # budgets charged, fair-drain queued, and PRE-EMPTIBLE
                    # at slice granularity like the primary path (PR 7's
                    # starvation bound must hold exactly when the system
                    # is degraded): the breaker reroutes the ENGINE, not
                    # the multi-tenant contract
                    if self._scheduler is None:
                        return read_slice(chunks)
                    total_fb = 0
                    for sl in self._scheduler.iter_slices(chunks):
                        nb = sum(ln for (_, _, _, ln) in sl)
                        with self._scheduler.grant(tenant, nb):
                            total_fb += read_slice(sl)
                    return total_fb

                try:
                    # breaker + failover (ISSUE 9): outcomes feed the
                    # per-engine circuit breaker; the gather that trips it
                    # (and every gather while it is open) reroutes to the
                    # python fallback engine instead of failing the caller
                    if self._resilience is not None:
                        total = self._resilience.execute(
                            primary, chunks, dest, idx_paths,
                            arbitrate=arbitrate)
                    else:
                        total = primary()
                except (DeadlineExceeded, EngineStallError):
                    # typed failures keep their class: callers (and tests)
                    # distinguish a deadline miss / stall diagnosis from a
                    # media error
                    raise
                except EngineError as e:
                    raise EngineError(e.errno,
                                      f"ssd2tpu {e.strerror}") from None
            if total != planned:
                # cheap insurance: any engine accounting bug (short read the
                # engine failed to flag) surfaces loudly instead of as a
                # zero-tailed jax array
                raise EngineError(
                    errno.EIO,
                    f"ssd2tpu read {total} bytes, planned {planned}")
            if cache is not None:
                # admission offer (second-touch policy decides): the engine
                # already landed the bytes in dest, so admitting is one
                # memcpy into a cache-owned slab, never an extra read
                t0a = _events_ring.now_us()
                admitted = 0
                for fi, fo, do, ln in chunks:
                    path = idx_paths.get(fi)
                    if path is not None:
                        admitted += cache.admit(path, fo, fo + ln,
                                                dflat[do: do + ln],
                                                tenant=tenant)
                if admitted:
                    _request.complete(t0a, _events_ring.now_us() - t0a,
                                      "cache", "cache.admit",
                                      {"bytes": admitted})
        self.scope.add("ssd2tpu_bytes", total + cache_hit)
        return total + cache_hit

    def _warm_read_chunks(self, chunks: list[tuple[int, int, int, int]],
                          dest: np.ndarray, idx_paths: dict[int, str],
                          tenant: "str | None" = None) -> int:
        """Readahead engine path: read miss chunks in slices of the
        in-flight budget (queue_depth x block_size), force-admitting each
        slice, yielding to demand gathers between slices — a demand read
        queues behind at most ONE warming slice. Advisory: engine errors
        and short passes end the warm quietly (the demand path will report
        them with full context if they matter)."""
        cache = self._hot_cache
        cfg = self.config
        if cache is None or not chunks:
            return 0
        # dest is allocated LAZILY, only once there are actual misses: in
        # steady state (window fully warm) the readahead poll must cost a
        # cache consult and nothing else — no slab churn, no memcpy
        acquired: np.ndarray | None = None
        if dest is None:
            span = max(do + ln for (_, _, do, ln) in chunks)
            if self._scheduler is not None:
                # slab-pool admission control (ISSUE 7): a warm slab is
                # BACKGROUND-class memory — under high-water pressure it
                # queues (bounded; a failed admit skips this warm pass)
                # instead of crowding demand tenants out of the pool
                if not self._scheduler.admission.admit(span, timeout_s=5.0):
                    return 0
            dest = acquired = self._slab_pool.acquire(span) \
                if self._slab_pool is not None else alloc_aligned(span)
        try:
            dflat = dest if dest.ndim == 1 and dest.dtype == np.uint8 \
                else dest.reshape(-1).view(np.uint8)
            budget = max(cfg.queue_depth * cfg.block_size, cfg.block_size)
            total = 0
            i = 0
            while i < len(chunks):
                if self._demand_active():
                    cache.note_yield()
                    break
                batch: list[tuple[int, int, int, int]] = []
                b = 0
                while i < len(chunks) and b < budget:
                    batch.append(chunks[i])
                    b += chunks[i][3]
                    i += 1
                t0 = _events_ring.now_us()
                try:
                    if self._scheduler is not None:
                        # readahead demotes to the lowest class
                        # automatically: a demand gather of ANY tenant
                        # outranks every warm slice in the fair drain
                        n = self._scheduler.read_chunks(
                            batch, dest, tenant="readahead",
                            retries=cfg.io_retries, priority="background")
                    else:
                        with self._engine_lock:
                            n = self.engine.read_vectored(
                                batch, dest, retries=cfg.io_retries)
                except EngineError:
                    break
                _events_ring.complete(t0, _events_ring.now_us() - t0, "cache",
                                      "cache.readahead", {"bytes": n})
                if n != b:
                    break
                for fi, fo, do, ln in batch:
                    path = idx_paths.get(fi)
                    if path is not None:
                        # admitted bytes charge the OWNING pipeline's
                        # partition (the engine read rode the shared
                        # background "readahead" tenant) — warming must not
                        # bypass the per-tenant cache carve-outs
                        cache.admit(path, fo, fo + ln, dflat[do: do + ln],
                                    force=True, tenant=tenant)
                total += n
        finally:
            if acquired is not None and self._slab_pool is not None:
                self._slab_pool.release(acquired)
        return total

    def alloc_read_buffer(self, source: "Source", nbytes: int) -> np.ndarray:
        """A fresh aligned host buffer for gathers from *source*, NUMA-bound
        the same way ``pread`` binds its slab — the allocation path for
        callers (the streamed batch assembly) that drive the gather
        themselves instead of going through pread."""
        dest = alloc_aligned(nbytes)
        if self._numa is not None and \
                self._numa.resolve(self._numa_path(
                    self.resolve_source(source))) is not None:
            self._numa.bind(dest)
        return dest

    # -- completion-driven streaming gather (ISSUE 5 tentpole) --------------
    def stream_segments(self, source: "Source", segments: Sequence[Segment],
                        dest: np.ndarray, base_offset: int = 0, *,
                        scope=None, tenant: str | None = None):
        """Begin a completion-driven gather of *segments* into *dest*: the
        same plan ``_read_segments`` would execute (striped aliases,
        coalescing, stripe windows, extent-aware ordering, hot-cache
        consult), but submitted through the engine's async vectored API so
        dest ranges surface the moment their extents land — cache hits as
        instant completions, the engine never waited on. Returns a
        :class:`strom.delivery.stream.StreamingGather`; see its docstring
        for the poll/finish/close protocol. The gather owns the engine's
        transfer path (engine lock + demand gate) until finish/close."""
        from strom.delivery.stream import StreamingGather

        if self._closed:
            raise RuntimeError("StromContext is closed")
        return StreamingGather(self, source, segments, dest, base_offset,
                               scope=scope, tenant=tenant)

    def warm(self, source: "Source", segments: Sequence[Segment],
             base_offset: int = 0, *, tenant: "str | None" = None) -> int:
        """Readahead entry point (strom.delivery.hotcache.Readahead): make
        the given ranges cache-resident. Serves nothing — already-cached
        ranges are skipped without a copy, misses are engine-read into a
        throwaway slab and force-admitted. Returns bytes warmed; yields
        (returns 0/short) whenever a demand gather is in flight."""
        if self._hot_cache is None or not self._hot_cache.enabled \
                or self._closed:
            return 0
        if self._demand_active():
            self._hot_cache.note_yield()
            return 0
        if sum(s.length for s in segments) <= 0:
            return 0
        try:
            # dest=None: the warm path allocates a slab only if there are
            # misses to read (a fully-warm window costs a consult, nothing
            # else — see _warm_read_chunks)
            warmed = self._read_segments(source, segments, None, base_offset,
                                         _warm=True, tenant=tenant)
        except (EngineError, OSError, ValueError):
            warmed = 0  # advisory: never turn readahead into a crash
        if warmed:
            self._hot_cache.note_readahead(warmed)
        return warmed

    # -- intra-transfer streaming (read/transfer overlap) -------------------
    def _deliver_streamed(self, source: "Source", segments: Sequence[Segment],
                          base_offset: int, nbytes: int, np_dtype: np.dtype,
                          local_shape: tuple, devices: Sequence[Any],
                          pool, tenant: str | None = None) -> list:
        """Pipeline one transfer internally: the engine reads piece k+1 from
        disk while piece k streams host->HBM, then the pieces are concatenated
        on-device. This is the intra-transfer half of the overlap story —
        round 1 only overlapped ACROSS transfers, and the whole-slab
        read-then-put serialization capped delivered bandwidth at ~55% of raw
        (VERDICT.md missing #1). ≙ the reference consumer's double-buffered
        DMA/compute recycle loop (SURVEY.md §3.5).

        Returns one delivered jax.Array per device in *devices* (replicas get
        the same pieces put to each device)."""
        import jax

        from strom.utils.tracing import trace_span

        chunk = self.config.overlap_chunk_bytes
        pieces = split_segments(segments, chunk)
        itemsize = np_dtype.itemsize
        n_elems = nbytes // itemsize
        ready: "queue.Queue[tuple[int, np.ndarray] | None]" = queue.Queue(maxsize=2)
        fail: list[BaseException] = []

        def reader() -> None:
            # Reader-side accounting: *idle* time is spent blocked on the
            # consumer (full ready queue, or waiting for a slab the consumer
            # hasn't recycled yet); *read* time is spent in the engine. The
            # disk-side half of the overlap story: a busy link plus an idle
            # reader means the software saturates the link; a busy reader
            # with no idle means the transfer is disk-bound (VERDICT.md r2
            # weak #2 — link_busy_frac alone is one timer wearing two names).
            r_t0 = time.perf_counter()
            idle = 0.0
            read_busy = 0.0
            try:
                for idx, (_, piece_len, piece_segs) in enumerate(pieces):
                    t = time.perf_counter()
                    if pool is not None:
                        slab = pool.acquire(piece_len)  # pool mbinds fresh slabs
                        idle += time.perf_counter() - t
                    else:
                        slab = alloc_aligned(piece_len,
                                             huge=self.config.huge_pages)
                        if self._numa is not None:
                            self._numa.bind(slab)
                    t = time.perf_counter()
                    self._read_segments(source, piece_segs, slab, base_offset,
                                        tenant=tenant)
                    read_busy += time.perf_counter() - t
                    t = time.perf_counter()
                    ready.put((idx, slab))
                    idle += time.perf_counter() - t
                ready.put(None)
            except BaseException as e:  # surfaced on the consumer side
                fail.append(e)
                ready.put(None)
            finally:
                self.scope.add("stream_reader_wall_us",
                               int((time.perf_counter() - r_t0) * 1e6))
                self.scope.add("stream_reader_idle_us", int(idle * 1e6))
                self.scope.add("stream_reader_read_us",
                               int(read_busy * 1e6))

        t = threading.Thread(target=reader, name="strom-stream-reader",
                             daemon=True)
        t.start()
        # Each device assembles into ONE preallocated buffer via donated
        # dynamic_update_slice pastes: peak device memory ~= nbytes + chunk,
        # where accumulating pieces + concatenating would peak at ~2x nbytes.
        bufs = [_alloc_on_device(n_elems, np_dtype, d) for d in devices]
        elem_off = 0
        wall_t0 = time.perf_counter()
        put_busy = 0.0
        try:
            while True:
                item = ready.get()
                if item is None:
                    break
                _, slab = item
                arr_host = slab.view(np_dtype)
                with self._put_lock, \
                        trace_span("strom.device_put", cat="put",
                                   enabled=self.config.trace_annotations):
                    put_t0 = time.perf_counter()
                    for i, d in enumerate(devices):
                        piece = jax.device_put(arr_host, d)
                        bufs[i] = _paste(bufs[i], piece, elem_off)
                    # serialize: the slab is recycled as soon as the paste
                    # retires, and the read of the NEXT piece overlaps this
                    for b in bufs:
                        b.block_until_ready()
                    put_busy += time.perf_counter() - put_t0
                elem_off += arr_host.shape[0]
                if pool is not None:
                    pool.release(slab)
        except BaseException:
            # unblock the reader (bounded queue) before re-raising
            while ready.get() is not None:
                pass
            raise
        finally:
            t.join()
        if fail:
            raise fail[0]
        # Overlap-quality counters: on a link-bound box, busy/wall ≈ 1.0 means
        # the software kept the host->HBM link saturated the whole transfer —
        # a weather-independent measure where absolute GB/s is hostage to the
        # (shared, token-bucket-throttled) transfer relay (BASELINE.md §C).
        self.scope.add("device_put_busy_us",
                       int(put_busy * 1e6))
        self.scope.add("stream_wall_us",
                       int((time.perf_counter() - wall_t0) * 1e6))
        return [_reshape_donated(b, tuple(local_shape)) for b in bufs]

    def _resolve_read_shape(self, source: "Source", offset: int,
                            shape, dtype, length
                            ) -> tuple[tuple[int, ...], np.dtype, int]:
        """(shape, np_dtype, nbytes) for a read request — shared by the
        device and host delivery paths so their length/shape semantics can
        never drift. shape=None → length bytes (length=None → to EOF)."""
        np_dtype = np.dtype(dtype)
        if shape is None:
            if length is None:
                length = source_size(source) - offset
            if length % np_dtype.itemsize:
                raise ValueError(
                    f"length {length} not a multiple of dtype itemsize")
            shape = (length // np_dtype.itemsize,)
        shape = tuple(int(s) for s in shape)
        return shape, np_dtype, math.prod(shape) * np_dtype.itemsize

    # -- the public hot path -------------------------------------------------
    def memcpy_ssd2tpu(self, source: "Source", *,
                       offset: int = 0,
                       shape: Sequence[int] | None = None,
                       dtype: Any = np.uint8,
                       length: int | None = None,
                       sharding: Any = None,
                       device: Any = None,
                       async_: bool = False,
                       pin: bool = False,
                       tenant: str | None = None) -> Any:
        """Read bytes from *source* and deliver them as a jax.Array.

        - shape/dtype: array view of the bytes (row-major on disk). If shape is
          None, length bytes of uint8 (length=None → to EOF).
        - sharding: a jax.sharding.Sharding → global array assembled across the
          mesh; each addressable device reads only its shard's byte ranges.
        - device: single-device destination (exclusive with sharding).
        - async_: return a DMAHandle immediately (≙ MEMCPY_SSD2GPU_ASYNC);
          otherwise return the array (≙ sync MEMCPY_SSD2GPU).
        """
        import jax

        if self._closed:
            raise RuntimeError("StromContext is closed")
        if sharding is not None and device is not None:
            raise ValueError("pass either sharding or device, not both")
        source = self.resolve_source(source)

        if self._numa is not None:
            # resolve the target node BEFORE any slab leaves the pool: a slab
            # allocated pre-resolution would skip its mbind and then recycle
            # with wrong placement for the context's lifetime
            self._numa.resolve(self._numa_path(source))

        shape, np_dtype, nbytes = self._resolve_read_shape(
            source, offset, shape, dtype, length)

        if isinstance(source, str):
            label = f"{source}@{offset}"
        elif isinstance(source, StripedFile):
            label = f"{'+'.join(source.members)}@{offset}"
        else:
            label = f"{source!r}@{offset}"

        def run() -> Any:
            from strom.utils.tracing import trace_span

            # slab recycling: only when device_put COPIES host bytes (every
            # real accelerator backend; the jax CPU backend aliases instead),
            # and released strictly after the transfer retires. Gate on the
            # TARGET's platform, not the default backend — a CPU destination
            # aliases regardless of what the default device is.
            if sharding is not None:
                target_platform = next(iter(sharding.device_set)).platform
            elif device is not None:
                target_platform = device.platform
            else:
                target_platform = jax.default_backend()
            pool = None if (pin or target_platform == "cpu") else self._slab_pool

            def acquire(n: int) -> np.ndarray:
                if pool is not None:
                    return pool.acquire(n)  # pool mbinds fresh slabs
                arr = alloc_aligned(n, pin=pin, huge=self.config.huge_pages)
                if self._numa is not None:
                    self._numa.bind(arr)
                return arr

            cfg = self.config
            def stream_eligible(n: int) -> bool:
                # safe on every backend: on CPU (device_put aliases host
                # memory) pool is already None, so each piece owns a fresh
                # slab the delivered array keeps alive
                return (cfg.overlap_chunk_bytes > 0
                        and n >= max(cfg.overlap_min_bytes, cfg.overlap_chunk_bytes))

            with trace_span("strom.memcpy_ssd2tpu", enabled=cfg.trace_annotations):
                if sharding is None:
                    if (self._hot_cache is not None
                            and self._hot_cache.enabled
                            and pool is not None
                            and isinstance(source, str)):
                        # full-hit fast path: the cached slab IS the host
                        # buffer jax serializes from — no dest slab, no
                        # engine, no serve memcpy. The entry stays pinned
                        # until the put RETIRES (block_until_ready), which
                        # is what lets eviction recycle slabs fearlessly;
                        # gated off aliasing backends (pool is None on CPU,
                        # where the delivered array would share bytes with
                        # an evictable slab forever).
                        hit = self._hot_cache.view(source, offset,
                                                   offset + nbytes)
                        if hit is not None:
                            view, entry = hit
                            try:
                                arr_host = view.view(np_dtype).reshape(shape)
                                with self._put_lock, \
                                        trace_span("strom.device_put",
                                                   cat="put",
                                                   enabled=cfg.trace_annotations):
                                    out = jax.device_put(arr_host, device)
                                out.block_until_ready()
                            finally:
                                self._hot_cache.unpin([entry])
                            self.scope.add("ssd2tpu_bytes", nbytes)
                            return out
                    if stream_eligible(nbytes):
                        return self._deliver_streamed(
                            source, [Segment(0, 0, nbytes)], offset, nbytes,
                            np_dtype, shape, [device], pool, tenant)[0]
                    dest = acquire(nbytes)
                    self._read_segments(source, [Segment(0, 0, nbytes)],
                                        dest, offset, tenant=tenant)
                    arr_host = dest.view(np_dtype).reshape(shape)
                    with self._put_lock, \
                            trace_span("strom.device_put", cat="put",
                                       enabled=cfg.trace_annotations):
                        out = jax.device_put(arr_host, device)  # device=None → default
                    if pool is not None:
                        out.block_until_ready()
                        pool.release(dest)
                    return out
                plans = plan_sharded_read(shape, np_dtype, sharding)
                groups = dedupe_plans(plans)
                shards = []
                dests = []
                group_items = list(groups.items())

                def deliver_group(segs, group) -> tuple[list, np.ndarray]:
                    dest = acquire(group[0].nbytes)
                    out = []
                    try:
                        self._read_segments(source, list(segs), dest, offset,
                                            tenant=tenant)
                        arr_host = dest.view(np_dtype).reshape(group[0].local_shape)
                        for p in group:
                            with self._put_lock, \
                                    trace_span("strom.device_put", cat="put",
                                               enabled=cfg.trace_annotations):
                                out.append(jax.device_put(arr_host, p.device))
                    except BaseException:
                        # recycle the slab on failure: dropping it silently
                        # defeats pool recycling under transient-EIO retry
                        # storms (each retry would fault+mbind fresh pages)
                        if pool is not None:
                            for a in out:  # in-flight puts still read dest
                                with contextlib.suppress(Exception):
                                    a.block_until_ready()
                            pool.release(dest)
                        raise
                    return out, dest

                any_stream = any(stream_eligible(g[0].nbytes)
                                 for _, g in group_items)
                if (len(group_items) > 1 and not any_stream
                        and cfg.delivery_workers > 1):
                    # group-parallel: group k+1's engine read (serialized by
                    # _engine_lock) overlaps group k's host->HBM put — the
                    # only overlap available to small-shard sync transfers,
                    # which the intra-transfer streaming path doesn't cover
                    # (streamed groups keep the sequential arm: they overlap
                    # internally and concurrency would multiply peak memory)
                    futs = [self._group_executor.submit(deliver_group, segs, g)
                            for segs, g in group_items]
                    # drain EVERY future before acting on any error: the old
                    # sequential path could never raise with reads still in
                    # flight, and neither may this one (a caller reacting to
                    # the error — deleting the file, closing the context —
                    # must not race live engine reads)
                    concurrent.futures.wait(futs)
                    first_err = next((f.exception() for f in futs
                                      if f.exception() is not None), None)
                    ok = [f.result() for f in futs if f.exception() is None]
                    if first_err is not None:
                        if pool is not None:
                            # successful groups' slabs go back to the pool
                            # once their puts retire; shards die with us
                            for s, d in ok:
                                for a in s:
                                    a.block_until_ready()
                                pool.release(d)
                        raise first_err
                    for s, d in ok:
                        shards.extend(s)
                        dests.append(d)
                else:
                    for segs, group in group_items:
                        if stream_eligible(group[0].nbytes):
                            shards.extend(self._deliver_streamed(
                                source, list(segs), offset, group[0].nbytes,
                                np_dtype, group[0].local_shape,
                                [p.device for p in group], pool, tenant))
                            continue
                        s, d = deliver_group(segs, group)
                        shards.extend(s)
                        dests.append(d)
                out = jax.make_array_from_single_device_arrays(
                    shape, sharding, shards)
                if pool is not None:
                    for s in shards:
                        s.block_until_ready()
                    for dest in dests:
                        pool.release(dest)
                return out

        if async_:
            return deferred_handle(run, self._executor, nbytes, label)
        return run()

    # -- the delivered path stopped at the device_put boundary --------------
    def memcpy_ssd2host(self, source: "Source", *,
                        offset: int = 0,
                        shape: Sequence[int] | None = None,
                        dtype: Any = np.uint8,
                        length: int | None = None,
                        out: np.ndarray | None = None,
                        tenant: str | None = None) -> np.ndarray:
        """Everything ``memcpy_ssd2tpu`` does UP TO (not including) the
        ``jax.device_put``: striped-alias resolution, extent-aware chunk
        planning, residency routing, and the engine gather — assembled
        zero-copy into the FINAL host array (the staging buffer the blocks
        land in IS the returned array; SURVEY.md §7.4 #1 "the staging buffer
        a block lands in must be the buffer jax serializes from").

        This isolates the framework's host-side cost over a raw engine read:
        on hardware whose host->device link is slower than the SSD, the
        end-to-end delivered/raw ratio measures the link, while
        host-delivered/raw measures the framework (the box-feasible form of
        the >=90%-of-raw target, BASELINE.json:5 — see bench.py's
        ``vs_baseline_host``).

        *out*: preallocated aligned destination of at least the read's size
        (a dest the caller registered with the engine rides READ_FIXED, same
        as the raw bench arm); default: a fresh aligned slab.
        """
        if self._closed:
            raise RuntimeError("StromContext is closed")
        source = self.resolve_source(source)
        if self._numa is not None:
            self._numa.resolve(self._numa_path(source))
        shape, np_dtype, nbytes = self._resolve_read_shape(
            source, offset, shape, dtype, length)
        if out is None:
            dest = alloc_aligned(nbytes, huge=self.config.huge_pages)
            if self._numa is not None:
                self._numa.bind(dest)
        else:
            if not out.flags.c_contiguous:
                # reshape(-1) on a strided view would silently produce a
                # COPY: the engine would land bytes the caller never sees,
                # defeating the zero-copy (and READ_FIXED) contract
                raise ValueError("out must be C-contiguous")
            flat = out.reshape(-1).view(np.uint8)
            if flat.nbytes < nbytes:
                raise ValueError(f"out holds {flat.nbytes} bytes, need {nbytes}")
            dest = flat[:nbytes]
        self._read_segments(source, [Segment(0, 0, nbytes)], dest, offset,
                            tenant=tenant)
        return dest.view(np_dtype).reshape(shape)

    # -- host-side range read (format readers: indexes, footers, members) ---
    def pread(self, source: "Source", offset: int = 0,
              length: int | None = None, *,
              tenant: str | None = None,
              deadline_s: "float | None" = None) -> np.ndarray:
        """Read bytes from *source* into a fresh aligned host slab (no device
        transfer). The staging path format readers use for metadata and member
        payloads before decode. *deadline_s* arms a request deadline: the
        read fails fast with DeadlineExceeded instead of waiting out a sick
        engine (default: config ``request_deadline_s``; 0/None = none).
        The deadline binds the gather-kind request this read mints; inside
        an enclosing traced request (a pipeline batch/step) the enclosing
        contract stands and *deadline_s* is not applied."""
        if self._closed:
            raise RuntimeError("StromContext is closed")
        source = self.resolve_source(source)
        if length is None:
            length = source_size(source) - offset
        if length == 0:
            return np.empty(0, dtype=np.uint8)
        dest = alloc_aligned(length)
        if self._numa is not None and \
                self._numa.resolve(self._numa_path(source)) is not None:
            self._numa.bind(dest)
        self._read_segments(source, [Segment(0, 0, length)], dest, offset,
                            tenant=tenant, deadline_s=deadline_s)
        return dest

    # -- the write path (ISSUE 13): host bytes -> SSD through the engine ----
    def write_chunks(self, chunks, src: np.ndarray, *,
                     tenant: "str | None" = None,
                     priority: "str | None" = None) -> int:
        """Execute a planned write scatter — (file_index, file_offset,
        src_offset, length) chunks out of *src* — scheduler-granted when a
        scheduler exists (PR 7 budgets/priority apply to writes), else under
        the legacy engine lock. Feeds the circuit breaker (a sick engine's
        write failures count toward the trip like read failures; writes do
        NOT fail over — a half-written checkpoint on a second engine is
        worse than a loud error, and the tmp+rename commit makes the retry
        unit the whole save). Returns bytes written; raises on short."""
        cfg = self.config
        planned = sum(ln for (_, _, _, ln) in chunks)
        if not chunks:
            return 0
        br = self._resilience.breaker if self._resilience is not None else None
        try:
            with self._demand_gate(), \
                    _request.span("strom.write_chunks", cat="write",
                                  args={"ops": len(chunks),
                                        "bytes": planned}):
                if self._scheduler is not None:
                    total = self._scheduler.write_chunks(
                        chunks, src, tenant=tenant,
                        retries=cfg.io_retries, priority=priority)
                else:
                    with self._engine_lock:
                        total = self.engine.write_vectored(
                            chunks, src, retries=cfg.io_retries)
        except (DeadlineExceeded, EngineStallError):
            raise
        except EngineError as e:
            if br is not None:
                from strom.engine.resilience import classify_errno

                if classify_errno(e.errno or errno.EIO) == "transient":
                    br.record_failure()
            raise EngineError(e.errno, f"host2ssd {e.strerror}") from None
        if br is not None:
            br.record_success()
        if total != planned:
            raise EngineError(errno.EIO,
                              f"host2ssd wrote {total} bytes, "
                              f"planned {planned}")
        self.scope.add("host2ssd_bytes", total)
        return total

    def pwrite(self, path: str, data: "np.ndarray | bytes | memoryview",
               offset: int = 0, *, tenant: "str | None" = None,
               create: bool = True, fsync: bool = False) -> int:
        """Write *data* to ``path[offset:offset+len)`` through the engine
        write path (ISSUE 13) — the write twin of :meth:`pread`. The file
        is created when absent (*create*); *fsync* makes the bytes durable
        before returning (the checkpoint layer's crash-safe commit relies
        on it). Alignment is handled like reads: page-aligned source
        buffers at aligned offsets ride O_DIRECT, anything else falls back
        to the buffered fd inside the engine. Returns bytes written."""
        if self._closed:
            raise RuntimeError("StromContext is closed")
        src = np.frombuffer(data, dtype=np.uint8) \
            if not isinstance(data, np.ndarray) \
            else np.ascontiguousarray(data).reshape(-1).view(np.uint8)
        n = src.nbytes
        if n == 0:
            return 0
        if create and not os.path.exists(path):
            os.close(os.open(path, os.O_WRONLY | os.O_CREAT, 0o644))
        fi = self.file_index(path, writable=True)
        try:
            total = self.write_chunks([(fi, offset, 0, n)], src,
                                      tenant=tenant)
        finally:
            # cached bytes for this path are stale once ANY of the write
            # landed — invalidated AFTER the write (a concurrent read
            # during the write window may have re-admitted pre-write
            # bytes; invalidating first would leave those stale entries
            # servable forever). fds stay valid (same inode), so
            # registrations are kept.
            self.invalidate_file(path, registrations=False)
        if fsync:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        return total

    # -- introspection (≙ LIST/INFO_GPU_MEMORY, /proc stats) ----------------
    def buffer_info(self) -> dict:
        return self.engine.buffer_info()

    def stats(self, sections: "Sequence[str] | None" = None) -> dict:
        """Nested per-section stats (the /stats and sections-exposition
        shape). *sections* selects a subset by name — the live endpoint's
        per-section TTL cache uses it so a scrape that only wants counters
        never recomputes the expensive stall-attribution section (ISSUE 6
        satellite). None = every section (the pre-existing contract).
        Known sections: context, decode, stream, steps, cache, spill,
        dist, slab_pool, engine, sched, slo, tune, exemplars, resilience,
        scopes."""
        want = None if sections is None else set(sections)

        def wanted(name: str) -> bool:
            return want is None or name in want

        out: dict = {}
        if wanted("context"):
            out["context"] = {
            "registered_files": len(self._files),
            "ssd2tpu_bytes": global_stats.counter("ssd2tpu_bytes").value,
            # write path (ISSUE 13): bytes landed on media through
            # ctx.pwrite / write_chunks (checkpoint saves, dataset writers)
            "host2ssd_bytes": global_stats.counter("host2ssd_bytes").value,
            # delivery-scheduler observability: op counts before/after
            # coalescing (cumulative + last transfer) and the striped-read
            # overlap window (bytes per window, windows planned)
            "coalesce_ops_in": global_stats.counter("coalesce_ops_in").value,
            "coalesce_ops_out": global_stats.counter("coalesce_ops_out").value,
            "coalesce_ops_in_last":
                global_stats.gauge("coalesce_ops_in_last").value,
            "coalesce_ops_out_last":
                global_stats.gauge("coalesce_ops_out_last").value,
            "stripe_overlap_window_bytes":
                global_stats.gauge("stripe_overlap_window_bytes").value,
            "stripe_windows": global_stats.counter("stripe_windows").value,
            }
        # decode-path observability (vision pipelines; ISSUE 2 tentpole):
        # reduced-scale hit counts per denominator, bytes decoded straight
        # into batch slots, per-sample decode failures absorbed by the
        # zero-image policy, and the decode/put overlap window
        if wanted("decode"):
            dh = global_stats.histogram("decode_batch")
            out["decode"] = {
                "decode_reduced_hits_2":
                    global_stats.counter("decode_reduced_hits_2").value,
                "decode_reduced_hits_4":
                    global_stats.counter("decode_reduced_hits_4").value,
                "decode_reduced_hits_8":
                    global_stats.counter("decode_reduced_hits_8").value,
                "decode_slot_bytes":
                    global_stats.counter("decode_slot_bytes").value,
                "decode_errors": global_stats.counter("decode_errors").value,
                "decode_put_overlap_ms":
                    global_stats.counter("decode_put_overlap_ms").value,
                # decode path v2 (ISSUE 12): native-binding decodes (and
                # per-sample fallbacks to cv2), fused-run dispatch volume,
                # ROI partial decodes with the scanlines they skipped, and
                # decoded-output cache traffic
                "decode_native_imgs":
                    global_stats.counter("decode_native_imgs").value,
                "decode_native_fallbacks":
                    global_stats.counter("decode_native_fallbacks").value,
                "decode_fused_runs":
                    global_stats.counter("decode_fused_runs").value,
                "decode_fused_samples":
                    global_stats.counter("decode_fused_samples").value,
                "decode_roi_hits":
                    global_stats.counter("decode_roi_hits").value,
                "decode_roi_rows_skipped":
                    global_stats.counter("decode_roi_rows_skipped").value,
                "decode_cache_hits":
                    global_stats.counter("decode_cache_hits").value,
                "decode_cache_misses":
                    global_stats.counter("decode_cache_misses").value,
                "decode_cache_hit_bytes":
                    global_stats.counter("decode_cache_hit_bytes").value,
                "decode_cache_admitted_bytes":
                    global_stats.counter("decode_cache_admitted_bytes").value,
                "decode_batch_p50_us": dh.percentile(0.50),
                "decode_batch_mean_us": dh.mean_us,
                "decode_batch_total_us": dh.total_us,
                "decode_batch_count": dh.count,
                "decode_batch_hist": list(dh.buckets),
            }
        # intra-batch streaming observability (ISSUE 5 tentpole): batches
        # that took the completion-driven path, the peak async depth, bytes
        # served as instant (cache) completions, the first-decode latency
        # (gather start -> first sample handed to the decode pool) and the
        # tail-extent spread (first -> last completion: the wait the old
        # barrier imposed on EVERY sample; with streaming, work overlapped
        # it). Flat keys, full metric names — same exposition contract as
        # the cache section.
        if wanted("stream"):
            fd = global_stats.histogram("stream_first_decode_lat")
            te = global_stats.histogram("stream_tail_extent")
            out["stream"] = {
                "stream_batches":
                    global_stats.counter("stream_batches").value,
                "stream_inflight_peak":
                    global_stats.gauge("stream_inflight_peak").value,
                "stream_instant_bytes":
                    global_stats.counter("stream_instant_bytes").value,
                "stream_samples_early":
                    global_stats.counter("stream_samples_early").value,
                "stream_first_decode_lat_p50_us": fd.percentile(0.50),
                "stream_first_decode_lat_mean_us": fd.mean_us,
                "stream_first_decode_lat_total_us": fd.total_us,
                "stream_first_decode_lat_count": fd.count,
                "stream_first_decode_lat_hist": list(fd.buckets),
                "stream_tail_extent_p50_us": te.percentile(0.50),
                "stream_tail_extent_mean_us": te.mean_us,
                "stream_tail_extent_total_us": te.total_us,
                "stream_tail_extent_count": te.count,
                "stream_tail_extent_hist": list(te.buckets),
            }
        # per-step stall attribution from the event ring (ISSUE 3 tentpole):
        # goodput_pct + ingest-wait/decode/put/read/compute bucket p50/p99
        # over the step windows retained from THIS context's lifetime —
        # flat keys so the section rides sections_prometheus unchanged.
        # Recomputed at most once per TTL: a full-ring attribution costs
        # ~170ms, which a 10s Prometheus poll must not repeatedly steal
        # from the single core the decode workers share. Section-selective
        # callers (the live endpoint's per-section cache) skip it entirely
        # by leaving "steps" out of *sections*.
        if wanted("steps"):
            from strom.obs import stall

            _STEPS_TTL_S = 2.0
            now = time.monotonic()
            deltas: "dict[str, int] | None" = None
            with self._steps_cache_lock:
                cached = self._steps_cache
                if cached is not None and now - cached[0] < _STEPS_TTL_S:
                    steps = dict(cached[1])
                else:
                    summary = stall.steps_summary(
                        _events_ring.snapshot(), lo_us=self._obs_t0_us)
                    steps = stall.flatten_summary(summary)
                    self._steps_cache = (now, dict(steps))
                    deltas = self._stall_deltas_locked(summary)
            if deltas:
                # counter writes OUTSIDE the cache lock; the delta state
                # above was settled under it, so two racing recomputes
                # can't publish the same growth twice
                for k, d in deltas.items():
                    global_stats.add(k, d)
            steps["events_dropped"] = _events_ring.events_dropped
            out["steps"] = steps
        # hot-set cache observability (ISSUE 4): hit/miss/admission/
        # eviction/readahead counters + hit ratio, keyed with full metric
        # names so the sections exposition types them via the global
        # registry mirror (same contract as the context section)
        if wanted("cache") and self._hot_cache is not None:
            out["cache"] = self._hot_cache.stats()
        if wanted("spill") and self._spill is not None:
            out["spill"] = self._spill.stats()
        # distributed data plane (ISSUE 15): peer-tier client traffic
        # (hits/misses/errors/rtt) + exporter serve counters, keyed by the
        # single-sourced DIST_FIELDS names so the exposition and the bench
        # columns derived from them cannot drift
        if wanted("dist") and (self._peer_tier is not None
                               or self._peer_server is not None):
            d: dict = {}
            if self._peer_tier is not None:
                d.update(self._peer_tier.stats())
            if self._peer_server is not None:
                d.update(self._peer_server.stats())
            out["dist"] = d
        if wanted("slab_pool") and self._slab_pool is not None:
            out["slab_pool"] = self._slab_pool.stats()
        if wanted("engine"):
            out["engine"] = self.engine.stats()
        # multi-tenant scheduler (ISSUE 7): aggregate queue/grant/admission
        # state — per-tenant series reach /metrics as labeled samples via
        # the registry scopes; the /tenants route renders the full rows
        if wanted("sched") and self._scheduler is not None:
            out["sched"] = self._scheduler.stats()
        # per-tenant SLO engine (ISSUE 8): aggregate burn-rate state —
        # per-tenant rows live on /slo, labeled gauges on /metrics
        if wanted("slo"):
            out["slo"] = self._slo.stats()
        # closed-loop autotuner (ISSUE 16): controller state + live knob
        # values, keyed by the single-sourced TUNE_FIELDS names (the /tune
        # route, compare_rounds and strom_top all read this section)
        if wanted("tune") and self._tuner is not None:
            out["tune"] = self._tuner.stats()
        # resilience (ISSUE 9 tentpole): retry/hedge/breaker/failover
        # counters (single-sourced key list RESILIENCE_FIELDS) + the
        # breaker's live state and the fault plan's injection tally when
        # one is wired — the "is this context degraded" section
        if wanted("resilience"):
            from strom.engine.resilience import RESILIENCE_FIELDS

            res: dict = {}
            for k in RESILIENCE_FIELDS:
                if k == "breaker_state":
                    res[k] = global_stats.gauge(k).value
                else:
                    res[k] = global_stats.counter(k).value
            res.update(self._resilience.stats())
            plan_stats = getattr(getattr(self.engine, "plan", None),
                                 "stats", None)
            if plan_stats is not None:
                res["fault_plan"] = plan_stats()
            out["resilience"] = res
        # tail-sampling exemplar store (ISSUE 8): retention counters; the
        # retained span trees themselves ride /flight and crash bundles
        if wanted("exemplars"):
            from strom.obs.exemplars import store as _exemplars

            out["exemplars"] = _exemplars.stats()
        # scoped telemetry (ISSUE 6 tentpole): every label scope's series as
        # {label-string: snapshot} — the JSON twin of the labeled samples
        # /metrics renders; the sections exposition skips it (nested dicts),
        # so labels appear on /metrics exactly once, via the registry.
        if wanted("scopes"):
            out["scopes"] = global_stats.scopes_snapshot()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        _request.remove_observer(self._slo_observer)
        # tuner first: its driver thread reads stats()/knob surfaces that
        # are about to be torn down (knobs stay where the search left
        # them — close is not a revert)
        if self._tuner is not None:
            self._tuner.close()
        # cluster view before the servers it scrapes through: its poll
        # thread must stop before the flight recorder it dumps to and the
        # metrics server serving /cluster go away
        if self._cluster is not None:
            self._cluster.close()
        # peer service down first: no new serve can start a cache/spill
        # read (or a scheduler grant) against a closing context, and the
        # consult stops probing peers before the engine goes away
        if self._peer_server is not None:
            self._peer_server.close()
        if self._peer_tier is not None:
            self._peer_tier.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
        if self._history is not None:
            self._history.close()
        if self._flight is not None:
            self._flight.close()
        self._executor.shutdown(wait=True)
        self._group_executor.shutdown(wait=True)
        self._resilience.close()
        self.engine.close()
        if self._spill is not None:
            # after the engine: no gather can be mid-consult anymore
            if self._hot_cache is not None:
                self._hot_cache.spill = None
            self._spill.close()
        if self._witness_enabled_here:
            # revert the witness THIS context turned on: locks already
            # constructed as WitnessLocks keep witnessing (the graph is
            # always live), but later contexts' make_lock sites go back
            # to plain threading.Lock. A context created while this one
            # was open keeps its witnessed locks — edges stay valid.
            from strom.utils.locks import enable_witness

            enable_witness(False)
