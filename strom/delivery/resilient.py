"""Engine failover + hedged-read support (ISSUE 9 tentpole, delivery half).

:class:`ResilientIo` is owned by a :class:`StromContext` and sits between
the delivery read paths and the engine:

- a per-engine :class:`~strom.engine.resilience.CircuitBreaker` records
  every demand gather's outcome. While CLOSED, reads ride the primary
  engine exactly as before; the gather that TRIPS it (and every gather
  while OPEN) reroutes to a lazily-built ``python_engine`` fallback —
  fresh fds over the same paths, the portable path that keeps serving
  when the uring/native path wedges. HALF_OPEN probes ride real traffic.
- the streamed path uses :meth:`read_chunk_fallback` for per-chunk
  recovery (a failed chunk no longer kills the batch) and for hedged
  reads (a chunk quiet past the adaptive threshold is re-read on the
  fallback; first completion wins).

The fallback engine is built on first use and serialized under its own
lock (the python engine's gather path is single-driver); chunks are
remapped path-wise, so failover works for exactly the reads the delivery
layer planned — engine-level callers with untracked fds stay primary-only.
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

import numpy as np

from strom.engine.base import DeadlineExceeded, EngineError
from strom.engine.resilience import (CircuitBreaker, HedgeController,
                                     classify_errno)
from strom.utils.locks import make_lock


class ResilientIo:
    def __init__(self, config, engine, *, scope=None,
                 on_trip: "Callable[[str], None] | None" = None):
        from strom.utils.stats import global_stats

        self.config = config
        self.engine = engine
        self.scope = scope if scope is not None else global_stats
        self.breaker: "CircuitBreaker | None" = None
        if getattr(config, "breaker_enabled", True):
            self.breaker = CircuitBreaker(
                window_s=config.breaker_window_s,
                min_events=config.breaker_min_events,
                error_rate=config.breaker_error_rate,
                cooldown_s=config.breaker_cooldown_s,
                half_open_successes=config.breaker_half_open_successes,
                scope=self.scope, on_trip=on_trip,
                name=getattr(engine, "name", "engine"))
        self.hedge: "HedgeController | None" = None
        # zero floor + zero multiplier is the documented OFF spelling (a
        # 0-threshold controller would hedge every incomplete chunk on
        # every poll — the opposite of disabled)
        if getattr(config, "hedge_enabled", True) \
                and (config.hedge_min_s > 0 or config.hedge_multiplier > 0):
            self.hedge = HedgeController(
                min_s=config.hedge_min_s,
                multiplier=config.hedge_multiplier)
        self._fb = None
        self._fb_failed = False
        self._fb_lock = make_lock("resil.fallback")    # creation + fi map
        self._fb_serial = make_lock("resil.fallback_serial")  # one fallback gather at a time
        self._fb_fi: dict[str, int] = {}

    # -- fallback engine -----------------------------------------------------
    def fallback_engine(self):
        """The python fallback engine, built on first use (None when it
        cannot be built — failover then degrades to plain propagation)."""
        with self._fb_lock:
            if self._fb is not None or self._fb_failed:
                return self._fb
            try:
                from strom.engine.python_engine import PythonEngine

                self._fb = PythonEngine(self.config)
            except Exception:
                self._fb_failed = True
            return self._fb

    def _fb_index(self, path: str) -> int:
        fb = self.fallback_engine()
        with self._fb_lock:
            fi = self._fb_fi.get(path)
            if fi is None:
                fi = fb.register_file(path,
                                      o_direct=self.config.o_direct)
                self._fb_fi[path] = fi
            return fi

    def can_fallback(self, chunks: Sequence[tuple[int, int, int, int]],
                     idx_paths: dict[int, str]) -> bool:
        """Failover needs a path per chunk (fallback fds are fresh opens).
        Deliberately does NOT build the fallback engine: this runs on
        every healthy demand gather, and the lifeboat (a second buffer
        pool + worker threads) must cost nothing until a read actually
        fails over."""
        if not chunks or any(fi not in idx_paths for (fi, _, _, _) in chunks):
            return False
        return not self._fb_failed

    def fallback_read(self, chunks: Sequence[tuple[int, int, int, int]],
                      dest: np.ndarray, idx_paths: dict[int, str]) -> int:
        """Execute a whole planned gather on the fallback engine (chunks
        remapped path-wise). Serialized: the fallback is the lifeboat, not
        a second fleet."""
        fb = self.fallback_engine()
        if fb is None:
            raise EngineError(5, "failover requested but no fallback engine")
        remapped = [(self._fb_index(idx_paths[fi]), fo, do, ln)
                    for (fi, fo, do, ln) in chunks]
        with self._fb_serial:
            n = fb.read_vectored(remapped, dest,
                                 retries=self.config.io_retries)
        self.scope.add("failover_reads")
        self.scope.add("failover_bytes", n)
        return n

    def read_chunk_fallback(self, path: str, file_off: int, length: int,
                            out: np.ndarray) -> bool:
        """One chunk on the fallback path (streamed recovery / hedges):
        read file[file_off : file_off+length) into *out*. True on a full
        read; False degrades quietly (the caller keeps its error)."""
        fb = self.fallback_engine()
        if fb is None:
            return False
        try:
            fi = self._fb_index(path)
            with self._fb_serial:
                n = fb.read_vectored([(fi, file_off, 0, length)], out,
                                     retries=self.config.io_retries)
            return n == length
        except (EngineError, OSError):
            return False

    # -- the demand-path wrapper --------------------------------------------
    def execute(self, primary: Callable[[], int],
                chunks: Sequence[tuple[int, int, int, int]],
                dest: np.ndarray, idx_paths: dict[int, str],
                arbitrate: "Callable[[Callable[[], int]], int] | None"
                = None) -> int:
        """Run a planned demand gather with breaker + failover semantics:

        - breaker CLOSED (or allowing a half-open probe): run *primary*
          (the scheduler-arbitrated / engine-locked gather). Success and
          failure both feed the breaker. A TRANSIENT failure whose record
          leaves the breaker OPEN (this gather tripped it, or re-failed a
          probe) reroutes THIS gather to the fallback; otherwise the
          error propagates — a lone failure is the caller's to see, same
          as it ever was.
        - breaker OPEN: straight to the fallback (primary never touched);
          gathers that cannot fail over (untracked fds) still run primary.
        - DeadlineExceeded always propagates: the deadline is the
          contract, a slower lifeboat does not honor it.

        *arbitrate* (the owning context's scheduler wrapper) runs every
        fallback read: it receives a read-one-slice callable and drives
        it under the tenant's arbitration — budgets charged, fair-drain
        queued, slice-preemptible exactly like the primary path. The
        breaker reroutes the ENGINE, not the multi-tenant contract.
        """
        br = self.breaker
        can_fb = self.can_fallback(chunks, idx_paths)

        def fallback() -> int:
            read_slice = (lambda sl: self.fallback_read(sl, dest,
                                                        idx_paths))
            if arbitrate is not None:
                return arbitrate(read_slice)
            return read_slice(chunks)

        # allow() is consulted whether or not THIS gather can fail over:
        # it owns the OPEN -> HALF_OPEN cooldown transition, and with no
        # fallback available the primary below doubles as the probe —
        # otherwise an unfallbackable workload leaves the breaker OPEN
        # (degraded on every surface) long after the engine recovered
        if br is not None and not br.allow() and can_fb:
            return fallback()
        try:
            n = primary()
        except DeadlineExceeded:
            raise
        except EngineError as e:
            if br is None:
                raise
            if classify_errno(e.errno or 5) == "permanent":
                # a caller bug (EINVAL, EBADF, ...) fails identically on
                # any engine — it is not evidence about THIS engine's
                # health and must not trip a fleet-wide failover
                raise
            br.record_failure()
            if br.state != CircuitBreaker.OPEN or not can_fb:
                raise
            return fallback()
        if br is not None:
            br.record_success()
        return n

    # -- observability / lifecycle ------------------------------------------
    def stats(self) -> dict:
        out = {}
        if self.breaker is not None:
            out.update(self.breaker.info())
        out["failover_available"] = self._fb is not None
        if self.hedge is not None:
            out["hedge_threshold_us"] = round(
                self.hedge.threshold_s() * 1e6, 1)
        return out

    def close(self) -> None:
        with self._fb_lock:
            fb, self._fb = self._fb, None
            self._fb_failed = True
        if fb is not None:
            fb.close()
