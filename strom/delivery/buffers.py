"""Page-aligned host slab allocation.

These slabs are the destination the NVMe reads land in *and* the buffer the
XLA runtime serializes from during host→HBM transfer — one landing spot, no
bounce copy (SURVEY.md §7.4 hard part #1).  The TPU-world analogue of the
reference pinning GPU BAR1 pages for the SSD's DMA engine (SURVEY.md §3.2;
reference cite UNVERIFIED — empty mount, SURVEY.md §0).
"""

from __future__ import annotations

import ctypes
import mmap
import threading

import numpy as np

_libc = ctypes.CDLL(None, use_errno=True)

PAGE = mmap.PAGESIZE
_MAP_POPULATE = getattr(mmap, "MAP_POPULATE", 0x8000)


def alloc_aligned(nbytes: int, *, pin: bool = False, populate: bool = False,
                  dtype=np.uint8) -> np.ndarray:
    """Allocate a page-aligned, optionally mlock'd uint8 slab as a numpy array.

    The mmap stays alive as long as the returned array (numpy holds the buffer
    via its .base chain). O_DIRECT reads require page alignment — a plain
    np.empty gives 16-byte alignment only.

    populate=True prefaults the pages inside the mmap call — lazy faulting
    during the read serializes against DMA submission (~0.5 ms/MiB measured),
    which is exactly the bounce-free hot path's enemy (SURVEY.md §7.4 #1).
    """
    if nbytes <= 0:
        raise ValueError("nbytes must be positive")
    padded = (nbytes + PAGE - 1) // PAGE * PAGE
    flags = mmap.MAP_PRIVATE | mmap.MAP_ANONYMOUS
    if populate:
        flags |= _MAP_POPULATE
    try:
        mm = mmap.mmap(-1, padded, flags=flags)
    except (ValueError, OSError):
        mm = mmap.mmap(-1, padded)  # kernel without MAP_POPULATE
    if pin:
        addr = ctypes.addressof(ctypes.c_char.from_buffer(mm))
        _libc.mlock(ctypes.c_void_p(addr), ctypes.c_size_t(padded))  # best effort
    arr = np.frombuffer(mm, dtype=np.uint8)[:nbytes]
    if dtype is not np.uint8:
        arr = arr.view(dtype)
    return arr


class SlabPool:
    """Recycles aligned slabs so steady-state transfers fault no pages.

    The recycle contract is the same lifetime handshake the reference does
    with P2P page refcounts + free callbacks (SURVEY.md §7.4 hard part #3):
    `release()` may only be called once nothing reads the slab anymore — for
    delivery that means after the device transfer completed
    (`block_until_ready`), and never on backends where `device_put` aliases
    host memory (jax CPU) instead of copying.
    """

    def __init__(self, max_bytes: int = 512 * 1024 * 1024):
        self.max_bytes = max_bytes
        self._free: dict[int, list[np.ndarray]] = {}
        self._cached_bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def acquire(self, nbytes: int) -> np.ndarray:
        with self._lock:
            bucket = self._free.get(nbytes)
            if bucket:
                self.hits += 1
                self._cached_bytes -= nbytes
                return bucket.pop()
            self.misses += 1
        return alloc_aligned(nbytes, populate=True)

    def release(self, arr: np.ndarray) -> None:
        nbytes = arr.nbytes
        with self._lock:
            if self._cached_bytes + nbytes > self.max_bytes:
                return  # let it drop; GC unmaps
            self._free.setdefault(nbytes, []).append(arr)
            self._cached_bytes += nbytes

    def stats(self) -> dict:
        with self._lock:
            return {"cached_bytes": self._cached_bytes, "hits": self.hits,
                    "misses": self.misses,
                    "buckets": {k: len(v) for k, v in self._free.items()}}
