"""Page-aligned host slab allocation.

These slabs are the destination the NVMe reads land in *and* the buffer the
XLA runtime serializes from during host→HBM transfer — one landing spot, no
bounce copy (SURVEY.md §7.4 hard part #1).  The TPU-world analogue of the
reference pinning GPU BAR1 pages for the SSD's DMA engine (SURVEY.md §3.2;
reference cite UNVERIFIED — empty mount, SURVEY.md §0).
"""

from __future__ import annotations

import ctypes
import mmap

import numpy as np

_libc = ctypes.CDLL(None, use_errno=True)

PAGE = mmap.PAGESIZE


def alloc_aligned(nbytes: int, *, pin: bool = False, dtype=np.uint8) -> np.ndarray:
    """Allocate a page-aligned, optionally mlock'd uint8 slab as a numpy array.

    The mmap stays alive as long as the returned array (numpy holds the buffer
    via its .base chain). O_DIRECT reads require page alignment — a plain
    np.empty gives 16-byte alignment only.
    """
    if nbytes <= 0:
        raise ValueError("nbytes must be positive")
    padded = (nbytes + PAGE - 1) // PAGE * PAGE
    mm = mmap.mmap(-1, padded)
    if pin:
        addr = ctypes.addressof(ctypes.c_char.from_buffer(mm))
        _libc.mlock(ctypes.c_void_p(addr), ctypes.c_size_t(padded))  # best effort
    arr = np.frombuffer(mm, dtype=np.uint8)[:nbytes]
    if dtype is not np.uint8:
        arr = arr.view(dtype)
    return arr
