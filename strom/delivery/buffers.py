"""Page-aligned host slab allocation.

These slabs are the destination the NVMe reads land in *and* the buffer the
XLA runtime serializes from during host→HBM transfer — one landing spot, no
bounce copy (SURVEY.md §7.4 hard part #1).  The TPU-world analogue of the
reference pinning GPU BAR1 pages for the SSD's DMA engine (SURVEY.md §3.2;
reference cite UNVERIFIED — empty mount, SURVEY.md §0).
"""

from __future__ import annotations

import ctypes
import mmap
import threading
import weakref

import numpy as np
from strom.utils.locks import make_lock

_libc = ctypes.CDLL(None, use_errno=True)

PAGE = mmap.PAGESIZE
HUGE_PAGE = 2 << 20  # default hugetlb size on x86_64/aarch64
_MAP_POPULATE = getattr(mmap, "MAP_POPULATE", 0x8000)
_MAP_HUGETLB = getattr(mmap, "MAP_HUGETLB", 0x40000)


def buf_addr(arr: np.ndarray) -> int:
    """Raw base address of an array's first byte — the key every
    registration/binding layer (io_uring dest table, mbind, keepalives)
    uses for host buffers."""
    return arr.view(np.uint8).reshape(-1).__array_interface__["data"][0]


def _mlock_mm(mm: mmap.mmap) -> bool:
    """mlock an anonymous mapping. True on success (RLIMIT_MEMLOCK may say no)."""
    addr = ctypes.addressof(ctypes.c_char.from_buffer(mm))
    return _libc.mlock(ctypes.c_void_p(addr), ctypes.c_size_t(len(mm))) == 0


def alloc_aligned(nbytes: int, *, pin: bool = False, populate: bool = False,
                  dtype=np.uint8, huge: bool = False) -> np.ndarray:
    """Allocate a page-aligned, optionally mlock'd uint8 slab as a numpy array.

    The mmap stays alive as long as the returned array (numpy holds the buffer
    via its .base chain). O_DIRECT reads require page alignment — a plain
    np.empty gives 16-byte alignment only.

    populate=True prefaults the pages inside the mmap call — lazy faulting
    during the read serializes against DMA submission (~0.5 ms/MiB measured),
    which is exactly the bounce-free hot path's enemy (SURVEY.md §7.4 #1).

    huge=True tries MAP_HUGETLB (2MiB pages: 512x fewer TLB entries and
    fewer per-IO page pins; SURVEY.md §2.2 staging-pool row) and silently
    falls back to normal pages when no hugepages are reserved
    (/proc/sys/vm/nr_hugepages = 0 is the common default).
    """
    if nbytes <= 0:
        raise ValueError("nbytes must be positive")
    flags = mmap.MAP_PRIVATE | mmap.MAP_ANONYMOUS
    if populate:
        flags |= _MAP_POPULATE
    mm = None
    if huge:
        hpadded = (nbytes + HUGE_PAGE - 1) // HUGE_PAGE * HUGE_PAGE
        try:
            mm = mmap.mmap(-1, hpadded, flags=flags | _MAP_HUGETLB)
            padded = hpadded
        except OSError:
            mm = None  # unreserved/unsupported → normal pages below
    if mm is None:
        padded = (nbytes + PAGE - 1) // PAGE * PAGE
        try:
            mm = mmap.mmap(-1, padded, flags=flags)
        except (ValueError, OSError):
            mm = mmap.mmap(-1, padded)  # kernel without MAP_POPULATE
    if pin:
        _mlock_mm(mm)  # best effort
    arr = np.frombuffer(mm, dtype=np.uint8)[:nbytes]
    if dtype is not np.uint8:
        arr = arr.view(dtype)
    return arr


def size_class(nbytes: int) -> int:
    """Round a request up to its allocation size class.

    Classes are quarter-power-of-two steps (4KiB, ..., 1MiB, 1.25MiB, 1.5MiB,
    1.75MiB, 2MiB, 2.5MiB, ...): worst-case internal waste is 25%, and every
    class is a page multiple. Quantizing means workloads with varying batch
    geometry land on a handful of classes and recycle slabs, where exact-size
    buckets degenerate to 100% misses + MAP_POPULATE faulting per transfer
    (VERDICT.md weak #7).
    """
    n = max(int(nbytes), PAGE)
    p = 1 << (n.bit_length() - 1)          # largest pow2 <= n
    step = max(p // 4, PAGE)
    return (n + step - 1) // step * step


class SlabPool:
    """Recycles aligned slabs so steady-state transfers fault no pages.

    Slabs are allocated at size-class granularity (see :func:`size_class`) and
    acquire() hands out a view of the first ``nbytes``; release() walks the
    view's ``.base`` chain back to the class-sized slab, so mixed-size
    workloads recycle instead of missing on every distinct size.

    Optionally mlocks slabs up to ``max_mlock_bytes`` (pinned pages keep the
    host side of the HBM transfer from faulting mid-DMA); past the cap slabs
    stay unpinned rather than failing.

    The recycle contract is the same lifetime handshake the reference does
    with P2P page refcounts + free callbacks (SURVEY.md §7.4 hard part #3):
    `release()` may only be called once nothing reads the slab anymore — for
    delivery that means after the device transfer completed
    (`block_until_ready`), and never on backends where `device_put` aliases
    host memory (jax CPU) instead of copying.
    """

    def __init__(self, max_bytes: int = 512 * 1024 * 1024, *,
                 pin: bool = False, max_mlock_bytes: int = 0,
                 huge: bool = False, on_alloc=None):
        self.max_bytes = max_bytes
        self.pin = pin
        self.max_mlock_bytes = max_mlock_bytes
        # 2MiB-page slabs: size classes round up to HUGE_PAGE so the bucket
        # key equals the mmap length whichever page size backs it
        self.huge = huge
        # called once per FRESH slab (recycled slabs keep their placement):
        # delivery hooks NUMA mbind here
        self.on_alloc = on_alloc
        self._free: dict[int, list[np.ndarray]] = {}  # class size -> base arrays
        self._cached_bytes = 0
        self._lock = make_lock("slab.pool")
        self.mlocked_bytes = 0
        self.hits = 0
        self.misses = 0
        # outstanding acquired-not-released bytes (class-rounded, the same
        # unit the budget is billed): the occupancy signal the multi-tenant
        # scheduler's admission control gates on, mirrored into the global
        # registry as the slab_pool_bytes_in_use gauge so admission
        # decisions are observable on /metrics. A slab the caller drops
        # without release() counts as in-use until its GC — honest, since
        # its pages really are committed until the munmap.
        self.in_use_bytes = 0
        # change hooks (scheduler admission gate): poked after every
        # acquire/release so queued background admits re-check occupancy
        # without polling
        self._change_hooks: list = []

    def add_change_hook(self, fn) -> None:
        """Register a no-arg callable invoked (outside the pool lock) after
        every occupancy change."""
        self._change_hooks.append(fn)

    def _occupancy_changed(self) -> None:
        from strom.utils.stats import global_stats

        global_stats.set_gauge("slab_pool_bytes_in_use", self.in_use_bytes)
        for fn in self._change_hooks:
            try:
                fn()
            # stromlint: ignore[swallowed-exceptions] -- a poke hook (the
            # admission gate's occupancy re-check) failing must never fail
            # the allocation it rides on; the gate re-polls on a timeout
            # anyway, so a lost poke degrades latency, not correctness
            except Exception:
                pass

    @staticmethod
    def _base(arr: np.ndarray) -> np.ndarray:
        while isinstance(arr.base, np.ndarray):
            arr = arr.base
        return arr

    def _unpin(self, n: int) -> None:
        # weakref.finalize callback: the mmap was destroyed (munmap munlocks)
        with self._lock:
            self.mlocked_bytes -= n

    def acquire(self, nbytes: int) -> np.ndarray:
        cls = size_class(nbytes)
        if self.huge:
            cls = (cls + HUGE_PAGE - 1) // HUGE_PAGE * HUGE_PAGE
        with self._lock:
            bucket = self._free.get(cls)
            if bucket:
                self.hits += 1
                self._cached_bytes -= cls
                self.in_use_bytes += cls
                slab = bucket.pop()[:nbytes]
            else:
                slab = None
                self.misses += 1
                self.in_use_bytes += cls
                # reserve under the lock: concurrent misses (prefetch
                # workers + the stream reader share one pool) must not both
                # pass a check-then-act cap test and pin past
                # max_mlock_bytes
                reserve = self.pin and \
                    self.mlocked_bytes + cls <= self.max_mlock_bytes
                if reserve:
                    self.mlocked_bytes += cls
        self._occupancy_changed()
        if slab is not None:
            return slab
        # past here the reservation is settled either by the finalizer
        # (mlock succeeded — munmap munlocks) or immediately (mlock
        # refused); until then a failure must hand it back
        mlock_settled = not reserve
        try:
            base = self._base(
                alloc_aligned(cls, populate=True, huge=self.huge))
            if reserve:
                mm = base.base
                if isinstance(mm, mmap.mmap) and _mlock_mm(mm):
                    # exactly-once release of the reservation, tied to the
                    # mmap's own lifetime: slabs that are dropped, leaked by
                    # a failing caller, or GC'd all reach munmap, which
                    # munlocks
                    weakref.finalize(mm, self._unpin, cls)
                else:
                    with self._lock:
                        self.mlocked_bytes -= cls
                mlock_settled = True
            if self.on_alloc is not None:
                self.on_alloc(base)
        except Exception:
            # the caller never gets a slab it could release(): roll the
            # occupancy charge back, or it would permanently inflate
            # slab_pool_bytes_in_use and wedge the admission gate past the
            # high-water mark on phantom bytes
            with self._lock:
                self.in_use_bytes -= cls
                if not mlock_settled:
                    self.mlocked_bytes -= cls
            self._occupancy_changed()
            raise
        return base[:nbytes]

    def release(self, arr: np.ndarray) -> None:
        base = self._base(arr)
        cls = base.nbytes
        with self._lock:
            # in-use drops whether the slab recycles or falls to GC: either
            # way the caller is done with it (admission headroom returns)
            self.in_use_bytes -= cls
            if self._cached_bytes + cls <= self.max_bytes:
                self._free.setdefault(cls, []).append(base)
                self._cached_bytes += cls
            # else: let it drop; GC unmaps (finalizer settles mlock)
        self._occupancy_changed()

    def stats(self) -> dict:
        with self._lock:
            return {"cached_bytes": self._cached_bytes,
                    "slab_in_use_bytes": self.in_use_bytes,
                    "huge": self.huge,
                    "mlocked_bytes": self.mlocked_bytes,
                    "mlock_cap_bytes": self.max_mlock_bytes,
                    "hits": self.hits,
                    "misses": self.misses,
                    "buckets": {k: len(v) for k, v in self._free.items()}}
