"""Async DMA handles — the MEMCPY_WAIT side of the contract.

The reference's async submit returns a DMA task id; STROM_IOCTL__MEMCPY_WAIT
blocks until the interrupt-driven completion path retires every chunk and
surfaces the aggregated status (SURVEY.md §3.3; reference cite UNVERIFIED —
empty mount, SURVEY.md §0).  strom-tpu's handle wraps the full pipeline
(engine reads → host slab → dispatch of host→HBM transfer) and resolves to a
`jax.Array`.  Because jax dispatch is asynchronous, `.result()` returning an
array does NOT block on the HBM copy — compute ordered after it overlaps the
transfer, which is exactly the "completion becomes an XLA token" design
(BASELINE.json:5).
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from typing import Any, Callable

from strom.utils.stats import global_stats
from strom.utils.locks import make_lock


class DMAHandle:
    """Future-like handle for an in-flight ssd2tpu copy."""

    def __init__(self, future: concurrent.futures.Future, *, nbytes: int,
                 label: str = ""):
        self._future = future
        self.nbytes = nbytes
        self.label = label
        self.submitted_at = time.monotonic()
        self._done_at: float | None = None
        self._lock = make_lock("app.handle")
        future.add_done_callback(self._on_done)

    def _on_done(self, _f) -> None:
        with self._lock:
            self._done_at = time.monotonic()
        global_stats.add("handles_completed")

    # -- MEMCPY_WAIT equivalents -------------------------------------------
    def done(self) -> bool:
        return self._future.done()

    def wait(self, timeout: float | None = None) -> "DMAHandle":
        """Block until the host-side pipeline retires (reads complete and the
        device transfer is dispatched). Raises the pipeline's error, if any."""
        self._future.result(timeout)
        return self

    def result(self, timeout: float | None = None) -> Any:
        """The delivered jax.Array (sharded when a sharding was requested)."""
        return self._future.result(timeout)

    def exception(self, timeout: float | None = None):
        return self._future.exception(timeout)

    def block_until_ready(self, timeout: float | None = None) -> Any:
        """Full barrier: also waits for the host→HBM transfer itself."""
        arr = self.result(timeout)
        return arr.block_until_ready() if hasattr(arr, "block_until_ready") else arr

    @property
    def elapsed_s(self) -> float:
        end = self._done_at if self._done_at is not None else time.monotonic()
        return end - self.submitted_at

    def __repr__(self) -> str:
        state = "done" if self.done() else "pending"
        return f"DMAHandle({self.label or hex(id(self))}, {self.nbytes}B, {state})"


def completed_handle(value: Any, nbytes: int = 0, label: str = "") -> DMAHandle:
    f: concurrent.futures.Future = concurrent.futures.Future()
    f.set_result(value)
    return DMAHandle(f, nbytes=nbytes, label=label)


def deferred_handle(fn: Callable[[], Any], executor: concurrent.futures.Executor,
                    nbytes: int, label: str = "") -> DMAHandle:
    return DMAHandle(executor.submit(fn), nbytes=nbytes, label=label)
