"""Prefetch queue: keep N batches in flight so compute never waits on I/O.

This is the consumer-facing half of the reference's double-buffered
I/O/compute-overlap pattern (SURVEY.md §3.5: buffer ring, async SSD2GPU into
the next buffer while the kernel consumes the previous one; reference cite
UNVERIFIED — empty mount, SURVEY.md §0).  The "0 data-stall steps" north-star
counter lives here (BASELINE.json:5): a stall is recorded whenever ``next()``
has to block because the head-of-line batch isn't ready.

Depth can be hand-picked (``depth=``) or auto-tuned (``auto_depth=True``): a
feedback controller tracks per-batch lead time (how long the head batch sat
ready before consumption) and the stall counter, GROWS depth multiplicatively
on a stall (a stall means the dispatch-ahead window was too shallow for the
observed jitter) and SHRINKS it by one step once the queue has run fully
ready for a patience window (lead time ample: the extra in-flight batches
only pin slab-pool memory). Depth stays inside [min_depth, max_depth];
callers bound max_depth by slab-pool capacity (:func:`bound_depth`).
"""

from __future__ import annotations

import concurrent.futures
import threading
from collections import deque
from typing import Callable, Generic, Iterable, Iterator, TypeVar

import time

from strom.obs.events import ring
from strom.utils.stats import StatsRegistry, global_stats
from strom.utils.locks import make_lock

T = TypeVar("T")

# auto-tune shape: grow is multiplicative (a stall under-estimates the needed
# window by an unknown factor; doubling finds it in log steps — the resnet
# JPEG arm went 6 stalls at fixed depth 2), shrink is one step per patience
# window of fully-ready pops (lead time ample), the classic AIMD asymmetry so
# depth converges from above without oscillating into stalls.
_SHRINK_PATIENCE = 8
_TRACE_CAP = 512


def bound_depth(pool_bytes: int, batch_bytes: int, *, floor: int = 2,
                cap: int = 32, reserve_bytes: int = 0) -> int:
    """Max prefetch depth a slab pool of *pool_bytes* can stage when each
    in-flight batch owns ~*batch_bytes* of slabs until its device_put
    retires. Unknown sizes (<=0) fall back to *cap*.

    *reserve_bytes* is pool capacity spoken for by someone else — the
    hot-set cache's ``hot_cache_bytes`` budget (strom/delivery/hotcache.py):
    cache entries live in pool slabs for the run's lifetime, so auto-depth
    growth sized against the FULL pool would double-commit that memory
    (depth grows, the cache admits, and together they overshoot the pool —
    ISSUE 4 satellite). A reserve at or beyond the pool collapses depth to
    *floor*, never errors: the cache keeps its budget, prefetch keeps its
    minimum overlap."""
    if pool_bytes <= 0 or batch_bytes <= 0:
        return cap
    avail = pool_bytes - max(reserve_bytes, 0)
    if avail <= 0:
        return floor
    return max(floor, min(cap, avail // batch_bytes))


class Prefetcher(Generic[T]):
    """Wraps an iterable of thunks (callables producing a batch) and runs up to
    *depth* of them ahead on an executor, yielding results in order.

    Thunks typically end in a `jax.device_put` dispatch, so "ready" here means
    the host-side work is done and the HBM transfer is enqueued — the classic
    dispatch-ahead overlap jax wants.

    With ``auto_depth=True``, *depth* is the starting point and the
    controller moves it inside [min_depth, max_depth] (see module
    docstring). ``depth_trace`` records every change as (step, new_depth).
    """

    def __init__(self, thunks: Iterable[Callable[[], T]], *, depth: int = 2,
                 executor: concurrent.futures.Executor | None = None,
                 stats: StatsRegistry | None = None,
                 auto_depth: bool = False,
                 min_depth: int = 1,
                 max_depth: int | None = None,
                 scope=None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        if min_depth < 1:
            raise ValueError("min_depth must be >= 1")
        self._auto = auto_depth
        if max_depth is None:
            max_depth = max(depth, 16) if auto_depth else depth
        if max_depth < min_depth:
            raise ValueError(f"max_depth {max_depth} < min_depth {min_depth}")
        self._min_depth = min_depth
        self._max_depth = max_depth
        self._depth = min(max(depth, min_depth), max_depth)
        self._thunks = iter(thunks)
        self._own_executor = executor is None
        # auto mode sizes its own pool at the ceiling so a grown depth has
        # workers to actually run the extra thunks in parallel
        self._executor = executor or concurrent.futures.ThreadPoolExecutor(
            max_workers=max_depth if auto_depth else depth,
            thread_name_prefix="strom-prefetch")
        self._queue: deque[concurrent.futures.Future] = deque()
        self._lock = make_lock("app.prefetch")
        self.stats = stats or StatsRegistry("prefetch")
        # telemetry scope (ISSUE 6): the pipeline's label scope, so two
        # pipelines' depth/stall series are distinguishable on /metrics;
        # None = the global registry (single-tenant behavior unchanged)
        self._scope = scope if scope is not None else global_stats
        self.stats.set_gauge("prefetch_depth", self._depth)
        # mirrored into the telemetry scope too (scoped series + global
        # aggregate), so depth and the stall count appear in /metrics and
        # bench JSON without bespoke plumbing (gauge semantics: the CURRENT
        # pipeline's state; within a scope, the latest pipeline wins)
        self._scope.set_gauge("prefetch_depth", self._depth)
        self._scope.set_gauge("prefetch_data_stall_steps", 0)
        self.depth_trace: list[tuple[int, int]] = [(0, self._depth)]
        self._ready_streak = 0
        self._was_stalled = False
        self._exhausted = False
        self._fill()

    @property
    def depth(self) -> int:
        return self._depth

    def set_depth(self, depth: int) -> None:
        """External controller surface (ISSUE 16 autotuner): move the
        target depth inside [min_depth, max_depth]. Same single-consumer
        write discipline as the internal controller; the executor was
        sized to max_depth only under auto_depth, so a hand-depth pool
        additionally caps at the worker count (a deeper queue than
        workers would just park thunks)."""
        cap = self._max_depth if self._auto \
            else min(self._max_depth, self._executor._max_workers)
        d = min(max(int(depth), self._min_depth), cap)
        self._set_depth(d, "grow" if d > self._depth else "shrink")

    def _fill(self) -> None:
        # next(thunks) runs OUTSIDE the lock: thunk generators may block
        # (e.g. the pipeline's epoch_sync DCN barrier sits at the epoch
        # boundary of the generator), and blocking under the lock would hang
        # any concurrent close(). Single-consumer discipline is assumed, as
        # everywhere else in this class.
        while True:
            with self._lock:
                if len(self._queue) >= self._depth or self._exhausted:
                    return
            try:
                thunk = next(self._thunks)
            except StopIteration:
                with self._lock:
                    self._exhausted = True
                return
            with self._lock:
                if self._exhausted:  # close() raced the pull: drop, don't submit
                    return
                fut = self._executor.submit(thunk)
                fut.add_done_callback(_stamp_done)
                self._queue.append(fut)

    def _set_depth(self, depth: int, kind: str) -> None:
        """Record a controller move (caller holds no lock; _depth writes are
        single-consumer like the rest of the class)."""
        if depth == self._depth:
            return
        self._depth = depth
        self.stats.add("depth_grow" if kind == "grow" else "depth_shrink")
        self.stats.set_gauge("prefetch_depth", depth)
        self._scope.set_gauge("prefetch_depth", depth)
        # depth changes on the timeline: the controller's moves line up
        # against the stalls that caused them
        ring.instant("prefetch.depth", cat="prefetch",
                     args={"depth": depth, "kind": kind})
        if len(self.depth_trace) < _TRACE_CAP:
            self.depth_trace.append(
                (self.stats.counter("steps").value, depth))

    def __iter__(self) -> Iterator[T]:
        return self

    def __next__(self) -> T:
        with self._lock:
            if not self._queue:
                if self._exhausted:
                    self._shutdown()
                    raise StopIteration
                fut = None
            else:
                fut = self._queue.popleft()
        if fut is None:
            # nothing queued yet (depth fill raced); refill and retry
            self._fill()
            with self._lock:
                if not self._queue:
                    self._shutdown()
                    raise StopIteration
                fut = self._queue.popleft()
        if not fut.done():
            self.stats.add("data_stall_steps")
            self._scope.set_gauge("prefetch_data_stall_steps",
                                  self.stats.counter("data_stall_steps").value)
            if not self._was_stalled:  # ready -> stall transition
                ring.instant("prefetch.state", cat="prefetch",
                             args={"state": "stall"})
                self._was_stalled = True
            t0 = time.monotonic()
            with ring.span("prefetch.stall_wait", cat="ingest_wait"):
                result = fut.result()
            self.stats.observe_us("stall_wait", (time.monotonic() - t0) * 1e6)
            if self._auto:
                # a stall: the window was too shallow for the observed jitter
                self._ready_streak = 0
                self._set_depth(min(self._depth * 2, self._max_depth), "grow")
        else:
            if self._was_stalled:  # stall -> ready transition
                ring.instant("prefetch.state", cat="prefetch",
                             args={"state": "ready"})
                self._was_stalled = False
            result = fut.result()
            done_at = getattr(fut, "_strom_done_at", None)
            if done_at is not None:
                # lead time: how long the head batch sat ready before the
                # consumer came for it — the controller's "ample" signal,
                # and the observable overlap margin per batch
                self.stats.observe_us(
                    "lead", max(time.monotonic() - done_at, 0.0) * 1e6)
            if self._auto:
                with self._lock:
                    full_ready = (len(self._queue) + 1 >= self._depth
                                  and all(f.done() for f in self._queue))
                if full_ready:
                    self._ready_streak += 1
                    if (self._ready_streak >= _SHRINK_PATIENCE
                            and self._depth > self._min_depth):
                        self._set_depth(self._depth - 1, "shrink")
                        self._ready_streak = 0
                else:
                    self._ready_streak = 0
        self.stats.add("steps")
        self._fill()
        return result

    @property
    def data_stall_steps(self) -> int:
        return self.stats.counter("data_stall_steps").value

    @property
    def steps(self) -> int:
        return self.stats.counter("steps").value

    def _shutdown(self) -> None:
        if self._own_executor:
            self._executor.shutdown(wait=False)

    def close(self) -> None:
        with self._lock:
            live = [f for f in self._queue if not f.cancel()]
            self._queue.clear()
            self._exhausted = True
        # a thunk already RUNNING when close() lands keeps using the decode
        # pool / engine the pipeline tears down right after this returns;
        # give it a bounded window to retire so the shutdown race doesn't
        # masquerade as a request failure (every such batch would otherwise
        # mint a bogus "errored" exemplar — strom/obs/exemplars.py)
        if live:
            concurrent.futures.wait(live, timeout=30.0)
        self._shutdown()


def _stamp_done(fut: concurrent.futures.Future) -> None:
    fut._strom_done_at = time.monotonic()  # type: ignore[attr-defined]
