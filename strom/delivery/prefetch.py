"""Prefetch queue: keep N batches in flight so compute never waits on I/O.

This is the consumer-facing half of the reference's double-buffered
I/O/compute-overlap pattern (SURVEY.md §3.5: buffer ring, async SSD2GPU into
the next buffer while the kernel consumes the previous one; reference cite
UNVERIFIED — empty mount, SURVEY.md §0).  The "0 data-stall steps" north-star
counter lives here (BASELINE.json:5): a stall is recorded whenever ``next()``
has to block because the head-of-line batch isn't ready.
"""

from __future__ import annotations

import concurrent.futures
import threading
from collections import deque
from typing import Callable, Generic, Iterable, Iterator, TypeVar

import time

from strom.utils.stats import StatsRegistry

T = TypeVar("T")


class Prefetcher(Generic[T]):
    """Wraps an iterable of thunks (callables producing a batch) and runs up to
    *depth* of them ahead on an executor, yielding results in order.

    Thunks typically end in a `jax.device_put` dispatch, so "ready" here means
    the host-side work is done and the HBM transfer is enqueued — the classic
    dispatch-ahead overlap jax wants.
    """

    def __init__(self, thunks: Iterable[Callable[[], T]], *, depth: int = 2,
                 executor: concurrent.futures.Executor | None = None,
                 stats: StatsRegistry | None = None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._thunks = iter(thunks)
        self._depth = depth
        self._own_executor = executor is None
        self._executor = executor or concurrent.futures.ThreadPoolExecutor(
            max_workers=depth, thread_name_prefix="strom-prefetch")
        self._queue: deque[concurrent.futures.Future] = deque()
        self._lock = threading.Lock()
        self.stats = stats or StatsRegistry("prefetch")
        self._exhausted = False
        self._fill()

    def _fill(self) -> None:
        # next(thunks) runs OUTSIDE the lock: thunk generators may block
        # (e.g. the pipeline's epoch_sync DCN barrier sits at the epoch
        # boundary of the generator), and blocking under the lock would hang
        # any concurrent close(). Single-consumer discipline is assumed, as
        # everywhere else in this class.
        while True:
            with self._lock:
                if len(self._queue) >= self._depth or self._exhausted:
                    return
            try:
                thunk = next(self._thunks)
            except StopIteration:
                with self._lock:
                    self._exhausted = True
                return
            with self._lock:
                if self._exhausted:  # close() raced the pull: drop, don't submit
                    return
                self._queue.append(self._executor.submit(thunk))

    def __iter__(self) -> Iterator[T]:
        return self

    def __next__(self) -> T:
        with self._lock:
            if not self._queue:
                if self._exhausted:
                    self._shutdown()
                    raise StopIteration
                fut = None
            else:
                fut = self._queue.popleft()
        if fut is None:
            # nothing queued yet (depth fill raced); refill and retry
            self._fill()
            with self._lock:
                if not self._queue:
                    self._shutdown()
                    raise StopIteration
                fut = self._queue.popleft()
        if not fut.done():
            self.stats.add("data_stall_steps")
            t0 = time.monotonic()
            result = fut.result()
            self.stats.observe_us("stall_wait", (time.monotonic() - t0) * 1e6)
        else:
            result = fut.result()
        self.stats.add("steps")
        self._fill()
        return result

    @property
    def data_stall_steps(self) -> int:
        return self.stats.counter("data_stall_steps").value

    @property
    def steps(self) -> int:
        return self.stats.counter("steps").value

    def _shutdown(self) -> None:
        if self._own_executor:
            self._executor.shutdown(wait=False)

    def close(self) -> None:
        with self._lock:
            for f in self._queue:
                f.cancel()
            self._queue.clear()
            self._exhausted = True
        self._shutdown()
