"""Hot-set host cache + epoch-aware readahead (ISSUE 4 tentpole).

The pipelines re-gather the same bytes from NVMe on *every* epoch and every
repeat request, even when the working set fits in host RAM. This module adds
the missing caching axis from the ROADMAP north star: an extent-keyed,
byte-budgeted, refcounted LRU over slab-pool-backed host buffers that the
delivery layer (`StromContext._read_segments`) consults before engine
submission — a full hit never touches the engine (the bytes memcpy from RAM
straight toward ``device_put``); a partial hit splits the request so only
the miss runs are submitted. ≙ the page-cache tier the reference bypasses
by design (SURVEY.md §2.1 "Page-cache fallback"), rebuilt in userspace where
O_DIRECT means the kernel's own cache never sees these bytes.

Design points, in the order they bit previous subsystems:

- **Stable keys.** Entries key on ``(physical path, byte range)`` AFTER
  extent/stripe expansion, not on caller segments: an ExtentList is rebuilt
  per batch with batch-relative logical offsets, and coalescing merges
  fragments differently depending on shuffle order — physical ranges are
  the only identity that repeats across epochs. Interval arithmetic (not
  whole-entry equality) serves overlaps, so epoch N+1's differently-split
  request still hits epoch N's entries.
- **Second-touch admission** (``hot_cache_admit="second_touch"``): the first
  epoch only *observes* (a block-granular touch ledger, bounded LRU), the
  second admits — one-shot scans never displace the hot set. Force-admit
  (``"always"``) is the knob for known-repeating workloads and the warm/cold
  bench arms; readahead always force-admits (warming IS the prediction).
- **Refcounted eviction.** Entries are pinned while anything reads them — a
  serve-memcpy, or a ``device_put`` sourced zero-copy from the cached slab
  (the full-hit fast path in ``memcpy_ssd2tpu``). Eviction under byte
  pressure skips pinned entries and an evicted-while-pinned entry only
  returns its slab to the pool on the LAST unpin, so a recycled slab can
  never be overwritten mid-put (the same lifetime handshake as
  SlabPool.release, SURVEY.md §7.4 hard part #3).
- **Readahead yields to demand.** The epoch-aware readahead thread pulls the
  sampler's upcoming-batch window (``EpochShuffleSampler.peek`` — it crosses
  the epoch boundary, so the next epoch's head warms while the tail of this
  one trains) and warms cache misses in slices of the engine's in-flight
  budget (``queue_depth * block_size``), checking for in-flight demand reads
  before every slice: a demand gather never queues behind more than one
  readahead slice, and an active demand read aborts the warming pass
  entirely (``cache_readahead_yields``).

Observability: ``cache_hit/miss/admitted/evicted/readahead`` counters and
the ``cache_hit_ratio`` gauge in the global registry (typed via
``all_counter_names`` for /metrics), the ``cache`` section of
``StromContext.stats()`` (→ /stats and Prometheus exposition), and
``cat="cache"`` spans in the event ring (serve/admit/readahead on the
timeline next to the reads they replace).
"""

from __future__ import annotations

import bisect
import threading
from collections import OrderedDict
from typing import Any, Callable, Iterable

import numpy as np

from strom.delivery.buffers import HUGE_PAGE, alloc_aligned, size_class
from strom.utils.locks import make_lock

ADMIT_POLICIES = ("second_touch", "always")

# bench-JSON columns the warm/cold epoch phase pair emits (cli.py
# _cache_epoch_phases), single-sourced so the driver's per-arm copy loop
# (bench.py) and the compare_rounds "cache" section cannot drift from the
# producer — the same contract STALL_FIELDS enforces for stall attribution
CACHE_BENCH_FIELDS = (
    "cold_images_per_s",
    "warm_images_per_s",
    "warm_vs_cold",
    "cache_hit_bytes",
    "cache_miss_bytes",
    "cache_admitted_bytes",
    "cache_readahead_bytes",
    "cache_epoch_steps",
)


class _Entry:
    """One cached physical range: ``buf[:hi-lo]`` holds file bytes [lo, hi)
    of ``skey``. ``refs`` pins it against eviction; ``dead`` marks an entry
    evicted while pinned (slab freed on last unpin). ``charge`` is what the
    byte budget is billed — the backing slab's ALLOCATED size (size class,
    2MiB-rounded under huge pages), not the logical length, so resident
    memory actually respects ``hot_cache_bytes``."""

    __slots__ = ("skey", "lo", "hi", "buf", "refs", "dead", "charge",
                 "tenant", "demote")

    def __init__(self, skey: Any, lo: int, hi: int, buf: np.ndarray,
                 charge: int, tenant: "str | None" = None):
        self.skey = skey
        self.lo = lo
        self.hi = hi
        self.buf = buf
        self.refs = 0
        self.dead = False
        self.charge = charge
        # owning tenant for partition accounting (ISSUE 7): None = charged
        # to the shared budget only (single-tenant behavior unchanged)
        self.tenant = tenant
        # evicted under byte pressure with a spill tier attached (ISSUE 13):
        # the freeing caller demotes the bytes to NVMe before returning the
        # slab to the pool. clear() leaves it False — a cleared cache drops,
        # it does not spill (the bench epoch pairs depend on that).
        self.demote = False

    @property
    def nbytes(self) -> int:
        return self.hi - self.lo


class HotCache:
    """Extent-keyed, byte-budgeted, refcounted LRU of host byte ranges.

    Thread-safe: metadata mutates under one lock; the actual byte copies
    happen outside it with the source entries pinned. Buffers come from the
    delivery slab pool when one is supplied (recycled, NUMA-placed,
    engine-registered slabs) and fall back to fresh aligned allocations.
    """

    def __init__(self, max_bytes: int, *, pool=None,
                 admit: str = "second_touch", block_bytes: int = 1 << 20,
                 touch_capacity: int = 1 << 16, scope=None):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        if admit not in ADMIT_POLICIES:
            raise ValueError(f"admit must be one of {ADMIT_POLICIES}, "
                             f"got {admit!r}")
        if block_bytes <= 0:
            raise ValueError("block_bytes must be positive")
        self.max_bytes = max_bytes
        self.admit_policy = admit
        self._block = block_bytes
        self._pool = pool
        # NVMe spill tier (ISSUE 13): when attached (StromContext wires a
        # strom.delivery.spill.SpillTier for spill_bytes > 0), entries
        # evicted under byte pressure demote there instead of vanishing —
        # the delivery consult then serves them from the spill file with
        # zero source-engine reads. None = single-tier behavior unchanged.
        self.spill = None
        # phase gate: a disabled cache serves/admits/warms nothing (entries
        # are kept). The bench arms use it to scope the cache to the
        # cold/warm epoch pair so the pre-existing headline phases
        # (flat-out img/s, train stalls, stall attribution) keep their
        # round-over-round meaning; library contexts stay always-on.
        self.enabled = True
        self._lock = make_lock("cache.meta")
        # skey -> entries sorted by lo (disjoint ranges per skey)
        self._index: dict[Any, list[_Entry]] = {}
        # LRU: oldest first; value is the entry (key is its id())
        self._lru: "OrderedDict[int, _Entry]" = OrderedDict()
        # block-granular touch ledger for second-touch admission, bounded
        # LRU so a giant cold scan can't grow it without limit
        self._touched: "OrderedDict[tuple, None]" = OrderedDict()
        self._touch_cap = touch_capacity
        self.bytes = 0
        # per-tenant partitions (ISSUE 7 tentpole): tenant -> byte cap
        # within the shared budget, charged at admit time. A tenant at its
        # cap evicts ITS OWN unpinned LRU entries first; only if that
        # frees nothing is the admission dropped — one tenant's working
        # set can never displace every other tenant's.
        self._partitions: dict[str, int] = {}
        self._tenant_bytes: dict[str, int] = {}
        # telemetry scope (ISSUE 6): the owning context's label scope, so a
        # tenant's cache traffic is distinguishable on /metrics; None = the
        # global registry (single-tenant behavior unchanged)
        from strom.utils.stats import global_stats

        self._scope = scope if scope is not None else global_stats
        # instance tallies (authoritative for stats()); the same names are
        # mirrored into the telemetry scope (scoped series + global
        # aggregate) so /metrics typing and bench deltas work without
        # bespoke plumbing
        self.hit_bytes = 0
        self.miss_bytes = 0
        self.hits = 0
        self.misses = 0
        self.admitted_bytes = 0
        self.evictions = 0
        self.evicted_bytes = 0
        self.readahead_bytes = 0
        self.readahead_yields = 0
        self.readahead_errors = 0

    # -- allocation ---------------------------------------------------------
    def _charge(self, n: int) -> int:
        """Budget charge for an *n*-byte entry: the backing slab's allocated
        size — pool slabs round to their size class (and to 2MiB under huge
        pages), so billing logical bytes would let resident memory overshoot
        ``max_bytes`` by the rounding factor (and silently break the
        ``bound_depth`` pool reservation sized on this budget)."""
        c = size_class(n)
        if getattr(self._pool, "huge", False):
            c = (c + HUGE_PAGE - 1) // HUGE_PAGE * HUGE_PAGE
        return c

    def _alloc(self, n: int) -> np.ndarray:
        if self._pool is not None:
            return self._pool.acquire(n)
        return alloc_aligned(n)

    def _free(self, buf: np.ndarray) -> None:
        if self._pool is not None:
            self._pool.release(buf)
        # else: GC unmaps

    # -- lookup / pinning ---------------------------------------------------
    def lookup(self, skey: Any, lo: int, hi: int, *, record: bool = True,
               count_misses: bool = True
               ) -> tuple[list[tuple[int, int, np.ndarray]],
                          list[tuple[int, int]], list[_Entry]]:
        """Split [lo, hi) of *skey* into cached and missing ranges.

        Returns ``(hits, misses, pinned)``: hits are ``(h_lo, h_hi, view)``
        where *view* is a zero-copy window of the backing slab; *pinned*
        holds the entries backing those views with their refcount raised —
        the caller MUST :meth:`unpin` them once it stops reading the views
        (after the memcpy, or after a device_put sourced from them retires).
        ``record=False`` skips the hit/miss counters (readahead probes must
        not inflate the demand hit ratio). ``count_misses=False`` defers
        ONLY the miss counters to the caller (:meth:`note_miss`) — the
        spill-tier consult (ISSUE 13) uses it so a RAM miss the spill file
        serves never shows up as ``cache_miss_bytes``.
        """
        hits: list[tuple[int, int, np.ndarray]] = []
        misses: list[tuple[int, int]] = []
        pinned: list[_Entry] = []
        with self._lock:
            entries = self._index.get(skey, ())
            pos = lo
            i = bisect.bisect_right(entries, lo, key=lambda e: e.lo) - 1 \
                if entries else 0
            i = max(i, 0)
            while pos < hi and i < len(entries):
                e = entries[i]
                if e.hi <= pos:
                    i += 1
                    continue
                if e.lo >= hi:
                    break
                if e.lo > pos:
                    misses.append((pos, e.lo))
                    pos = e.lo
                s, t = max(pos, e.lo), min(hi, e.hi)
                e.refs += 1
                pinned.append(e)
                self._lru.move_to_end(id(e))
                hits.append((s, t, e.buf[s - e.lo: t - e.lo]))
                pos = t
                i += 1
            if pos < hi:
                misses.append((pos, hi))
            if record:
                hb = sum(t - s for s, t, _ in hits)
                self.hit_bytes += hb
                self.hits += len(hits)
                if count_misses:
                    self.miss_bytes += sum(t - s for s, t in misses)
                    self.misses += len(misses)
        if record:
            if hits:
                self._scope.add("cache_hits", len(hits))
                self._scope.add("cache_hit_bytes",
                                 sum(t - s for s, t, _ in hits))
            if misses and count_misses:
                self._scope.add("cache_misses", len(misses))
                self._scope.add("cache_miss_bytes",
                                 sum(t - s for s, t in misses))
        return hits, misses, pinned

    def note_miss(self, nbytes: int, n: int = 1) -> None:
        """Count a TRUE miss (no RAM entry, no spill entry) whose counting
        :meth:`lookup` deferred via ``count_misses=False``."""
        if nbytes <= 0:
            return
        with self._lock:
            self.miss_bytes += nbytes
            self.misses += n
        self._scope.add("cache_misses", n)
        self._scope.add("cache_miss_bytes", nbytes)

    def view(self, skey: Any, lo: int, hi: int, *, record: bool = True
             ) -> tuple[np.ndarray, _Entry] | None:
        """A single pinned zero-copy view when ONE entry covers the whole
        [lo, hi) — the full-hit fast path ``memcpy_ssd2tpu`` device_puts
        from directly. Caller must :meth:`unpin` after the put retires."""
        with self._lock:
            entries = self._index.get(skey, ())
            if not entries:
                return None
            i = bisect.bisect_right(entries, lo, key=lambda e: e.lo) - 1
            if i < 0:
                return None
            e = entries[i]
            if not (e.lo <= lo and hi <= e.hi):
                return None
            e.refs += 1
            self._lru.move_to_end(id(e))
            if record:
                self.hit_bytes += hi - lo
                self.hits += 1
        if record:
            self._scope.add("cache_hits")
            self._scope.add("cache_hit_bytes", hi - lo)
        return e.buf[lo - e.lo: hi - e.lo], e

    def unpin(self, entries: Iterable[_Entry]) -> None:
        """Drop pins taken by :meth:`lookup`/:meth:`view`; frees the slab
        of any entry that was evicted while pinned. Dead entries NEVER
        demote to the spill tier: pressure eviction only picks unpinned
        victims, so a dead entry can only come from invalidate()/clear()
        — and spilling at unpin time could republish bytes a concurrent
        invalidation (a write landed on the file) just purged."""
        dead: list[np.ndarray] = []
        with self._lock:
            for e in entries:
                e.refs -= 1
                if e.dead and e.refs == 0:
                    dead.append(e.buf)
                    e.buf = None  # type: ignore[assignment]
        for buf in dead:
            self._free(buf)

    def _demote_and_free(self, e: _Entry, buf: np.ndarray) -> None:
        """Outside-the-lock half of eviction (ISSUE 13): offer the evicted
        bytes to the spill tier (when attached and the eviction wanted it),
        then hand the slab back to the pool. Spill failures are counted,
        never raised — losing a demotion means a future source re-read, the
        exact behavior of the spill-less cache."""
        sp = self.spill
        if e.demote and sp is not None and e.skey is not None:
            try:
                sp.offer(e.skey, e.lo, e.hi, buf[: e.nbytes],
                         tenant=e.tenant)
            # stromlint: ignore[swallowed-exceptions] -- advisory demotion:
            # a full/closed spill file degrades to the pre-spill eviction
            # (drop), and the error is counted below
            except Exception:
                self._scope.add("spill_errors")
        self._free(buf)

    # -- admission / eviction -----------------------------------------------
    def _blocks(self, skey: Any, lo: int, hi: int) -> list[tuple]:
        return [(skey, b) for b in range(lo // self._block,
                                         (hi - 1) // self._block + 1)]

    def _touch(self, blocks: list[tuple]) -> bool:
        """Mark blocks touched; True when EVERY block had been touched
        before (the second-touch admission test)."""
        seen = all(b in self._touched for b in blocks)
        for b in blocks:
            self._touched[b] = None
            self._touched.move_to_end(b)
        while len(self._touched) > self._touch_cap:
            self._touched.popitem(last=False)
        return seen

    def set_partition(self, tenant: str, max_bytes: int) -> None:
        """Cap *tenant*'s resident bytes at *max_bytes* (0 removes the
        partition; the tenant then shares the global budget unpartitioned).
        Existing entries keep their charge — enforcement applies from the
        next admission."""
        with self._lock:
            if max_bytes <= 0:
                self._partitions.pop(tenant, None)
            else:
                self._partitions[tenant] = int(max_bytes)

    def partitions(self) -> dict:
        """{tenant: {"max_bytes", "bytes"}} — the /tenants route's cache
        column."""
        with self._lock:
            return {t: {"max_bytes": m,
                        "bytes": self._tenant_bytes.get(t, 0)}
                    for t, m in self._partitions.items()}

    def admit(self, skey: Any, lo: int, hi: int, data: np.ndarray, *,
              force: bool = False, tenant: "str | None" = None) -> int:
        """Offer file bytes [lo, hi) of *skey* (``data`` holds them) for
        admission. Subject to the admission policy (unless *force*), the
        byte budget (LRU eviction of unpinned entries makes room) and
        disjointness (already-cached subranges are skipped). Returns bytes
        actually admitted."""
        n = hi - lo
        if n <= 0 or self._charge(n) > self.max_bytes:
            return 0
        with self._lock:
            if not force and self.admit_policy == "second_touch" \
                    and not self._touch(self._blocks(skey, lo, hi)):
                return 0
        # gaps only (keeps per-skey entries disjoint); lookup pins the
        # overlapped entries — unpin immediately, we only needed the holes
        _, gaps, pinned = self.lookup(skey, lo, hi, record=False)
        self.unpin(pinned)
        admitted = 0
        for g_lo, g_hi in gaps:
            admitted += self._insert(skey, g_lo, g_hi,
                                     data[g_lo - lo: g_hi - lo],
                                     tenant=tenant)
        if admitted:
            with self._lock:
                self.admitted_bytes += admitted
            self._scope.add("cache_admitted_bytes", admitted)
        return admitted

    def _insert(self, skey: Any, lo: int, hi: int, data: np.ndarray, *,
                tenant: "str | None" = None) -> int:
        n = hi - lo
        charge = self._charge(n)
        buf = self._alloc(n)
        buf[:n] = data[:n]
        # evicted-but-unpinned slabs collected under the lock, demoted to
        # the spill tier and returned to the pool AFTER it releases:
        # spill pwrites block and pool.release takes the slab-pool lock,
        # which ranks BEFORE the cache lock in the canonical hierarchy
        # (scheduler -> engine -> slab pool -> hot cache -> stats/ring) —
        # the same free-outside-the-lock shape unpin() has
        to_free: list[tuple[_Entry, np.ndarray]] = []
        with self._lock:
            # partition enforcement (ISSUE 7): a tenant over its carve-out
            # first evicts its OWN unpinned entries (self-displacement —
            # other tenants' hot sets are untouchable via this path), and
            # admission is refused if its cap still can't fit the entry
            refused = False
            cap = self._partitions.get(tenant) if tenant is not None else None
            if cap is not None:
                if charge > cap:
                    refused = True
                else:
                    while self._tenant_bytes.get(tenant, 0) + charge > cap:
                        victim = next(
                            (e for e in self._lru.values()
                             if e.refs == 0 and e.tenant == tenant), None)
                        if victim is None:
                            break
                        to_free.extend(self._evict_locked(victim))
                    if self._tenant_bytes.get(tenant, 0) + charge > cap:
                        refused = True
            # make room in the shared budget (skip pinned entries: never
            # free a slab with an in-flight reader/put)
            while not refused and self.bytes + charge > self.max_bytes:
                victim = next((e for e in self._lru.values() if e.refs == 0),
                              None)
                if victim is None:
                    break
                to_free.extend(self._evict_locked(victim))
            if refused or self.bytes + charge > self.max_bytes:
                drop = buf  # over partition / everything left pinned
            else:
                # a concurrent admit may have covered part of this gap
                # between our lookup and now; keep entries disjoint
                entries = self._index.setdefault(skey, [])
                i = bisect.bisect_right(entries, lo, key=lambda e: e.lo)
                prev_ok = i == 0 or entries[i - 1].hi <= lo
                next_ok = i == len(entries) or entries[i].lo >= hi
                if not (prev_ok and next_ok):
                    drop = buf
                else:
                    e = _Entry(skey, lo, hi, buf, charge, tenant)
                    entries.insert(i, e)
                    self._lru[id(e)] = e
                    self.bytes += charge
                    if tenant is not None:
                        self._tenant_bytes[tenant] = \
                            self._tenant_bytes.get(tenant, 0) + charge
                    drop = None
        for victim, victim_buf in to_free:
            self._demote_and_free(victim, victim_buf)
        if drop is not None:
            self._free(drop)
            return 0
        return n

    def _evict_locked(self, e: _Entry, *, demote: bool = True
                      ) -> list[tuple[_Entry, np.ndarray]]:
        """Remove *e* from the index/LRU (lock held). Returns the
        (entry, slab) pairs to demote+free — the CALLER runs
        :meth:`_demote_and_free` after releasing the cache lock (spill
        pwrites and pool.release must not run under it; the hierarchy
        orders the slab-pool lock before this one). A still-pinned entry
        returns nothing here; its last unpin frees WITHOUT demoting
        (see unpin). ``demote=False``
        (clear()) drops without spilling."""
        self._lru.pop(id(e), None)
        entries = self._index.get(e.skey)
        if entries is not None:
            i = bisect.bisect_right(entries, e.lo, key=lambda x: x.lo) - 1
            if 0 <= i < len(entries) and entries[i] is e:
                entries.pop(i)
            if not entries:
                del self._index[e.skey]
        self.bytes -= e.charge
        if e.tenant is not None:
            left = self._tenant_bytes.get(e.tenant, 0) - e.charge
            if left > 0:
                self._tenant_bytes[e.tenant] = left
            else:
                self._tenant_bytes.pop(e.tenant, None)
        self.evictions += 1
        self.evicted_bytes += e.nbytes
        self._scope.add("cache_evictions")
        self._scope.add("cache_evicted_bytes", e.nbytes)
        e.demote = demote and self.spill is not None
        if e.refs == 0:
            buf, e.buf = e.buf, None  # type: ignore[assignment]
            return [(e, buf)]
        e.dead = True  # last unpin frees (never demotes: see unpin)
        return []

    def invalidate(self, skey: Any) -> int:
        """Drop every entry of *skey* — and of any DERIVED tuple key that
        embeds it (the decoded-output cache keys frames as
        ``("jpegdec", path, lo, hi, fp)``: pixels decoded from the old
        bytes must go too) — WITHOUT demoting: the backing bytes changed
        (a write landed on the file), so neither tier may keep serving
        them. Returns entries dropped. Pinned entries leave the index
        immediately; their slabs free on the last unpin."""
        to_free: list[tuple[_Entry, np.ndarray]] = []
        dropped = 0
        with self._lock:
            keys = [k for k in self._index
                    if k == skey or (isinstance(k, tuple) and skey in k)]
            for k in keys:
                for e in list(self._index.get(k, ())):
                    dropped += 1
                    to_free.extend(self._evict_locked(e, demote=False))
        for _e, buf in to_free:
            self._free(buf)
        if self.spill is not None:
            self.spill.invalidate(skey)
        return dropped

    def clear(self) -> None:
        """Drop every entry AND the touch ledger (a cleared cache forgets
        its observations too — the cold/warm bench pair depends on this).
        Pinned entries leave the index immediately (no new lookup can hit
        them) but their slabs free on the last unpin."""
        to_free: list[tuple[_Entry, np.ndarray]] = []
        with self._lock:
            for e in list(self._lru.values()):
                to_free.extend(self._evict_locked(e, demote=False))
            self._touched.clear()
        for _e, buf in to_free:
            self._free(buf)

    # -- readahead accounting ----------------------------------------------
    def note_readahead(self, nbytes: int) -> None:
        with self._lock:
            self.readahead_bytes += nbytes
        self._scope.add("cache_readahead_bytes", nbytes)

    def note_yield(self) -> None:
        with self._lock:
            self.readahead_yields += 1
        self._scope.add("cache_readahead_yields")

    def note_error(self) -> None:
        """A readahead tick died (window_fn raised, source vanished): the
        thread keeps running, but 'readahead silently broken' must be
        distinguishable from 'nothing to warm' (readahead_bytes 0 alone
        cannot tell the two apart)."""
        with self._lock:
            self.readahead_errors += 1
        self._scope.add("cache_readahead_errors")

    # -- introspection ------------------------------------------------------
    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._lru)

    def manifest(self, *, max_entries: int = 4096) -> list[list]:
        """Resident path-keyed ranges, newest-first, as JSON-stable
        ``[path, lo, hi]`` triples — the warm-state hints a StepToken can
        carry across a restart (ISSUE 14, strom/ckpt/jobstate.py).
        Derived tuple keys (decoded frames) are skipped: they are decode
        OUTPUT, not re-readable source ranges."""
        out: list[list] = []
        with self._lock:
            for e in reversed(self._lru.values()):
                if len(out) >= max_entries:
                    break
                if isinstance(e.skey, str):
                    out.append([e.skey, e.lo, e.hi])
        return out

    def stats(self) -> dict:
        """The ``cache`` section of ``StromContext.stats()`` — full metric
        names as keys so the sections exposition types the counters via the
        global registry mirror (PR 3 exposition rules)."""
        with self._lock:
            served = self.hit_bytes + self.miss_bytes
            ratio = self.hit_bytes / served if served else 0.0
            out = {
                "cache_budget_bytes": self.max_bytes,
                "cache_bytes": self.bytes,
                "cache_entries": len(self._lru),
                "cache_hit_bytes": self.hit_bytes,
                "cache_miss_bytes": self.miss_bytes,
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "cache_admitted_bytes": self.admitted_bytes,
                "cache_evictions": self.evictions,
                "cache_evicted_bytes": self.evicted_bytes,
                "cache_readahead_bytes": self.readahead_bytes,
                "cache_readahead_yields": self.readahead_yields,
                "cache_readahead_errors": self.readahead_errors,
                "cache_hit_ratio": round(ratio, 4),
            }
        self._scope.set_gauge("cache_hit_ratio", out["cache_hit_ratio"])
        return out


class Readahead:
    """Epoch-aware readahead: warm the upcoming-batch window into the cache.

    *window_fn* returns an iterable of ``(source, segments, base_offset)``
    read requests describing the next ``readahead_window_batches`` batches
    (pipelines build it from ``EpochShuffleSampler.peek``, which crosses the
    epoch boundary — the next epoch's head warms while this one drains).
    Each tick re-pulls the window, so the thread tracks the sampler as the
    prefetcher advances it; fully-warm windows back off to a longer sleep.

    All warming goes through ``StromContext.warm``, which serves only
    MISSES, force-admits what it reads, and yields to demand reads between
    engine-budget-sized slices — this thread can therefore never turn a
    demand gather into a queue-depth casualty (asserted in
    tests/test_hotcache.py).
    """

    def __init__(self, ctx, window_fn: Callable[..., Iterable[tuple]], *,
                 interval_s: float = 0.02, tenant: "str | None" = None,
                 window_batches: int = 0):
        import inspect

        self._ctx = ctx
        self._window_fn = window_fn
        self._interval = interval_s
        # live window size (ISSUE 19 satellite): the autotuner's
        # readahead_window_batches knob writes here and the next tick
        # builds that many batches — window fns taking an argument receive
        # it, zero-arg fns (fixed windows) keep their own count
        self.window_batches = int(window_batches)
        try:
            self._fn_takes_n = bool(
                inspect.signature(window_fn).parameters)
        except (TypeError, ValueError):  # builtins/partials w/o signature
            self._fn_takes_n = False
        # the pipeline this thread warms FOR: admitted entries charge that
        # tenant's cache partition (the ENGINE reads still ride the shared
        # background "readahead" tenant — ownership and scheduling differ)
        self._tenant = tenant
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="strom-readahead")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            cache = getattr(self._ctx, "hot_cache", None)
            if cache is None or not cache.enabled:
                # gate BEFORE window_fn: building the window (sampler.peek
                # + per-batch extents) is exactly the CPU the disabled
                # phases must not pay on a 1-core box
                self._stop.wait(self._interval * 5)
                continue
            warmed = 0
            try:
                window = (self._window_fn(self.window_batches)
                          if self._fn_takes_n else self._window_fn())
                for source, segments, base_offset in window:
                    if self._stop.is_set():
                        break
                    warmed += self._ctx.warm(source, segments, base_offset,
                                             tenant=self._tenant)
            except Exception:
                # advisory path: a racing pipeline/context close (or a
                # transient engine error) must neither kill the thread nor
                # spew into the consumer's stderr — but it must be COUNTED,
                # or a broken window_fn reads as "nothing to warm"
                cache = getattr(self._ctx, "hot_cache", None)
                if cache is not None:
                    cache.note_error()
            self._stop.wait(self._interval if warmed else self._interval * 5)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
