"""NVMe spill tier for the hot-set cache (ISSUE 13 tentpole, front 3).

The hot cache (strom/delivery/hotcache.py) is a single RAM tier: an entry
evicted under byte pressure vanishes, and the next request for those bytes
pays a full source gather — image-member reads, stripe decode, the lot.
This module gives eviction a second landing spot: evicted-but-warm extents
DEMOTE to a dedicated spill file on local NVMe instead of vanishing, and
the delivery layer's cache consult serves them from there — a read of the
spill file's pages, never the source engine. The cache becomes a real
RAM → NVMe → source hierarchy; decoded-output entries (`("jpegdec", ...)`
keys, ISSUE 12) demote like any other entry, which makes the spill file a
second *decoded* tier exactly as ROADMAP item 3's residual asked.

Design points:

- **Same keys, same interval arithmetic.** Entries key on the hot cache's
  skey (physical path / decoded-frame tuple) with [lo, hi) byte ranges and
  are served by interval intersection, so a differently-split request
  still hits. Per-skey entries stay disjoint (a re-evicted range that
  already spilled is skipped — source bytes are immutable, the copy on
  NVMe is still right).
- **Refcounted, two-phase I/O.** File I/O never runs under the tier lock
  (the lock-order discipline, tools/stromlint): `offer` allocates file
  space under the lock, pwrites outside it, then publishes the entry;
  `lookup` pins entries under the lock and the caller preads outside it
  (`read_into`), unpinning after. Eviction skips pinned entries; a dead
  pinned entry's file slot recycles on the last unpin.
- **Size-class allocator.** Spill-file space is allocated at
  :func:`~strom.delivery.buffers.size_class` granularity with per-class
  free lists, so a churning cache recycles file slots instead of growing
  the file without bound; `max_bytes` caps the allocated footprint and
  makes room by dropping the oldest unpinned entries (which at THIS tier
  really do vanish — below NVMe there is only the source).
- **Per-tenant partition accounting** (ISSUE 7 parity): entries carry the
  evicting tenant; `set_partition` caps a tenant's spill bytes, and an
  over-cap tenant drops its OWN oldest spill entries first — one tenant's
  spilled working set can never displace another's.

Counters (``spill_*``, single-sourced in :data:`SPILL_FIELDS` for the
bench/compare_rounds contract): served/spilled bytes, hit ratio, occupancy.
"""

from __future__ import annotations

import bisect
import contextlib
import os
from collections import OrderedDict
from typing import Any

import numpy as np

from strom.delivery.buffers import size_class
from strom.utils.locks import make_lock

# bench-JSON columns the spill epoch phase emits (cli.py bench_checkpoint's
# spill pass), single-sourced so the driver's copy loop (bench.py) and the
# compare_rounds "write path" section cannot drift from the producer — the
# same contract CACHE_BENCH_FIELDS / CKPT_FIELDS enforce.
SPILL_FIELDS = (
    "spill_hit_bytes",
    "spill_hits",
    "spill_spilled_bytes",
    "spill_entries",
    "spill_bytes",
    "spill_hit_ratio",
    "spill_cache_miss_bytes",
    "spill_promote_bytes",
    "spill_engine_ops",
    "spill_fallback_ops",
)


class _SpillEntry:
    """One spilled range: spill_file[off : off + stored] holds bytes
    [lo, hi) of *skey* — raw (``codec`` None, ``stored`` == hi-lo) or
    compressed (``codec`` names the wire codec, ``stored`` is the on-disk
    payload length; ISSUE 19). ``cls`` is the size-class-rounded file
    allocation the occupancy budget is billed; ``refs`` pins against
    eviction (the caller is mid-pread); ``dead`` marks
    evicted-while-pinned (slot recycles on last unpin)."""

    __slots__ = ("skey", "lo", "hi", "off", "cls", "refs", "dead", "tenant",
                 "codec", "stored")

    def __init__(self, skey: Any, lo: int, hi: int, off: int, cls: int,
                 tenant: "str | None", *, codec: "str | None" = None,
                 stored: "int | None" = None):
        self.skey = skey
        self.lo = lo
        self.hi = hi
        self.off = off
        self.cls = cls
        self.refs = 0
        self.dead = False
        self.tenant = tenant
        self.codec = codec
        self.stored = (hi - lo) if stored is None else stored

    @property
    def nbytes(self) -> int:
        return self.hi - self.lo


class SpillTier:
    """Byte-budgeted spill file with per-skey disjoint ranges, refcounted
    entries and per-tenant accounting. Thread-safe; all file I/O runs
    outside the tier lock (see module docstring)."""

    def __init__(self, path: str, max_bytes: int, *, scope=None, io=None,
                 compress: bool = False):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be positive")
        from strom.utils.stats import global_stats

        self.path = path
        self.max_bytes = max_bytes
        # transparent demote compression (ISSUE 19 front 3): the probed
        # LZ4-class codec, engaged per entry only when it PAYS (raw
        # otherwise — strom/utils/codec.py); None = the pre-compression
        # tier byte for byte
        self._codec = None
        if compress:
            from strom.utils.codec import default_codec

            self._codec = default_codec()
        self._scope = scope if scope is not None else global_stats
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        # engine I/O router (ISSUE 14 satellite): an object with
        # write(data_u8, off) -> bool / read(dest_u8, off, n) -> bool that
        # routes spill bytes through the context's engine path (O_DIRECT,
        # background-class scheduler grants) when it is SAFE to enqueue,
        # returning False to request the buffered-fd fallback below
        # (strom.delivery.core._SpillEngineIo). None = always buffered fd
        # (the pre-ISSUE-14 behavior; the spill_engine_io=False A/B arm).
        self._io = io
        self._lock = make_lock("cache.spill")
        self._index: dict[Any, list[_SpillEntry]] = {}
        self._lru: "OrderedDict[int, _SpillEntry]" = OrderedDict()
        self._free: dict[int, list[int]] = {}   # class -> file offsets
        self._next_off = 0
        self.bytes = 0                          # allocated (class-rounded)
        self._tenant_bytes: dict[str, int] = {}
        self._partitions: dict[str, int] = {}
        self._closed = False
        # tallies (authoritative for stats(); mirrored into the scope)
        self.hit_bytes = 0
        self.hits = 0
        self.miss_bytes = 0
        self.misses = 0
        self.spilled_bytes = 0
        self.spills = 0
        self.evictions = 0
        # readahead-driven spill→RAM promotions (ISSUE 14 satellite,
        # ROADMAP item 2 residual c) — counted by the warm consult
        self.promote_bytes = 0
        # which route spill bytes took (engine vs buffered-fd fallback)
        self.engine_ops = 0
        self.fallback_ops = 0
        # compression accounting (COMP_FIELDS contract): raw bytes entering
        # the codec vs stored bytes leaving it, and served decompressions
        self.comp_bytes_in = 0
        self.comp_bytes_out = 0
        self.decomp_bytes = 0

    # -- allocator (lock held) ----------------------------------------------
    def _alloc_locked(self, n: int, tenant: "str | None") -> "int | None":
        """A file offset for an n-byte entry, or None when no room can be
        made. Evicts oldest unpinned entries (the tenant's own first when
        it is over its partition) to fit the budget."""
        cls = size_class(n)
        cap = self._partitions.get(tenant) if tenant is not None else None
        if cap is not None:
            if cls > cap:
                return None
            while self._tenant_bytes.get(tenant, 0) + cls > cap:
                victim = next((e for e in self._lru.values()
                               if e.refs == 0 and e.tenant == tenant), None)
                if victim is None:
                    return None
                self._evict_locked(victim)
        while self.bytes + cls > self.max_bytes:
            victim = next((e for e in self._lru.values() if e.refs == 0),
                          None)
            if victim is None:
                return None
            self._evict_locked(victim)
        bucket = self._free.get(cls)
        if bucket:
            off = bucket.pop()
        else:
            off = self._next_off
            self._next_off += cls
        self.bytes += cls
        if tenant is not None:
            self._tenant_bytes[tenant] = \
                self._tenant_bytes.get(tenant, 0) + cls
        return off

    def _release_slot_locked(self, e: _SpillEntry) -> None:
        self._free.setdefault(e.cls, []).append(e.off)
        self.bytes -= e.cls
        if e.tenant is not None:
            left = self._tenant_bytes.get(e.tenant, 0) - e.cls
            if left > 0:
                self._tenant_bytes[e.tenant] = left
            else:
                self._tenant_bytes.pop(e.tenant, None)

    def _evict_locked(self, e: _SpillEntry) -> None:
        """Drop *e* from the tier (lock held). Below this tier there is
        only the source — the bytes really vanish. Pinned entries recycle
        their file slot on the last unpin."""
        self._lru.pop(id(e), None)
        entries = self._index.get(e.skey)
        if entries is not None:
            i = bisect.bisect_right(entries, e.lo, key=lambda x: x.lo) - 1
            if 0 <= i < len(entries) and entries[i] is e:
                entries.pop(i)
            if not entries:
                del self._index[e.skey]
        self.evictions += 1
        if e.refs == 0:
            self._release_slot_locked(e)
        else:
            e.dead = True  # last unpin releases the slot

    # -- demote (HotCache eviction hook) ------------------------------------
    def offer(self, skey: Any, lo: int, hi: int, data: np.ndarray, *,
              tenant: "str | None" = None) -> int:
        """Spill bytes [lo, hi) of *skey* (``data`` holds them). Skips
        subranges already spilled (disjointness; source bytes are
        immutable). Returns bytes newly spilled."""
        n = hi - lo
        if n <= 0 or size_class(n) > self.max_bytes or self._closed:
            return 0
        d8 = np.ascontiguousarray(data).reshape(-1).view(np.uint8)
        written = 0
        # gap scan under the lock; codec pass OUTSIDE it (CPU never runs
        # under the tier lock); allocation under it; pwrite outside;
        # publish under it again — the allocated slot is private until
        # published, so nothing can read half-written bytes
        with self._lock:
            if self._closed:
                return 0
            entries = self._index.get(skey, ())
            gaps: list[tuple[int, int]] = []
            pos = lo
            i = max(bisect.bisect_right(entries, lo, key=lambda e: e.lo) - 1,
                    0) if entries else 0
            while pos < hi and i < len(entries):
                e = entries[i]
                if e.hi <= pos:
                    i += 1
                    continue
                if e.lo >= hi:
                    break
                if e.lo > pos:
                    gaps.append((pos, e.lo))
                pos = max(pos, e.hi)
                i += 1
            if pos < hi:
                gaps.append((pos, hi))
        codec = self._codec
        # (g_lo, g_hi, payload_u8, codec_name): payload is the raw slice
        # view when compression is off or didn't pay — no copy either way
        prepped: list = []
        for g_lo, g_hi in gaps:
            seg = d8[g_lo - lo: g_hi - lo]
            payload, cname = seg, None
            if codec is not None:
                comp = codec.compress(seg.tobytes())
                if len(comp) < len(seg):
                    payload = np.frombuffer(comp, np.uint8)
                    cname = codec.name
            prepped.append((g_lo, g_hi, payload, cname))
        staged: list = []   # + (off, cls)
        with self._lock:
            if self._closed:
                return 0
            for g_lo, g_hi, payload, cname in prepped:
                off = self._alloc_locked(len(payload), tenant)
                if off is None:
                    continue
                staged.append((g_lo, g_hi, payload, cname, off,
                               size_class(len(payload))))
        for _g_lo, _g_hi, payload, _cname, off, _cls in staged:
            self._pwrite(payload, off)
        if not staged:
            return 0
        comp_in = comp_out = 0
        with self._lock:
            if self._closed:
                return 0
            entries = self._index.setdefault(skey, [])
            for g_lo, g_hi, payload, cname, off, cls in staged:
                e = _SpillEntry(skey, g_lo, g_hi, off, cls, tenant,
                                codec=cname, stored=len(payload))
                i = bisect.bisect_right(entries, g_lo, key=lambda x: x.lo)
                # a concurrent offer may have covered the gap meanwhile;
                # keep entries disjoint (release the orphaned slot)
                prev_ok = i == 0 or entries[i - 1].hi <= g_lo
                next_ok = i == len(entries) or entries[i].lo >= g_hi
                if not (prev_ok and next_ok):
                    self._release_slot_locked(e)
                    continue
                entries.insert(i, e)
                self._lru[id(e)] = e
                written += g_hi - g_lo
                if cname is not None:
                    comp_in += g_hi - g_lo
                    comp_out += len(payload)
            self.spilled_bytes += written
            self.spills += 1 if written else 0
            self.comp_bytes_in += comp_in
            self.comp_bytes_out += comp_out
            ratio = (round(self.comp_bytes_in / self.comp_bytes_out, 4)
                     if self.comp_bytes_out else 0.0)
        if written:
            self._scope.add("spill_spilled_bytes", written)
        if comp_in:
            self._scope.add("spill_comp_bytes_in", comp_in)
            self._scope.add("spill_comp_bytes_out", comp_out)
            self._scope.set_gauge("spill_comp_ratio", ratio)
        return written

    # -- serve ---------------------------------------------------------------
    def lookup(self, skey: Any, lo: int, hi: int, *, record: bool = True
               ) -> tuple[list[tuple[int, int, _SpillEntry]],
                          list[tuple[int, int]]]:
        """Split [lo, hi) of *skey* into spilled and missing ranges.
        Returned entries are PINNED — the caller preads them via
        :meth:`read_into` and MUST :meth:`unpin` afterwards."""
        hits: list[tuple[int, int, _SpillEntry]] = []
        misses: list[tuple[int, int]] = []
        with self._lock:
            entries = self._index.get(skey, ())
            pos = lo
            i = max(bisect.bisect_right(entries, lo, key=lambda e: e.lo) - 1,
                    0) if entries else 0
            while pos < hi and i < len(entries):
                e = entries[i]
                if e.hi <= pos:
                    i += 1
                    continue
                if e.lo >= hi:
                    break
                if e.lo > pos:
                    misses.append((pos, e.lo))
                    pos = e.lo
                s, t = max(pos, e.lo), min(hi, e.hi)
                e.refs += 1
                self._lru.move_to_end(id(e))
                hits.append((s, t, e))
                pos = t
                i += 1
            if pos < hi:
                misses.append((pos, hi))
            if record:
                self.hit_bytes += sum(t - s for s, t, _ in hits)
                self.hits += len(hits)
                self.miss_bytes += sum(t - s for s, t in misses)
                self.misses += len(misses)
        if record and hits:
            self._scope.add("spill_hits", len(hits))
            self._scope.add("spill_hit_bytes",
                            sum(t - s for s, t, _ in hits))
        return hits, misses

    def read_into(self, e: _SpillEntry, s: int, t: int,
                  dest: np.ndarray) -> int:
        """Read spill bytes [s, t) of *e*'s range straight into *dest*
        (writable uint8 view, len >= t-s). Raw entries pread with no
        intermediate copy (engine-routed when a router is attached and can
        enqueue safely, else the buffered fd); compressed entries read
        their stored payload and decompress through it (counted
        ``spill_decomp_bytes``). The entry must be pinned (a
        :meth:`lookup` hit)."""
        n = t - s
        if e.codec is None:
            return self._read_raw(dest, e.off + (s - e.lo), n)
        from strom.utils.codec import get_codec

        comp = np.empty(e.stored, np.uint8)
        self._read_raw(comp, e.off, e.stored)
        codec = get_codec(e.codec)
        if codec is None:  # pragma: no cover - entry codec is process-local
            raise RuntimeError(f"spill entry codec {e.codec!r} unavailable")
        raw = codec.decompress(comp)
        dest[:n] = np.frombuffer(raw, np.uint8, count=n, offset=s - e.lo)
        with self._lock:
            self.decomp_bytes += n
        self._scope.add("spill_decomp_bytes", n)
        return n

    def _read_raw(self, dest: np.ndarray, off: int, n: int) -> int:
        io = self._io
        if io is not None and io.read(dest[:n], off, n):
            with self._lock:
                self.engine_ops += 1
            return n
        with self._lock:
            self.fallback_ops += 1
        return os.preadv(self._fd, [memoryview(dest)[:n]], off)

    def file_range(self, e: _SpillEntry, s: int, t: int
                   ) -> "tuple[int, int, int] | None":
        """``(fd, file_offset, length)`` for bytes [s, t) of *e*'s range —
        the sendfile(2) coordinates the zero-copy peer exporter uses to
        ship spill-resident bytes without a userspace read, or None for a
        COMPRESSED entry (its file bytes aren't the logical bytes; the
        caller falls back to :meth:`read_into`, which decompresses). The
        entry must be pinned (a :meth:`lookup` hit) and stay pinned until
        the send completes; the fd is owned by this tier, do not close
        it."""
        if e.codec is not None:
            return None
        return self._fd, e.off + (s - e.lo), t - s

    def _pwrite(self, data: np.ndarray, off: int) -> None:
        """Spill-file write: engine-routed when safe, buffered fd
        otherwise. Never called under the tier lock (two-phase
        allocate/publish — see module docstring)."""
        io = self._io
        if io is not None and io.write(data, off):
            with self._lock:
                self.engine_ops += 1
            return
        with self._lock:
            self.fallback_ops += 1
        # numpy slices speak the buffer protocol: no bytes() bounce
        os.pwrite(self._fd, data.data, off)

    def note_promote(self, nbytes: int) -> None:
        """Count a readahead-driven spill→RAM promotion (the warm consult
        in strom/delivery/core.py re-admits upcoming-window spill hits)."""
        if nbytes <= 0:
            return
        with self._lock:
            self.promote_bytes += nbytes
        self._scope.add("spill_promote_bytes", nbytes)

    def unpin(self, entries) -> None:
        with self._lock:
            for e in entries:
                e.refs -= 1
                if e.dead and e.refs == 0:
                    self._release_slot_locked(e)
                    e.dead = False

    # -- partitions / lifecycle ----------------------------------------------
    def set_io(self, io) -> None:
        """Attach the engine I/O router (see ``__init__``; the context
        attaches it after construction so registration sees the created
        spill file)."""
        self._io = io

    def set_partition(self, tenant: str, max_bytes: int) -> None:
        """Cap *tenant*'s spill bytes (0 removes the partition)."""
        with self._lock:
            if max_bytes <= 0:
                self._partitions.pop(tenant, None)
            else:
                self._partitions[tenant] = int(max_bytes)

    def partitions(self) -> dict:
        with self._lock:
            return {t: {"max_bytes": m,
                        "bytes": self._tenant_bytes.get(t, 0)}
                    for t, m in self._partitions.items()}

    def invalidate(self, skey: Any) -> int:
        """Drop every spilled range of *skey* — and of any derived tuple
        key embedding it (decoded-frame keys carry the shard path inside a
        tuple) — the source bytes changed."""
        dropped = 0
        with self._lock:
            keys = [k for k in self._index
                    if k == skey or (isinstance(k, tuple) and skey in k)]
            for k in keys:
                for e in list(self._index.get(k, ())):
                    dropped += 1
                    self._evict_locked(e)
        return dropped

    def clear(self) -> None:
        with self._lock:
            for e in list(self._lru.values()):
                self._evict_locked(e)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        io, self._io = self._io, None
        if io is not None:
            with contextlib.suppress(Exception):
                io.close()
        os.close(self._fd)
        with contextlib.suppress(OSError):
            os.unlink(self.path)

    # -- introspection -------------------------------------------------------
    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._lru)

    def manifest(self, *, max_entries: int = 4096) -> list[list]:
        """Spilled path-keyed ranges, newest-first, as JSON-stable
        ``[path, lo, hi]`` triples — warm-state hints for a StepToken
        (ISSUE 14); tuple (decoded-frame) keys are skipped like the hot
        cache's manifest."""
        out: list[list] = []
        with self._lock:
            for e in reversed(self._lru.values()):
                if len(out) >= max_entries:
                    break
                if isinstance(e.skey, str):
                    out.append([e.skey, e.lo, e.hi])
        return out

    def stats(self) -> dict:
        """The ``spill`` section of ``StromContext.stats()`` — full metric
        names as keys (the PR 3 exposition rules)."""
        with self._lock:
            served = self.hit_bytes + self.miss_bytes
            return {
                "spill_budget_bytes": self.max_bytes,
                "spill_bytes": self.bytes,
                "spill_entries": len(self._lru),
                "spill_hit_bytes": self.hit_bytes,
                "spill_hits": self.hits,
                "spill_miss_bytes": self.miss_bytes,
                "spill_spilled_bytes": self.spilled_bytes,
                "spill_evictions": self.evictions,
                "spill_promote_bytes": self.promote_bytes,
                "spill_engine_ops": self.engine_ops,
                "spill_fallback_ops": self.fallback_ops,
                "spill_comp_bytes_in": self.comp_bytes_in,
                "spill_comp_bytes_out": self.comp_bytes_out,
                "spill_decomp_bytes": self.decomp_bytes,
                "spill_comp_ratio":
                    round(self.comp_bytes_in / self.comp_bytes_out, 4)
                    if self.comp_bytes_out else 0.0,
                "spill_hit_ratio":
                    round(self.hit_bytes / served, 4) if served else 0.0,
            }
