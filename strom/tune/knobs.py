"""Knob surfaces the autotuner can move at runtime.

A :class:`Knob` is a named scalar with live get/set accessors and bounds.
The tuner only ever moves values through ``set`` — every surface here is
one that the owning component re-reads on its next decision (scheduler
slice size per grant, cache budget per admit, prefetch depth per fill), so
a move takes effect without restarting anything and a revert is exact.
"""

from __future__ import annotations

import dataclasses
from typing import Callable


@dataclasses.dataclass
class Knob:
    """One tunable scalar: live accessors, bounds, and a search step.

    ``step`` is the tuner's initial move size; ``quantize`` snaps proposed
    values onto the surface's legal grid (int depths, 4 KiB-aligned byte
    budgets) so a knob can never be set to a value its owner would reject.
    """

    name: str
    get: Callable[[], float]
    set: Callable[[float], None]
    lo: float
    hi: float
    step: float
    quantize: Callable[[float], float] | None = None
    # smallest meaningful move — the tuner's step-halving floors here so
    # refinement can never shrink a proposal below the quantization grid
    # (where quantize would collapse it to a no-op and pin the knob)
    min_step: float | None = None

    @property
    def step_floor(self) -> float:
        return self.min_step if self.min_step is not None else self.step / 8

    def clamp(self, value: float) -> float:
        v = min(max(value, self.lo), self.hi)
        if self.quantize is not None:
            v = self.quantize(v)
        return min(max(v, self.lo), self.hi)


def _quant_int(v: float) -> float:
    return float(int(round(v)))


def _quant_4k(v: float) -> float:
    return float(max(int(v) // 4096, 1) * 4096)


def prefetcher_knob(pf, *, max_depth: int | None = None) -> Knob:
    """Depth knob over a live :class:`strom.delivery.prefetch.Prefetcher`."""
    hi = float(max_depth if max_depth is not None
               else getattr(pf, "_max_depth", 16))
    return Knob(name="prefetch_depth",
                get=lambda: float(pf.depth),
                set=lambda v: pf.set_depth(int(v)),
                lo=float(getattr(pf, "_min_depth", 1)), hi=hi,
                step=1.0, quantize=_quant_int, min_step=1.0)


def standard_knobs(ctx) -> list[Knob]:
    """The knobs a :class:`StromContext` exposes, built from whichever
    surfaces this context actually has (scheduler off → no slice knob,
    cache off → no budget knob). Pipelines append their own (prefetch
    depth via :func:`prefetcher_knob`)."""
    knobs: list[Knob] = []
    sched = getattr(ctx, "scheduler", None)
    if sched is not None:
        base = float(sched._slice_bytes() or ctx.config.queue_depth
                     * ctx.config.block_size)

        def _set_slice(v: float, _s=sched) -> None:
            _s.slice_bytes_override = int(v)

        knobs.append(Knob(
            name="sched_slice_bytes",
            get=lambda _s=sched: float(_s._slice_bytes()),
            set=_set_slice,
            # an order of magnitude either side of the configured/auto
            # slice: enough room to matter, bounded so one runaway arm
            # can't turn slicing off entirely
            lo=max(base / 8, 256 * 1024.0), hi=base * 8,
            step=max(base / 4, 256 * 1024.0), quantize=_quant_4k,
            min_step=4096.0))
    cache = getattr(ctx, "hot_cache", None)
    if cache is not None:
        base = float(cache.max_bytes)

        def _set_budget(v: float, _c=cache) -> None:
            _c.max_bytes = int(v)

        knobs.append(Knob(
            name="cache_budget_bytes",
            get=lambda _c=cache: float(_c.max_bytes),
            set=_set_budget,
            # never below half the configured budget (shrinking a warm
            # cache evicts; the tuner explores, it must not thrash) and at
            # most 2x (host memory is someone else's budget too)
            lo=base / 2, hi=base * 2,
            step=base / 8, quantize=_quant_4k, min_step=4096.0))
    # pipeline surfaces registered via ctx.register_tunable (ISSUE 19
    # satellite): present only after a pipeline is built on this context
    tunables = getattr(ctx, "_tunables", {})
    pool = tunables.get("decode_pool")
    if pool is not None and hasattr(pool, "run_target_us"):
        def _set_target(v: float, _p=pool) -> None:
            _p.run_target_us = float(v)

        knobs.append(Knob(
            name="decode_run_target_us",
            get=lambda _p=pool: float(_p.run_target_us),
            set=_set_target,
            # half a task-overhead-bound run up to 5x the measured sweet
            # spot: enough room to trade tail granularity vs dispatch
            # overhead, never so low that fusing degenerates to per-sample
            lo=500.0, hi=20000.0, step=1000.0, min_step=100.0))
    tier = tunables.get("peer_tier")
    if tier is not None and hasattr(tier, "batch_max_extents"):
        def _set_batch(v: float, _t=tier) -> None:
            _t.batch_max_extents = int(v)

        knobs.append(Knob(
            name="dist_batch_max_extents",
            get=lambda _t=tier: float(_t.batch_max_extents),
            set=_set_batch,
            # 1 keeps the batched wire on (0 = unbatched is the A/B arm's
            # call, not the tuner's); 512 bounds the per-chunk frame the
            # server must buffer before its first response byte
            lo=1.0, hi=512.0, step=16.0, quantize=_quant_int,
            min_step=1.0))

        def _set_pool(v: float, _t=tier) -> None:
            _t.conn_pool_size = int(v)

        knobs.append(Knob(
            name="dist_conn_pool_size",
            get=lambda _t=tier: float(_t.conn_pool_size),
            set=_set_pool,
            # at least one pooled conn per peer; 16 bounds idle-socket FD
            # cost across a wide fleet
            lo=1.0, hi=16.0, step=1.0, quantize=_quant_int, min_step=1.0))
    ra = tunables.get("readahead")
    if ra is not None and getattr(ra, "window_batches", 0) > 0:
        base = float(ra.window_batches)

        def _set_window(v: float, _r=ra) -> None:
            _r.window_batches = int(v)

        knobs.append(Knob(
            name="readahead_window_batches",
            get=lambda _r=ra: float(_r.window_batches),
            set=_set_window,
            # 1 keeps the warmer alive (0 = off is the operator's call,
            # not the tuner's); 4x the configured window bounds the cache
            # churn one runaway arm can cause
            lo=1.0, hi=max(base * 4, 16.0),
            step=1.0, quantize=_quant_int, min_step=1.0))
    return knobs
