"""The closed-loop controller: coordinate descent with guarded revert.

One :class:`Autotuner` owns a set of :class:`~strom.tune.knobs.Knob`
surfaces and a ``metrics_fn`` returning the live objective (higher is
better — goodput_pct for a training context, items/s for a bench arm) plus
the SLO-burn flag. ``step()`` advances a two-beat state machine:

- **propose**: pick the next knob round-robin and move it one step in its
  remembered direction (flipping at a bound), leaving the move in flight;
- **evaluate** (the next call, one settle window later): accept the move
  only when the objective improved by at least ``epsilon`` — anything
  else is reverted exactly, and a drop past ``guard_frac`` additionally
  halves the knob's step (a hard regression means the step was too big,
  not just the wrong direction).

Safety invariants (tested on a fake clock in tests/test_tune.py):

- a trial is never left applied unless it measured better — the tuned
  state can only drift upward from the hand-tuned start, which is what
  the bench gate's ``tuned_vs_hand >= 1.0`` contract rides on;
- while ``slo_burning`` is reported the tuner reverts any in-flight trial
  and proposes nothing (``tune_holds`` counts these) — it never
  experiments on a tenant that is already missing its target.

The driver thread (``start()``) is optional; tests and the bench arms
call ``step()`` directly.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Callable, Sequence

from strom.tune.knobs import Knob
from strom.utils.locks import make_lock

# single-sourced numeric leaves of stats()["tune"] — the /tune route, the
# compare_rounds autotune section, and strom_top's tuner row all read these
# names (tools/lint_stats_names.py walks this tuple)
TUNE_FIELDS = (
    "tune_active",
    "tune_moves",
    "tune_reverts",
    "tune_holds",
    "tune_trials",
    "tune_objective",
    "tune_baseline_objective",
    "tune_best_objective",
    "tuned_vs_baseline",
)

# bench-JSON columns the tune arm (cli.py bench_tune) and the nvme arm's
# SQPOLL A/B emit — the compare_rounds "kernel bypass & autotune" section
# and the bench_sentinel gates (tuned_vs_hand up, sqpoll syscalls/GB down)
# read these names; same single-sourcing contract as CACHE_BENCH_FIELDS
TUNE_BENCH_FIELDS = (
    "hand_items_per_s",
    "tuned_items_per_s",
    "tuned_vs_hand",
    "tune_moves",
    "tune_reverts",
    "tune_holds",
    "engine_fixed_buf_ratio",
    "engine_unregistered_reads",
    "plain_submit_syscalls_per_gb",
    "sqpoll_submit_syscalls_per_gb",
    "sqpoll_active",
)


def stall_weighted_metrics(base_fn: Callable[[], dict], *,
                           wait_weight: float = 0.5) -> Callable[[], dict]:
    """Wrap a ``metrics_fn`` so the objective also PENALIZES ingest-wait
    share, not just rewards goodput (ISSUE 19 satellite).

    The base fn's stall-attribution rates (``stall_<bucket>_us_per_s``,
    published by ``StromContext._tune_metrics``) give the split of step
    wall time between waiting on ingest and computing. The wrapped
    objective is ``objective * (1 - wait_weight * share)`` with
    ``share = ingest_wait / (ingest_wait + compute)`` — two knob settings
    with equal goodput now rank by how much accelerator time each one
    leaves stalled, steering the search toward settings with headroom
    instead of ones barely keeping up. Without the rates (no step windows
    yet, history off) the metrics pass through untouched, so the wrapper
    is safe as a default."""
    w = min(max(float(wait_weight), 0.0), 1.0)

    def metrics() -> dict:
        m = dict(base_fn())
        wait = m.get("stall_ingest_wait_us_per_s")
        comp = m.get("stall_compute_us_per_s")
        if wait is not None and comp is not None and (wait + comp) > 0:
            share = min(max(wait / (wait + comp), 0.0), 1.0)
            m["ingest_wait_share"] = round(share, 4)
            m["objective"] = float(m.get("objective", 0.0)) \
                * (1.0 - w * share)
        return m

    return metrics


@dataclasses.dataclass
class Profile:
    """A persisted knob assignment: what the tuner converged to for one
    workload (bench arm), reloadable so the next run starts there."""

    name: str
    knobs: dict[str, float]
    objective: float = 0.0

    def save(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"name": self.name, "knobs": self.knobs,
                       "objective": self.objective}, f, indent=2)
        os.replace(tmp, path)  # atomic: a crashed save never truncates

    @classmethod
    def load(cls, path: str) -> "Profile":
        with open(path) as f:
            d = json.load(f)
        return cls(name=str(d.get("name", "default")),
                   knobs={str(k): float(v)
                          for k, v in dict(d.get("knobs", {})).items()},
                   objective=float(d.get("objective", 0.0)))


class Autotuner:
    def __init__(self, knobs: Sequence[Knob],
                 metrics_fn: Callable[[], dict], *,
                 interval_s: float = 1.0,
                 guard_frac: float = 0.10,
                 epsilon: float = 0.01,
                 clock: Callable[[], float] = time.monotonic,
                 scope=None,
                 profile_name: str = "default"):
        if not 0.0 < guard_frac <= 1.0:
            raise ValueError("guard_frac must be in (0, 1]")
        self.knobs = list(knobs)
        self.metrics_fn = metrics_fn
        self.interval_s = float(interval_s)
        self.guard_frac = float(guard_frac)
        self.epsilon = float(epsilon)
        self.clock = clock
        self.profile_name = profile_name
        self._scope = scope
        # guards the counters/state below ONLY — metrics_fn and knob.set
        # both run outside it (metrics_fn walks the context's stats locks;
        # holding app.tune across that would invert the hierarchy)
        self._lock = make_lock("app.tune")
        self._knob_i = 0
        self._dir = {k.name: 1.0 for k in self.knobs}
        self._step = {k.name: float(k.step) for k in self.knobs}
        self._flips = {k.name: 0 for k in self.knobs}
        self._pending: tuple[Knob, float, float] | None = None
        self._ref: float | None = None         # tracked accepted objective
        self._baseline: float | None = None    # FIRST measurement, fixed
        self._best: float | None = None
        self._moves = self._reverts = self._holds = self._trials = 0
        self._objective = 0.0
        self._last_move = ""
        self._last_move_t = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- the control loop ----------------------------------------------------
    def step(self) -> str:
        """One controller beat; returns what it did (``"hold"``,
        ``"accept"``, ``"revert"``, ``"propose"``, ``"idle"``)."""
        m = self.metrics_fn() or {}
        obj = float(m.get("objective", 0.0))
        burning = bool(m.get("slo_burning", False))
        with self._lock:
            self._objective = obj
            if self._baseline is None:
                self._baseline = obj
                self._ref = obj
                self._best = obj
            pending, self._pending = self._pending, None
        if burning:
            # SLO hold: revert the in-flight trial (its effect is part of
            # whatever is burning) and propose nothing until clean
            if pending is not None:
                knob, old, _new = pending
                knob.set(old)
            with self._lock:
                self._holds += 1
                self._note_move("hold (slo burning)")
            self._scope_add("tune_holds")
            return "hold"
        if pending is not None:
            return self._evaluate(pending, obj)
        return self._propose(obj)

    def _evaluate(self, pending: tuple[Knob, float, float],
                  obj: float) -> str:
        knob, old, new = pending
        with self._lock:
            self._trials += 1
            ref = self._ref if self._ref is not None else 0.0
        # ABSOLUTE margins on a |ref|-scale: relative (1 +/- frac) margins
        # invert for objectives that pass through zero or go negative
        # (goodput deltas, negative synthetic landscapes)
        scale = max(abs(ref), 1.0)
        if obj >= ref + self.epsilon * scale:
            with self._lock:
                self._moves += 1
                self._ref = obj
                if self._best is None or obj > self._best:
                    self._best = obj
                self._flips[knob.name] = 0
                self._note_move(f"{knob.name} {old:g}->{new:g} accepted")
            self._scope_add("tune_moves")
            return "accept"
        # not better: exact revert (the safety contract — tuned state only
        # ever drifts upward from the hand baseline)
        knob.set(old)
        with self._lock:
            self._reverts += 1
            self._dir[knob.name] = -self._dir[knob.name]
            self._flips[knob.name] += 1
            if obj < ref - self.guard_frac * scale:
                # hard regression: the step overshot, not just the wrong
                # direction — halve it (floored at the knob's min_step so
                # refinement never collapses below the quantization grid)
                self._step[knob.name] = max(self._step[knob.name] / 2,
                                            knob.step_floor)
            if self._flips[knob.name] >= 2:
                # both directions measured worse: this knob is locally
                # converged — move on and shrink its step for next visit
                self._flips[knob.name] = 0
                self._step[knob.name] = max(self._step[knob.name] / 2,
                                            knob.step_floor)
                self._knob_i += 1
            # a revert still refreshes the tracked reference (slowly): a
            # drifting workload must not strand the tuner comparing
            # against a stale good epoch
            self._ref = 0.7 * ref + 0.3 * obj
            self._note_move(f"{knob.name} {new:g}->{old:g} reverted")
        self._scope_add("tune_reverts")
        return "revert"

    def _propose(self, obj: float) -> str:
        with self._lock:
            ref = self._ref if self._ref is not None else obj
            # idle refresh: between trials the measurement IS the accepted
            # state — track it so ref follows workload drift
            self._ref = 0.7 * ref + 0.3 * obj
            if self._best is None or obj > self._best:
                self._best = obj
        if not self.knobs:
            return "idle"
        for _ in range(len(self.knobs)):
            with self._lock:
                knob = self.knobs[self._knob_i % len(self.knobs)]
                direction = self._dir[knob.name]
                step = self._step[knob.name]
            cur = float(knob.get())
            cand = knob.clamp(cur + direction * step)
            if cand == cur:
                cand = knob.clamp(cur - direction * step)
                if cand == cur:  # pinned both ways (degenerate bounds)
                    with self._lock:
                        self._knob_i += 1
                    continue
                with self._lock:
                    self._dir[knob.name] = -direction
            knob.set(cand)
            with self._lock:
                self._pending = (knob, cur, cand)
                self._note_move(f"{knob.name} {cur:g}->{cand:g} trial")
            return "propose"
        return "idle"

    def settle(self) -> str:
        """Evaluate the in-flight trial (if any) against the current
        objective WITHOUT proposing a new one — the terminal beat for
        bench arms, which must measure the converged state, not a
        half-evaluated experiment. Returns ``"accept"``, ``"revert"``
        or ``"idle"``."""
        m = self.metrics_fn() or {}
        obj = float(m.get("objective", 0.0))
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is None:
            return "idle"
        if bool(m.get("slo_burning", False)):
            knob, old, _new = pending
            knob.set(old)
            with self._lock:
                self._holds += 1
                self._note_move("hold (slo burning)")
            self._scope_add("tune_holds")
            return "revert"
        return self._evaluate(pending, obj)

    def _note_move(self, text: str) -> None:
        # caller holds self._lock
        self._last_move = text
        self._last_move_t = self.clock()

    def _scope_add(self, name: str) -> None:
        sc = self._scope
        if sc is not None:
            with contextlib.suppress(Exception):
                sc.add(name)

    # -- profiles ------------------------------------------------------------
    def profile(self) -> Profile:
        return Profile(name=self.profile_name,
                       knobs={k.name: float(k.get()) for k in self.knobs},
                       objective=float(self._best or 0.0))

    def apply_profile(self, profile: Profile) -> int:
        """Set every knob the profile names (clamped to the knob's live
        bounds); unknown names are ignored — a profile saved on a bigger
        box must not wedge a smaller one. Returns knobs applied."""
        by_name = {k.name: k for k in self.knobs}
        applied = 0
        for name, value in profile.knobs.items():
            knob = by_name.get(name)
            if knob is None:
                continue
            knob.set(knob.clamp(float(value)))
            applied += 1
        with self._lock:
            self.profile_name = profile.name
            self._note_move(f"profile {profile.name} applied")
        return applied

    # -- driver thread -------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="strom-tune",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # stromlint: ignore[swallowed-exceptions] -- the tuner is advisory: a step that raises (context mid-close, knob surface gone) must not kill the driver thread; the error surfaces as tune_step_errors
                self._scope_add("tune_step_errors")

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        # leave knobs where the search put them: close() is not a revert —
        # callers that want the hand state back apply their own profile

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            baseline = self._baseline
            best = self._best
            out = {
                "tune_active": int(self._thread is not None),
                "tune_moves": self._moves,
                "tune_reverts": self._reverts,
                "tune_holds": self._holds,
                "tune_trials": self._trials,
                "tune_objective": round(self._objective, 4),
                "tune_baseline_objective": round(baseline or 0.0, 4),
                "tune_best_objective": round(best or 0.0, 4),
                # >= 1.0 by construction (only measured-better moves
                # persist); the bench gate's tuned_vs_hand reads the same
                # quantity measured externally across phases
                "tuned_vs_baseline": round(
                    (best / baseline) if baseline and best else 1.0, 4),
                "tune_profile": self.profile_name,
                "tune_last_move": self._last_move,
            }
        out["tune_knobs"] = {k.name: float(k.get()) for k in self.knobs}
        return out
