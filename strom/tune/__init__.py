"""Closed-loop knob autotuner (ISSUE 16 policy half).

The mechanism half of the kernel-bypass pass makes the transport cheap; this
package makes the KNOBS that drive it self-setting. An :class:`Autotuner`
runs coordinate descent over live knob surfaces (prefetch depth,
``sched_slice_bytes``, hot-cache budget, ...) against a caller-supplied
objective (goodput / items-per-second), with two safety invariants:

- **guarded step**: a move that costs more than ``guard_frac`` of the
  objective is reverted immediately and the search direction flips;
- **SLO hold**: while any tenant's SLO is burning the tuner reverts its
  in-flight trial and proposes nothing — it never experiments on a
  workload that is already missing its target.

Profiles (the converged knob values) persist as JSON per bench arm
(``--profile`` on the cli) so a tuned workload starts where the last run
ended instead of re-searching from the hand defaults.
"""

from strom.tune.autotuner import (TUNE_BENCH_FIELDS, TUNE_FIELDS, Autotuner,
                                  Profile, stall_weighted_metrics)
from strom.tune.knobs import Knob, prefetcher_knob, standard_knobs

__all__ = ["Autotuner", "Knob", "Profile", "TUNE_BENCH_FIELDS",
           "TUNE_FIELDS", "prefetcher_knob", "stall_weighted_metrics",
           "standard_knobs"]
