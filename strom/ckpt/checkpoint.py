"""HBM→SSD checkpoint save/restore through the engine write path (ISSUE 13
tentpole, front 2).

The repo's existing :class:`~strom.pipelines.checkpoint.TrainCheckpointer`
delegates the train state to orbax — a generic serializer writing through
the page cache with no relationship to the I/O engine the rest of the data
plane runs on. This module is the engine-native alternative: a train state
(any pytree of arrays) is flattened into one flat ``data.bin`` of
4096-aligned leaf spans and written through ``submit_vectored(op="write")``
— O_DIRECT-aligned via the delivery slab pool, scheduler-granted (a
checkpoint save is a tenant like any other: PR 7 budgets/priority apply,
and a concurrent pipeline's read queues behind at most one write slice),
retry/breaker covered. Restore reads each leaf back with
``memcpy_ssd2tpu`` — the same SSD→accelerator hot path training data rides.

Layout (one checkpoint = one directory)::

    <dir>/manifest.json   # format tag, leaf table (shape/dtype/offset/
                          # nbytes/crc32), total_bytes
    <dir>/data.bin        # leaf bytes, each span 4096-aligned (gaps zero)

Crash safety: everything lands in ``<dir>.tmp-<pid>`` first, data and
manifest are fsync'd, and the directory rename is the COMMIT — a crash at
any earlier point leaves the previous checkpoint (or nothing) intact and a
``.tmp-*`` orphan that never looks like a checkpoint. Integrity: every
leaf carries a CRC32; ``restore_checkpoint(verify=True)`` detects on-media
corruption (a bit-flipped ``data.bin``) with a typed
:class:`CkptCorruptError` instead of silently training from garbage.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import zlib
from typing import Any

import numpy as np

from strom.delivery.buffers import alloc_aligned

ALIGN = 4096          # leaf-span alignment: O_DIRECT offset granularity
FORMAT = "strom-ckpt-v1"
_STAGE_BYTES = 32 << 20   # staging slab per write flush

# bench-JSON columns the checkpoint bench phase emits (cli.py
# bench_checkpoint), single-sourced so the driver's copy loop (bench.py)
# and the compare_rounds "write path" section cannot drift from the
# producer — the same contract CACHE_BENCH_FIELDS / SPILL_FIELDS enforce.
CKPT_FIELDS = (
    "ckpt_bytes",
    "ckpt_leaves",
    "ckpt_save_mb_per_s",
    "ckpt_restore_mb_per_s",
    "ckpt_pickle_save_mb_per_s",
    "ckpt_save_vs_pickle",
    "ckpt_roundtrip_ok",
)


class CkptError(RuntimeError):
    pass


class CkptCorruptError(CkptError):
    """A leaf's bytes on media do not match its manifest CRC."""


def _aligned(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


def _dtype_name(dt) -> str:
    # .name round-trips the accelerator dtypes ("bfloat16", "float8_e4m3fn")
    # where .str degrades them to opaque void ("|V2")
    return np.dtype(dt).name


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # accelerator dtypes live in ml_dtypes (a jax dependency)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _host_leaves(state: Any, *, snapshot: bool = False
                 ) -> tuple[list[np.ndarray], Any]:
    """Flatten *state* and pull every leaf to host memory as a contiguous
    numpy array (jax arrays device_get; scalars become 0-d arrays).

    ``snapshot=True`` (the async save path, strom/ckpt/async_save.py)
    additionally COPIES leaves the caller could mutate in place after this
    returns: jax arrays are immutable — holding the device_get result is
    already a stable snapshot — but a plain numpy leaf (an optimizer step
    counter someone increments, a running metric buffer) is live memory,
    and a background commit reading it mid-train would persist a torn
    state. The copy is the snapshot half of snapshot-then-commit: bounded
    by host memcpy bandwidth, never by NVMe."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    out = []
    for leaf in leaves:
        a = np.asarray(jax.device_get(leaf))
        if not a.flags["C_CONTIGUOUS"]:
            # ascontiguousarray unconditionally would also promote 0-d
            # scalars to (1,) and break the template shape check
            a = np.ascontiguousarray(a)
        elif snapshot and isinstance(leaf, np.ndarray):
            a = a.copy()
        out.append(a)
    return out, treedef


class _Stager:
    """Double-buffered staging for the checkpoint write stream: leaf spans
    are copied (CRC computed in the same pass — no separate integrity
    sweep over the whole state) into one of two O_DIRECT-aligned slabs
    while the OTHER slab's multi-chunk engine write drains on a writer
    thread — staging memcpy+CRC overlap the NVMe writes, so save wall is
    ~max(copy, write) instead of their sum. The slabs are the aligned
    bounce the caller's (arbitrarily-aligned) host arrays ride to disk."""

    def __init__(self, ctx, fi: int, tenant: "str | None",
                 priority: "str | None" = None):
        import concurrent.futures

        self._ctx = ctx
        self._fi = fi
        self._tenant = tenant
        # scheduler priority class for the engine writes (ISSUE 14): the
        # async checkpointer commits as "background" so a save stream never
        # outranks the training tenants' demand reads in the fair drain
        self._priority = priority
        pool = getattr(ctx, "_slab_pool", None)
        self._pool = pool
        self._bufs = [pool.acquire(_STAGE_BYTES) if pool is not None
                      else alloc_aligned(_STAGE_BYTES) for _ in range(2)]
        self._futs: list = [None, None]
        self._cur = 0
        self._used = 0
        self._chunks: list[tuple[int, int, int, int]] = []
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="strom-ckpt-write")

    def _flush_swap(self) -> None:
        """Hand the current slab to the writer thread and make the other
        one (its previous write drained) current."""
        if not self._chunks:
            return
        i = self._cur
        self._futs[i] = self._exec.submit(
            self._ctx.write_chunks, self._chunks, self._bufs[i],
            tenant=self._tenant, priority=self._priority)
        self._chunks = []
        self._used = 0
        self._cur = 1 - i
        f = self._futs[self._cur]
        if f is not None:
            self._futs[self._cur] = None
            f.result()  # the slab we are about to fill must be retired

    def add(self, file_off: int, a8: np.ndarray) -> int:
        """Stage one leaf's bytes; returns their CRC32 (computed during
        the copy — the bytes are already streaming through the cache)."""
        crc = 0
        pos = 0
        n = a8.nbytes
        buf = None
        while pos < n:
            free = _STAGE_BYTES - self._used
            if free == 0:
                self._flush_swap()
                free = _STAGE_BYTES
            buf = self._bufs[self._cur]
            take = min(free, n - pos)
            piece = a8[pos: pos + take]
            crc = zlib.crc32(piece, crc)
            buf[self._used: self._used + take] = piece
            self._chunks.append((self._fi, file_off + pos, self._used, take))
            self._used += take
            pos += take
        return crc & 0xFFFFFFFF

    def finish(self) -> None:
        """Drain everything (the LAST write included) — raises the first
        writer-thread failure here, before the manifest commits."""
        self._flush_swap()
        for i, f in enumerate(self._futs):
            if f is not None:
                self._futs[i] = None
                f.result()

    def close(self) -> None:
        self._exec.shutdown(wait=True)
        if self._pool is not None:
            for b in self._bufs:
                self._pool.release(b)
        self._bufs = []


def _build_manifest(leaves: "list[np.ndarray]",
                    extra: "dict | None" = None) -> dict:
    """Leaf table + span layout for a flattened state. ``extra`` is an
    opaque caller payload stored INSIDE the manifest (the resume layer
    puts the StepToken there, strom/ckpt/jobstate.py) — committed by the
    same rename as the data, so a checkpoint can never exist without its
    resume point or vice versa."""
    metas = []
    off = 0
    for i, a in enumerate(leaves):
        metas.append({
            "index": i,
            "shape": list(a.shape),
            "dtype": _dtype_name(a.dtype),
            "offset": off,
            "nbytes": int(a.nbytes),
            "crc32": 0,  # filled during staging (one pass over the bytes)
        })
        off += _aligned(max(a.nbytes, 1))
    return {"format": FORMAT, "total_bytes": off,
            "payload_bytes": int(sum(m["nbytes"] for m in metas)),
            "extra": extra or {},
            "leaves": metas}


def save_checkpoint(ctx, directory: str, state: Any, *,
                    tenant: "str | None" = None,
                    extra: "dict | None" = None,
                    priority: "str | None" = None) -> dict:
    """Write *state* (any pytree of arrays) to *directory* through the
    engine write path. Returns the manifest dict (``total_bytes`` is the
    payload size the bench rates). Crash-safe: the directory rename is the
    commit; an existing checkpoint at *directory* is replaced atomically
    (old state survives any crash before the rename lands). *extra* rides
    the manifest (see :func:`_build_manifest`); *priority* is the
    scheduler class the engine writes run under."""
    leaves, _treedef = _host_leaves(state)
    return _commit_checkpoint(ctx, directory, leaves,
                              _build_manifest(leaves, extra),
                              tenant=tenant, priority=priority)


def _commit_checkpoint(ctx, directory: str, leaves: "list[np.ndarray]",
                       manifest: dict, *, tenant: "str | None" = None,
                       priority: "str | None" = None) -> dict:
    """The commit half of a save: stage + engine-write the (already
    host-resident) leaves into ``<dir>.tmp-<pid>``, fsync, and rename —
    shared by the blocking save above and the async checkpointer's writer
    thread (strom/ckpt/async_save.py), so the two paths' crash-safety
    semantics can never drift."""
    metas = manifest["leaves"]
    total = manifest["total_bytes"]
    directory = os.path.abspath(directory)
    tmp = f"{directory}.tmp-{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    try:
        data_path = os.path.join(tmp, "data.bin")
        fd = os.open(data_path, os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, total)  # gaps between spans read as zeros
        finally:
            os.close(fd)
        if total:
            # registered directly with the engine, NOT through the ctx
            # path-keyed registry: the tmp path is reused across saves in
            # one process, and a cached fd would write into the PREVIOUS
            # (renamed, committed) inode
            fi = ctx.engine.register_file(data_path,
                                          o_direct=ctx.config.o_direct,
                                          writable=True)
            try:
                st = _Stager(ctx, fi, tenant, priority)
                try:
                    for meta, a in zip(metas, leaves):
                        if meta["nbytes"]:
                            meta["crc32"] = st.add(
                                meta["offset"],
                                a.reshape(-1).view(np.uint8))
                    st.finish()
                finally:
                    st.close()
            finally:
                ctx.engine.unregister_file(fi)
        # durability before the commit rename: data, then manifest, then
        # the directory entries themselves
        for name, payload in (("data.bin", None),
                              ("manifest.json", manifest)):
            p = os.path.join(tmp, name)
            if payload is not None:
                with open(p, "w") as f:
                    json.dump(payload, f)
            fd = os.open(p, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        dfd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        # commit: rename is atomic; replacing an existing checkpoint keeps
        # the old one live until the new one is fully durable. A FAILED
        # second rename rolls the old checkpoint back into place (neither
        # copy is ever destroyed by an exception); the only residual hole
        # is a hard process crash exactly between the two renames, which
        # leaves the previous checkpoint recoverable at
        # ``<dir>.old-<pid>`` (documented, never silently deleted by a
        # different process's later save)
        if os.path.exists(directory):
            old = f"{directory}.old-{os.getpid()}"
            shutil.rmtree(old, ignore_errors=True)
            os.rename(directory, old)
            try:
                os.rename(tmp, directory)
            except BaseException:
                with contextlib.suppress(OSError):
                    os.rename(old, directory)  # roll back: old state live
                raise
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    parent = os.open(os.path.dirname(directory) or ".", os.O_RDONLY)
    try:
        os.fsync(parent)
    finally:
        os.close(parent)
    # the committed path names a NEW inode: stale fds / cached bytes for a
    # previous checkpoint at this directory must not serve a restore
    ctx.invalidate_file(os.path.join(directory, "data.bin"))
    return manifest


def load_manifest(directory: str) -> dict:
    p = os.path.join(directory, "manifest.json")
    try:
        with open(p) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CkptError(f"not a checkpoint: {p}: {e}") from None
    if manifest.get("format") != FORMAT:
        raise CkptError(f"unknown checkpoint format "
                        f"{manifest.get('format')!r} at {directory}")
    return manifest


def last_committed(directory: str) -> "tuple[str, dict] | None":
    """The committed checkpoint at *directory* as ``(path, manifest)``, or
    None when nothing committed. Cross-process recovery entry point
    (ISSUE 14): a restarted job calls this FIRST. Handles the one residual
    crash hole of the commit protocol — a hard kill exactly between the
    two renames of a replace-commit leaves *directory* absent and the
    previous checkpoint at ``<dir>.old-<pid>``; that orphan is rolled back
    into place here (the pid in the suffix belongs to the dead process, so
    nobody else can be mid-commit on it)."""
    directory = os.path.abspath(directory)
    try:
        return directory, load_manifest(directory)
    except CkptError:
        pass
    import glob as _glob

    if not os.path.exists(directory):
        for old in sorted(_glob.glob(f"{directory}.old-*")):
            try:
                manifest = load_manifest(old)
            except CkptError:
                continue
            os.rename(old, directory)
            return directory, manifest
    return None


def clean_orphans(directory: str) -> list[str]:
    """Remove ``<dir>.tmp-*`` staging orphans a killed process left behind
    (and any ``.old-*`` made redundant by a live committed checkpoint).
    Returns the paths removed. Never touches the committed checkpoint —
    orphans are, by the commit protocol, never loadable as one. Call
    AFTER :func:`last_committed` (which may still need an ``.old-*``)."""
    directory = os.path.abspath(directory)
    import glob as _glob

    removed = []
    for p in sorted(_glob.glob(f"{directory}.tmp-*")):
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    if os.path.isdir(directory):
        for p in sorted(_glob.glob(f"{directory}.old-*")):
            shutil.rmtree(p, ignore_errors=True)
            removed.append(p)
    return removed


def restore_checkpoint(ctx, directory: str, template: Any, *,
                       verify: bool = False,
                       tenant: "str | None" = None) -> Any:
    """Restore the pytree saved at *directory*, structured like *template*
    (the usual abstract-state contract: the treedef and leaf shapes/dtypes
    come from it and are checked against the manifest). Leaves are
    delivered with ``memcpy_ssd2tpu`` — the training-data hot path, hot
    cache and all. ``verify=True`` additionally host-reads each leaf and
    checks its CRC32 (typed :class:`CkptCorruptError` on mismatch) before
    the bytes are handed to the accelerator."""
    import jax

    manifest = load_manifest(directory)
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    metas = manifest["leaves"]
    if len(t_leaves) != len(metas):
        raise CkptError(f"template has {len(t_leaves)} leaves, checkpoint "
                        f"has {len(metas)}")
    data_path = os.path.join(directory, "data.bin")
    out = []
    for meta, t_leaf in zip(metas, t_leaves):
        shape = tuple(meta["shape"])
        dtype = _np_dtype(meta["dtype"])
        t_shape = tuple(getattr(t_leaf, "shape", np.shape(t_leaf)))
        if t_shape != shape:
            raise CkptError(f"leaf {meta['index']}: template shape "
                            f"{t_shape} != checkpoint {shape}")
        t_dtype = getattr(t_leaf, "dtype", None)
        if t_dtype is not None and _dtype_name(t_dtype) != meta["dtype"]:
            raise CkptError(f"leaf {meta['index']}: template dtype "
                            f"{_dtype_name(t_dtype)} != checkpoint "
                            f"{meta['dtype']}")
        if meta["nbytes"] == 0:
            out.append(np.empty(shape, dtype=dtype))
            continue
        if verify:
            host = ctx.pread(data_path, offset=meta["offset"],
                             length=meta["nbytes"], tenant=tenant)
            crc = zlib.crc32(host[: meta["nbytes"]]) & 0xFFFFFFFF
            if crc != meta["crc32"]:
                raise CkptCorruptError(
                    f"leaf {meta['index']} CRC mismatch at {data_path}"
                    f"+{meta['offset']}: {crc:#010x} != "
                    f"{meta['crc32']:#010x}")
            arr = jax.device_put(
                host[: meta["nbytes"]].view(dtype).reshape(shape))
        else:
            arr = ctx.memcpy_ssd2tpu(data_path, offset=meta["offset"],
                                     shape=shape, dtype=dtype,
                                     tenant=tenant)
        sh = getattr(t_leaf, "sharding", None)
        if sh is not None:
            arr = jax.device_put(arr, sh)
        if not hasattr(t_leaf, "shape") and np.ndim(t_leaf) == 0:
            # plain python scalar in the template (a step counter): hand
            # back the same kind, not a 0-d device array
            arr = np.asarray(arr).item()
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


# -- the baseline the bench compares against ---------------------------------
def save_pickle(path: str, state: Any) -> int:
    """pickle-to-filesystem baseline: device_get the tree and pickle.dump
    it through the page cache (fsync'd, same durability bar). Returns
    bytes written."""
    import jax

    host = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), state)
    with open(path, "wb") as f:
        pickle.dump(host, f, protocol=4)
        f.flush()
        os.fsync(f.fileno())
    return os.path.getsize(path)


def load_pickle(path: str) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)
