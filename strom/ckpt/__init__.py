"""HBM→SSD checkpointing through the engine write path (ISSUE 13) plus the
preemption-safety layer on top (ISSUE 14): async snapshot-then-commit
saves (strom/ckpt/async_save.py) and deterministic end-to-end resume
tokens (strom/ckpt/jobstate.py)."""

from strom.ckpt.async_save import (CKPT_ASYNC_FIELDS, AsyncCheckpointer,
                                   CkptAsyncError, save_checkpoint_async)
from strom.ckpt.checkpoint import (CKPT_FIELDS, CkptCorruptError, CkptError,
                                   clean_orphans, last_committed, load_manifest,
                                   load_pickle, restore_checkpoint,
                                   save_checkpoint, save_pickle)
from strom.ckpt.jobstate import (RESUME_FIELDS, StepToken, capture_warm_state,
                                 restore_warm_state)

__all__ = [
    "CKPT_ASYNC_FIELDS",
    "CKPT_FIELDS",
    "RESUME_FIELDS",
    "AsyncCheckpointer",
    "CkptAsyncError",
    "CkptCorruptError",
    "CkptError",
    "StepToken",
    "capture_warm_state",
    "clean_orphans",
    "last_committed",
    "load_manifest",
    "load_pickle",
    "restore_checkpoint",
    "restore_warm_state",
    "save_checkpoint",
    "save_checkpoint_async",
    "save_pickle",
]
