"""HBM→SSD checkpointing through the engine write path (ISSUE 13)."""

from strom.ckpt.checkpoint import (CKPT_FIELDS, CkptCorruptError, CkptError,
                                   load_pickle, restore_checkpoint,
                                   save_checkpoint, save_pickle)

__all__ = [
    "CKPT_FIELDS",
    "CkptCorruptError",
    "CkptError",
    "load_pickle",
    "restore_checkpoint",
    "save_checkpoint",
    "save_pickle",
]
