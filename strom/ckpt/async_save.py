"""Async snapshot-then-commit checkpointing (ISSUE 14 tentpole, front 1).

The blocking :func:`~strom.ckpt.checkpoint.save_checkpoint` stalls the
training thread for the whole save wall — on the llama-small state that is
seconds of NVMe write time the accelerator spends idle. This module splits
the save at the only boundary that matters for that stall:

- **snapshot** (caller's thread, bounded, fast): flatten the pytree and
  pull every leaf to host memory — jax arrays are immutable so device_get
  IS the snapshot; mutable numpy leaves are copied
  (``_host_leaves(snapshot=True)``). Cost: one pass at host-memcpy
  bandwidth, never NVMe. The moment :meth:`AsyncCheckpointer.save`
  returns, training may mutate/replace the state freely.
- **commit** (background writer thread): the exact
  :func:`~strom.ckpt.checkpoint._commit_checkpoint` the blocking save
  runs — double-buffered slab staging with CRC folded into the copy pass,
  multi-chunk engine writes (scheduler-granted as the BACKGROUND class so
  a save stream never outranks training's demand reads), fsync, and the
  tmp+rename commit.

Failure contract: a failed commit NEVER destroys the previous checkpoint
(the rename-is-commit protocol guarantees it), latches the error, dumps a
flight bundle (reason ``ckpt_commit_failed``) when the context has a
flight dir, and raises the latched :class:`CkptError` on the NEXT
:meth:`~AsyncCheckpointer.save` or :meth:`~AsyncCheckpointer.wait` — an
async save may not fail silently, but it also must not fail on a thread
nobody is watching. One in-flight save at a time: a second ``save`` first
waits out the current commit (back-pressure, counted in the stall timer),
so the checkpointer can never queue unbounded snapshots.

``CKPT_ASYNC_FIELDS`` single-sources the bench columns the ``resume`` arm
emits (cli.py bench_resume → bench.py copy loop → compare_rounds "resume"
section → bench_sentinel gate on ``ckpt_async_stall_p99_us``).
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import os
import time
from typing import Any

from strom.ckpt.checkpoint import (CkptError, _build_manifest,
                                   _commit_checkpoint, _host_leaves,
                                   load_manifest)
from strom.utils.locks import make_lock

# bench-JSON columns the resume arm's async-save phase emits (cli.py
# bench_resume), single-sourced so the driver's copy loop (bench.py) and
# the compare_rounds "resume" section cannot drift from the producer —
# the same contract CKPT_FIELDS / SPILL_FIELDS enforce.
CKPT_ASYNC_FIELDS = (
    "ckpt_async_saves",
    "ckpt_async_stall_p99_us",
    "ckpt_async_stall_mean_us",
    "ckpt_sync_save_wall_us",
    "ckpt_async_stall_frac",
    "ckpt_async_commit_mb_per_s",
)


class CkptAsyncError(CkptError):
    """A background commit failed; the PREVIOUS checkpoint is intact and
    restorable. Carries the original failure as ``__cause__``."""


class AsyncCheckpointer:
    """Snapshot-then-commit checkpoints to one directory.

    One writer per directory: two checkpointers (or processes) committing
    to the same path would race the pid-keyed tmp staging. ``save`` is the
    training-loop call; ``wait`` joins the in-flight commit; ``last_saved``
    is the manifest of the newest COMMITTED save (None before the first);
    ``last_committed`` the committed directory path. ``close`` drains.

    Telemetry (scoped through *ctx*): ``ckpt_async_saves`` /
    ``ckpt_async_commits`` / ``ckpt_async_failures`` counters and the
    ``ckpt_async_stall_us`` histogram of per-save caller-thread stalls —
    the number the <25%-of-sync-wall acceptance is measured on.
    """

    def __init__(self, ctx, directory: str, *, tenant: "str | None" = None,
                 priority: "str | None" = "background"):
        self._ctx = ctx
        self._dir = os.path.abspath(directory)
        self._tenant = tenant
        self._priority = priority
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="strom-ckpt-commit")
        self._lock = make_lock("app.ckpt_async")
        self._fut: "concurrent.futures.Future | None" = None
        self._error: "BaseException | None" = None
        self._last_manifest: "dict | None" = None
        self._closed = False
        self.saves = 0
        self.commits = 0
        self.failures = 0
        # caller-blocked time per save(), bounded: a trainer saving for
        # weeks must not grow resident memory per save (the full series
        # also lands in the scoped ckpt_async_stall_us histogram)
        import collections

        self.stalls_us: "collections.deque[float]" = \
            collections.deque(maxlen=1024)

    # -- the training-loop call ---------------------------------------------
    def save(self, state: Any, *, extra: "dict | None" = None) -> int:
        """Snapshot *state* on THIS thread and hand the commit to the
        writer. Returns the save serial. Blocks only for the snapshot
        (plus draining a still-running previous commit — back-pressure).
        Raises the latched :class:`CkptAsyncError` if the previous commit
        failed (the old checkpoint is still committed and restorable)."""
        t0 = time.perf_counter()
        if self._closed:
            raise CkptError("AsyncCheckpointer is closed")
        self._join(raise_error=True)
        leaves, _ = _host_leaves(state, snapshot=True)
        manifest = _build_manifest(leaves, extra)
        with self._lock:
            self.saves += 1
            serial = self.saves
            self._fut = self._exec.submit(self._commit, leaves, manifest)
        stall_us = (time.perf_counter() - t0) * 1e6
        self.stalls_us.append(stall_us)
        scope = getattr(self._ctx, "scope", None)
        if scope is not None:
            scope.add("ckpt_async_saves")
            scope.observe_us("ckpt_async_stall_us", stall_us)
        return serial

    def _commit(self, leaves, manifest) -> dict:
        try:
            m = _commit_checkpoint(self._ctx, self._dir, leaves, manifest,
                                   tenant=self._tenant,
                                   priority=self._priority)
        except BaseException as e:
            with self._lock:
                self._error = e
                self.failures += 1
            scope = getattr(self._ctx, "scope", None)
            if scope is not None:
                scope.add("ckpt_async_failures")
            self._dump_flight(e)
            raise
        with self._lock:
            self._last_manifest = m
            self.commits += 1
        scope = getattr(self._ctx, "scope", None)
        if scope is not None:
            scope.add("ckpt_async_commits")
        return m

    def _dump_flight(self, exc: BaseException) -> None:
        """A failed commit is a post-mortem moment: the bundle carries the
        stats/stacks/trace that led up to it (same policy as a breaker
        trip). Best-effort — the error itself is latched regardless."""
        with contextlib.suppress(Exception):
            fr = getattr(self._ctx, "flight_recorder", None)
            if fr is not None:
                fr.dump("ckpt_commit_failed", note=repr(exc))
            elif getattr(self._ctx.config, "flight_dir", ""):
                from strom.obs.flight import dump_capture

                dump_capture(self._ctx.config.flight_dir,
                             reason="ckpt_commit_failed", note=repr(exc),
                             ctx=self._ctx)

    def _join(self, *, raise_error: bool) -> None:
        with self._lock:
            fut = self._fut
        if fut is not None:
            # the future's own exception is re-raised via the latch below
            # (typed, with the "old checkpoint intact" framing), not here
            concurrent.futures.wait([fut])
            with self._lock:
                if self._fut is fut:
                    self._fut = None
        if raise_error:
            with self._lock:
                err, self._error = self._error, None
            if err is not None:
                raise CkptAsyncError(
                    f"async checkpoint commit to {self._dir} failed "
                    f"({err!r}); the previous checkpoint is intact"
                ) from err

    # -- completion surface --------------------------------------------------
    def wait(self) -> "dict | None":
        """Drain the in-flight commit (if any). Raises the latched
        :class:`CkptAsyncError` from a failed one; returns the manifest of
        the newest committed save (None when nothing ever committed)."""
        self._join(raise_error=True)
        with self._lock:
            return self._last_manifest

    def last_committed(self) -> "str | None":
        """Path of the newest COMMITTED checkpoint this process knows of:
        the directory once a commit landed (this checkpointer's or a
        previous process's — a pre-existing committed checkpoint counts),
        else None. Never blocks; an in-flight commit doesn't count until
        its rename lands."""
        with self._lock:
            if self._last_manifest is not None:
                return self._dir
        try:
            load_manifest(self._dir)
            return self._dir
        except CkptError:
            return None

    @property
    def in_flight(self) -> bool:
        with self._lock:
            return self._fut is not None and not self._fut.done()

    @property
    def error(self) -> "BaseException | None":
        """The latched commit failure (cleared when save/wait raises it)."""
        with self._lock:
            return self._error

    def stats(self) -> dict:
        with self._lock:
            st = sorted(self.stalls_us)
            return {
                "ckpt_async_saves": self.saves,
                "ckpt_async_commits": self.commits,
                "ckpt_async_failures": self.failures,
                "ckpt_async_stall_p99_us":
                    round(st[min(int(len(st) * 0.99), len(st) - 1)], 1)
                    if st else 0.0,
                "ckpt_async_stall_mean_us":
                    round(sum(st) / len(st), 1) if st else 0.0,
            }

    def close(self, *, wait: bool = True) -> None:
        """Drain (``wait=True``) and shut the writer down. Swallows
        nothing: a latched failure still raises here unless ``wait=False``
        (teardown-on-error paths)."""
        if self._closed:
            return
        self._closed = True
        try:
            if wait:
                self._join(raise_error=True)
        finally:
            self._exec.shutdown(wait=wait)

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        # an exception already unwinding must not be masked by the drain's
        self.close(wait=exc_type is None)


def save_checkpoint_async(ctx, directory: str, state: Any, *,
                          tenant: "str | None" = None,
                          extra: "dict | None" = None,
                          priority: "str | None" = "background"
                          ) -> AsyncCheckpointer:
    """One-shot spelling of the above: snapshot *state* now, commit in the
    background, return the checkpointer (``wait()`` for the manifest).
    Training loops that save repeatedly should hold one
    :class:`AsyncCheckpointer` instead (one writer thread, back-pressure,
    the failure latch across saves)."""
    cp = AsyncCheckpointer(ctx, directory, tenant=tenant, priority=priority)
    cp.save(state, extra=extra)
    return cp
