"""StepToken: the deterministic-resume point of a training job (ISSUE 14
tentpole, front 2).

The sampler layer already made the BATCH STREAM a pure function of
``(seed, epoch, cursor)`` (strom/pipelines/sampler.py: Philox(seed, epoch)
permutations, cursor fast-forward, no stored RNG state) and the decode
layer made augmentation a pure function of the global batch SERIAL
(strom/pipelines/vision.py: RNG streams keyed on serial, stable across
resume). This module packages those coordinates — plus the two pieces of
soft state worth carrying across a restart — into one compact, JSON-stable
token:

- **position**: epoch, batch-in-epoch cursor, shuffle seed, and the global
  consumed-batch serial (the serial is derivable from the first three; it
  is carried explicitly so a resumed process can assert it continued at
  exactly the right batch — the harness's no-replay check).
- **prefetch depth**: the auto-depth controller's current operating point,
  so a resumed job starts at the depth the workload already converged to
  instead of re-learning it from stalls.
- **warm-state hints** (optional): the hot-cache and spill-tier manifests
  — ``(path, lo, hi)`` physical ranges — captured at save time. A restart
  can replay them through ``restore_warm_state`` (ctx.warm: background
  class, yields to demand) so the second process's cache starts where the
  first one's ended instead of cold. Hints are ADVISORY: correctness never
  depends on them, and decoded-frame tuple keys are skipped (pixels are
  re-derived, not re-read).

Tokens commit ATOMICALLY with the checkpoint they describe: the
checkpoint manifest's ``extra`` field carries ``{"step_token": ...}``
(strom/ckpt/checkpoint._build_manifest), so the tmp+rename commit is the
single durability point for both — a restart can never see a state
without its resume point or a token pointing at uncommitted state.

``RESUME_FIELDS`` single-sources the kill/restart harness's verdict
columns (strom/faults/resume_harness.py → bench resume arm →
compare_rounds "resume" section → bench_sentinel gate on ``resume_ok``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

from strom.pipelines.sampler import SamplerState

TOKEN_VERSION = 1
TOKEN_KEY = "step_token"       # where a token rides in manifest["extra"]

# resume-harness verdict columns (single-sourced: the harness emits them,
# the bench resume arm copies them, compare_rounds' "resume" section and
# bench_sentinel's resume_ok gate read them, lint_stats_names scans them —
# the same contract every *_FIELDS tuple in this repo enforces). They are
# also mirrored as gauges into the global registry by the harness, so a
# live /metrics scrape of a soak run shows the latest verdict.
RESUME_FIELDS = (
    "resume_ok",
    "resume_kill_step",
    "resume_restart_step",
    "resume_replayed_batches",
    "resume_batches_checked",
    "resume_orphan_tmps",
    "resume_ckpt_commits",
    "resume_wall_s",
)


@dataclasses.dataclass
class StepToken:
    """Everything a restarted job needs to continue the exact batch
    stream. ``sampler`` is the resume point of the NEXT unconsumed batch
    (the same derived-from-consumption contract ``Pipeline.state()``
    keeps); ``consumed`` its global serial. JSON round-trips via
    to_dict/from_dict; persists via save/load (atomic tmp+replace)."""

    sampler: SamplerState
    consumed: int = 0
    prefetch_depth: int = 0            # 0 = unknown / fixed-depth pipeline
    fingerprint: dict = dataclasses.field(default_factory=dict)
    warm: "dict | None" = None         # restore_warm_state hints
    extra: dict = dataclasses.field(default_factory=dict)
    version: int = TOKEN_VERSION

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["sampler"] = self.sampler.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "StepToken":
        if d.get("version") != TOKEN_VERSION:
            raise ValueError(f"unknown StepToken version {d.get('version')}")
        return cls(sampler=SamplerState.from_dict(d["sampler"]),
                   consumed=int(d.get("consumed", 0)),
                   prefetch_depth=int(d.get("prefetch_depth", 0)),
                   fingerprint=d.get("fingerprint") or {},
                   warm=d.get("warm"),
                   extra=d.get("extra") or {})

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "StepToken":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @classmethod
    def from_manifest(cls, manifest: dict) -> "StepToken | None":
        """The token committed with a checkpoint (manifest ``extra``), or
        None when the save carried no resume point."""
        doc = (manifest.get("extra") or {}).get(TOKEN_KEY)
        return cls.from_dict(doc) if doc else None


# -- warm-state hints ---------------------------------------------------------
def capture_warm_state(ctx, *, max_entries: int = 4096) -> "dict | None":
    """The hot-cache + spill-tier manifests as JSON-stable warm hints:
    ``{"cache": [[path, lo, hi], ...], "spill": [...]}``. Bounded at
    *max_entries* per tier (newest-first — the LRU tail is the part worth
    rewarming). None when the context has no cache. Decoded-frame tuple
    keys are skipped: their bytes are decode OUTPUT, not re-readable
    ranges of any source."""
    cache = getattr(ctx, "hot_cache", None)
    if cache is None:
        return None
    out: dict = {"cache": cache.manifest(max_entries=max_entries)}
    spill = getattr(ctx, "spill_tier", None)
    if spill is not None:
        out["spill"] = spill.manifest(max_entries=max_entries)
    return out


def restore_warm_state(ctx, warm: "dict | None", *,
                       tenant: "str | None" = None) -> int:
    """Replay warm hints through ``ctx.warm`` (background class, yields to
    demand reads, force-admits). Advisory: unreadable/vanished sources are
    skipped, a 0 return is legal. Returns bytes warmed."""
    if not warm or getattr(ctx, "hot_cache", None) is None:
        return 0
    from strom.delivery.shard import Segment

    by_path: dict[str, list[tuple[int, int]]] = {}
    for tier in ("cache", "spill"):
        for ent in warm.get(tier) or ():
            path, lo, hi = ent[0], int(ent[1]), int(ent[2])
            if isinstance(path, str) and hi > lo:
                by_path.setdefault(path, []).append((lo, hi))
    warmed = 0
    for path, spans in by_path.items():
        if not (os.path.exists(path)
                or ctx.striped_source(path) is not None):
            continue
        # merge overlaps: a promoted range is resident in BOTH tiers (the
        # readahead promotion leaves the spill copy in place), and warming
        # the same bytes twice would double the rewarm reads
        spans.sort()
        merged: list[tuple[int, int]] = []
        for lo, hi in spans:
            if merged and lo <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
            else:
                merged.append((lo, hi))
        # one warm call per path: dest offsets packed contiguously so the
        # warm slab (allocated lazily, misses only) stays minimal
        segs = []
        dest = 0
        for lo, hi in merged:
            segs.append(Segment(lo, dest, hi - lo))
            dest += hi - lo
        warmed += ctx.warm(path, segs, tenant=tenant)
    return warmed


def set_resume_gauges(results: dict, scope: "Any | None" = None) -> None:
    """Mirror a harness verdict dict onto /metrics: every numeric
    RESUME_FIELDS value becomes a same-named gauge (the live-scrape twin
    of the bench columns)."""
    if scope is None:
        from strom.utils.stats import global_stats as scope  # type: ignore

    for k in RESUME_FIELDS:
        v = results.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            scope.set_gauge(k, v)
        elif isinstance(v, bool):
            scope.set_gauge(k, int(v))
