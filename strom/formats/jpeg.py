"""JPEG host decode + ImageNet-style transforms (SURVEY.md §7.2 step 7:
"JPEG (host decode worker pool)").

The engine lands compressed bytes in host slabs; decode runs on a thread pool
(cv2 releases the GIL inside imdecode, so threads scale) and the decoded
uint8 tensor is what gets `device_put`.  Keeping decode on host mirrors the
division of labor in the reference's consumer (PG-Strom decompresses on GPU —
strom-tpu instead keeps the TPU's MXU for the model and spends host cores on
decode; the "0 data-stall" overlap hides both).  Consumer: the ResNet-50
pipeline (BASELINE config #2, BASELINE.json:8).
"""

from __future__ import annotations

import concurrent.futures
from typing import Callable, Iterable, Sequence

import numpy as np

try:
    import cv2

    _HAVE_CV2 = True
except Exception:  # pragma: no cover - cv2 is present in the target image
    _HAVE_CV2 = False

try:
    from PIL import Image
    import io

    _HAVE_PIL = True
except Exception:  # pragma: no cover
    _HAVE_PIL = False


def decode_jpeg(data: bytes | np.ndarray) -> np.ndarray:
    """Decode JPEG/PNG bytes → HWC uint8 RGB array."""
    if _HAVE_CV2:
        buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, memoryview)) \
            else data.view(np.uint8).reshape(-1)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if img is None:
            raise ValueError("not a decodable image")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if _HAVE_PIL:
        raw = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
        try:
            with Image.open(io.BytesIO(raw)) as im:
                return np.asarray(im.convert("RGB"))
        except Exception as e:  # UnidentifiedImageError etc. → one contract
            raise ValueError("not a decodable image") from e
    raise RuntimeError("no JPEG decoder available (need cv2 or PIL)")


def _resize(img: np.ndarray, h: int, w: int) -> np.ndarray:
    if _HAVE_CV2:
        return cv2.resize(img, (w, h), interpolation=cv2.INTER_LINEAR)
    return np.asarray(Image.fromarray(img).resize((w, h), Image.BILINEAR))


def center_crop_resize(img: np.ndarray, size: int,
                       *, resize_shorter: int | None = None) -> np.ndarray:
    """Eval transform: resize shorter side (default size*1.15), center crop."""
    shorter = resize_shorter or int(size * 1.15)
    h, w = img.shape[:2]
    scale = shorter / min(h, w)
    img = _resize(img, max(size, round(h * scale)), max(size, round(w * scale)))
    h, w = img.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return img[top: top + size, left: left + size]


def random_resized_crop(img: np.ndarray, size: int, rng: np.random.Generator,
                        *, scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3)) -> np.ndarray:
    """Train transform: Inception-style random area/aspect crop → size×size,
    plus a horizontal flip coin."""
    h, w = img.shape[:2]
    area = h * w
    for _ in range(10):
        target = area * rng.uniform(*scale)
        log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(log_r)
        cw = round(np.sqrt(target * ar))
        ch = round(np.sqrt(target / ar))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            img = img[top: top + ch, left: left + cw]
            break
    else:
        img = center_crop_resize(img, min(h, w), resize_shorter=min(h, w))
    out = _resize(img, size, size)
    if rng.random() < 0.5:
        out = out[:, ::-1]
    return np.ascontiguousarray(out)


class DecodePool:
    """Thread pool mapping decode+transform over batches of member payloads."""

    def __init__(self, workers: int = 8):
        if _HAVE_CV2:
            # parallelism comes from this pool, not from within one image
            cv2.setNumThreads(0)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="strom-decode")

    def map(self, fn: Callable[..., np.ndarray],
            items: Iterable, *extra: Sequence) -> list[np.ndarray]:
        return list(self._pool.map(fn, items, *extra))

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "DecodePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
