"""JPEG host decode + ImageNet-style transforms (SURVEY.md §7.2 step 7:
"JPEG (host decode worker pool)").

The engine lands compressed bytes in host slabs; decode runs on a thread pool
(cv2 releases the GIL inside imdecode, so threads scale) and the decoded
uint8 tensor is what gets `device_put`.  Keeping decode on host mirrors the
division of labor in the reference's consumer (PG-Strom decompresses on GPU —
strom-tpu instead keeps the TPU's MXU for the model and spends host cores on
decode; the "0 data-stall" overlap hides both).  Consumer: the ResNet-50
pipeline (BASELINE config #2, BASELINE.json:8).

Decode-path scheduling (ISSUE 2 tentpole):

- **Reduced-scale decode**: when the SAMPLED crop at 1/d scale still covers
  the target (d in 2/4/8; encoded dims read from the SOF header by
  :func:`parse_jpeg_dims` without decoding), decode via cv2's
  ``IMREAD_REDUCED_COLOR_{2,4,8}`` — libjpeg skips the corresponding IDCT
  work, up to 64x less at 1/8. The crop geometry is sampled in FULL-res
  coordinates BEFORE the denominator is chosen (RNG stream identical either
  way) and rescaled onto the reduced image; a crop that would need
  upscaling at 1/d rides a smaller d or the full path, so the knob is
  quality-neutral.  Counters: ``decode_reduced_hits_{2,4,8}``.
- **Direct-to-slot decode**: every transform takes an optional ``out=`` row
  (the final size x size x 3 destination inside a preallocated batch array)
  so the resize lands its pixels straight into the batch slot — no
  ``np.stack`` pass over the batch, no per-row output temporaries.
  :meth:`DecodePool.map_into` drives it; ``decode_slot_bytes`` counts the
  bytes delivered this way.
- **Per-sample failure policy** (slot path): a ``ValueError`` decode failure
  zeroes the row and bumps ``decode_errors`` instead of aborting the whole
  batch — one truncated JPEG in a million-sample epoch is data loss of one
  sample, not of the run.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from typing import Callable, Iterable, Sequence

import numpy as np

from strom.obs.events import ring
from strom.utils.stats import global_stats
from strom.utils.locks import make_lock

try:
    import cv2

    _HAVE_CV2 = True
# stromlint: ignore[swallowed-exceptions] -- capability probe: cv2 can
# fail to import with non-ImportError (missing libGL raises OSError);
# either way the flag flips and every decode path branches on it
except Exception:  # pragma: no cover - cv2 is present in the target image
    _HAVE_CV2 = False

try:
    from PIL import Image
    import io

    _HAVE_PIL = True
# stromlint: ignore[swallowed-exceptions] -- capability probe, same
# contract as the cv2 probe above: the flag is the observable outcome
except Exception:  # pragma: no cover
    _HAVE_PIL = False


# -- SOF header parsing (no decode) -----------------------------------------

# SOF0..SOF15 carry frame dimensions, except DHT (C4), JPG (C8), DAC (CC)
_SOF_MARKERS = frozenset(range(0xC0, 0xD0)) - {0xC4, 0xC8, 0xCC}


def parse_jpeg_dims(data: bytes | np.ndarray) -> tuple[int, int] | None:
    """(height, width) from a JPEG's SOF header, walking marker segments
    only — no entropy decode, no IDCT. Returns None for anything that is
    not parseable JPEG (PNG members, truncated headers): callers fall back
    to the full-scale decode path, which raises its own clear error."""
    if isinstance(data, np.ndarray):
        b = data.view(np.uint8).reshape(-1)
    else:
        b = np.frombuffer(data, dtype=np.uint8)
    n = b.shape[0]
    if n < 4 or b[0] != 0xFF or b[1] != 0xD8:
        return None
    i = 2
    while i + 3 < n:
        if b[i] != 0xFF:
            return None  # desynced: not walking marker segments anymore
        marker = int(b[i + 1])
        if marker == 0xFF:  # fill byte before a marker
            i += 1
            continue
        if marker == 0x01 or 0xD0 <= marker <= 0xD7:  # standalone TEM/RSTn
            i += 2
            continue
        if marker in (0xD9, 0xDA):  # EOI / SOS before any SOF: give up
            return None
        seg_len = (int(b[i + 2]) << 8) | int(b[i + 3])
        if seg_len < 2:
            return None
        if marker in _SOF_MARKERS:
            if i + 9 > n:
                return None
            h = (int(b[i + 5]) << 8) | int(b[i + 6])
            w = (int(b[i + 7]) << 8) | int(b[i + 8])
            return (h, w) if h > 0 and w > 0 else None
        i += 2 + seg_len
    return None


def reduced_denom(h: int, w: int, size: int) -> int:
    """Largest decode denominator d in (8, 4, 2) at which an (h, w) crop
    still covers the size×size target: min(h, w) >= size * d. Callers pass
    the CROP rectangle's dimensions, not the encoded image's — a reduced
    decode whose crop region lands below the target size would be bilinearly
    UPSCALED where the full path downsamples real pixels, a silent training
    -quality regression. 1 = decode full scale."""
    if size <= 0:
        return 1
    shorter = min(h, w)
    for d in (8, 4, 2):
        if shorter >= size * d:
            return d
    return 1


def decode_jpeg(data: bytes | np.ndarray, *, reduced: int = 1) -> np.ndarray:
    """Decode JPEG/PNG bytes → HWC uint8 RGB array.

    *reduced* in (2, 4, 8) decodes JPEGs at 1/reduced scale (libjpeg
    skips the corresponding IDCT work); the caller owns rescaling any
    crop geometry onto the reduced image (:func:`make_train_transform`).
    """
    if _HAVE_CV2:
        flag = {1: cv2.IMREAD_COLOR,
                2: cv2.IMREAD_REDUCED_COLOR_2,
                4: cv2.IMREAD_REDUCED_COLOR_4,
                8: cv2.IMREAD_REDUCED_COLOR_8}[reduced]
        buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, memoryview)) \
            else data.view(np.uint8).reshape(-1)
        img = cv2.imdecode(buf, flag)
        if img is None:
            raise ValueError("not a decodable image")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if _HAVE_PIL:
        raw = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
        try:
            with Image.open(io.BytesIO(raw)) as im:
                if reduced > 1:
                    # draft mode: JPEG power-of-2 reduced decode, same trick
                    im.draft("RGB", (max(1, im.width // reduced),
                                     max(1, im.height // reduced)))
                return np.asarray(im.convert("RGB"))
        except Exception as e:  # UnidentifiedImageError etc. → one contract
            raise ValueError("not a decodable image") from e
    raise RuntimeError("no JPEG decoder available (need cv2 or PIL)")


def _resize(img: np.ndarray, h: int, w: int) -> np.ndarray:
    if _HAVE_CV2:
        return cv2.resize(img, (w, h), interpolation=cv2.INTER_LINEAR)
    return np.asarray(Image.fromarray(img).resize((w, h), Image.BILINEAR))


def _resize_into(img: np.ndarray, size: int,
                 out: np.ndarray | None) -> np.ndarray:
    """Bilinear resize to size x size, into *out* when given (cv2 writes the
    pixels straight into the destination row — the zero-copy half of the
    slot-decode story)."""
    if out is None:
        return _resize(img, size, size)
    if _HAVE_CV2:
        cv2.resize(img, (size, size), dst=out,
                   interpolation=cv2.INTER_LINEAR)
    else:
        out[:] = _resize(img, size, size)
    return out


def _flip_h(dst: np.ndarray, out: np.ndarray | None) -> np.ndarray:
    """Horizontal flip; in place (cv2.flip supports src==dst) on the slot
    path, a fresh contiguous mirror otherwise — values identical."""
    if out is None:
        return np.ascontiguousarray(dst[:, ::-1])
    if _HAVE_CV2:
        cv2.flip(dst, 1, dst=dst)
    else:
        dst[:] = dst[:, ::-1].copy()
    return dst


def center_crop_resize(img: np.ndarray, size: int,
                       *, resize_shorter: int | None = None) -> np.ndarray:
    """Eval transform: resize shorter side (default size*1.15), center crop."""
    shorter = resize_shorter or int(size * 1.15)
    h, w = img.shape[:2]
    scale = shorter / min(h, w)
    img = _resize(img, max(size, round(h * scale)), max(size, round(w * scale)))
    h, w = img.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return img[top: top + size, left: left + size]


def sample_rrc_geometry(h: int, w: int, rng: np.random.Generator,
                        *, scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3)
                        ) -> tuple[int, int, int, int]:
    """(top, left, crop_h, crop_w) of an Inception-style random area/aspect
    crop in (h, w) coordinates; falls back to the center square. Pure RNG +
    arithmetic — the full-scale and reduced-scale decode paths both sample
    here in FULL-resolution coordinates, so their random streams (and
    therefore checkpoint-resume determinism) are identical."""
    area = h * w
    for _ in range(10):
        target = area * rng.uniform(*scale)
        log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(log_r)
        cw = round(np.sqrt(target * ar))
        ch = round(np.sqrt(target / ar))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            return top, left, ch, cw
    side = min(h, w)
    return (h - side) // 2, (w - side) // 2, side, side


def random_resized_crop(img: np.ndarray, size: int, rng: np.random.Generator,
                        *, scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3),
                        out: np.ndarray | None = None) -> np.ndarray:
    """Train transform: Inception-style random area/aspect crop → size×size,
    plus a horizontal flip coin. With *out*, the result lands in the given
    row (bit-identical values to the allocating path)."""
    h, w = img.shape[:2]
    top, left, ch, cw = sample_rrc_geometry(h, w, rng, scale=scale,
                                            ratio=ratio)
    dst = _resize_into(img[top: top + ch, left: left + cw], size, out)
    if rng.random() < 0.5:
        return _flip_h(dst, out)
    return np.ascontiguousarray(dst) if out is None else dst


def _scale_crop(top: int, left: int, ch: int, cw: int,
                fh: int, fw: int, rh: int, rw: int
                ) -> tuple[int, int, int, int]:
    """Map a full-resolution crop rectangle onto a reduced decode of actual
    shape (rh, rw) (libjpeg reduced sizes are ceil(dim/d), so the exact
    ratio comes from the decoded image, not the nominal denominator).
    Clamped non-empty."""
    sy, sx = rh / fh, rw / fw
    r0 = min(int(round(top * sy)), rh - 1)
    c0 = min(int(round(left * sx)), rw - 1)
    r1 = max(r0 + 1, min(int(round((top + ch) * sy)), rh))
    c1 = max(c0 + 1, min(int(round((left + cw) * sx)), rw))
    return r0, c0, r1 - r0, c1 - c0


def make_train_transform(size: int, *, reduced_scale: bool = True,
                         scale: tuple[float, float] = (0.08, 1.0),
                         ratio: tuple[float, float] = (3 / 4, 4 / 3)
                         ) -> Callable[..., np.ndarray]:
    """Transform(jpeg_bytes, rng, out=None) -> size×size×3 uint8.

    With *reduced_scale*, the crop rectangle is sampled FIRST (in full-res
    coordinates from the SOF header's dimensions — identical RNG stream to
    the full path), then the largest decode denominator at which that crop
    still covers the size×size target is chosen (:func:`reduced_denom` on
    the CROP dims: a crop that would land below the target at 1/d must not
    be upscaled from a reduced decode) and the rectangle is rescaled onto
    the reduced image. Non-JPEG members (no SOF) ride the full path."""

    def tf(data, rng: np.random.Generator,
           out: np.ndarray | None = None) -> np.ndarray:
        dims = parse_jpeg_dims(data) if reduced_scale else None
        if dims is None:
            return random_resized_crop(decode_jpeg(data), size, rng,
                                       scale=scale, ratio=ratio, out=out)
        fh, fw = dims
        top, left, ch, cw = sample_rrc_geometry(fh, fw, rng, scale=scale,
                                                ratio=ratio)
        denom = reduced_denom(ch, cw, size)
        if denom == 1:
            img = decode_jpeg(data)
            r0, c0, rch, rcw = top, left, ch, cw
        else:
            img = decode_jpeg(data, reduced=denom)
            global_stats.add(f"decode_reduced_hits_{denom}")
            r0, c0, rch, rcw = _scale_crop(top, left, ch, cw, fh, fw,
                                           img.shape[0], img.shape[1])
        dst = _resize_into(img[r0: r0 + rch, c0: c0 + rcw], size, out)
        if rng.random() < 0.5:
            return _flip_h(dst, out)
        return np.ascontiguousarray(dst) if out is None else dst

    return tf


class DecodePool:
    """Thread pool mapping decode+transform over batches of member payloads.

    Worker count is clamped to the host's core count (decode has no I/O
    waits to hide; extra threads only add GIL churn and context switches).

    cv2's internal threading is disabled while a pool lives (parallelism
    comes from this pool, not from within one image); the prior thread count
    is snapshotted at construction and restored in :meth:`close` so library
    users embedding a pipeline don't inherit a globally-mutated cv2.
    (Overlapping pool lifetimes restore whatever the LAST close sees —
    cv2 keeps one global setting, there is nothing finer to restore.)
    """

    def __init__(self, workers: int = 8):
        self._cv2_threads_prev: int | None = None
        if _HAVE_CV2:
            self._cv2_threads_prev = cv2.getNumThreads()
            cv2.setNumThreads(0)
        # decode is pure CPU (no I/O waits to hide), so workers beyond the
        # core count only thrash: measured 177ms vs 126ms per 64-image batch
        # at 8 vs 2 workers on a 2-core host — oversubscription cost ate
        # more than the reduced-scale decode win. Clamp, don't trust the
        # caller's guess about this host.
        workers = max(1, min(workers, os.cpu_count() or workers))
        self.workers = workers
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="strom-decode")
        self.decode_errors = 0
        self._err_lock = make_lock("app.jpeg_errs")
        self._closed = False

    @staticmethod
    def _worker_span(req):
        """The per-sample decode span: request-linked when the submitter
        was inside a traced request (ISSUE 8 — *req* is captured at SUBMIT
        time, because the worker thread has no contextvar of its own),
        else the plain ring span."""
        if req is not None:
            return req.span("decode.worker", cat="decode")
        return ring.span("decode.worker", cat="decode")

    def map(self, fn: Callable[..., np.ndarray],
            items: Iterable, *extra: Sequence) -> list[np.ndarray]:
        from strom.obs import request as _request

        req = _request.current()

        def traced(*a) -> np.ndarray:
            # worker span on the shared timeline: per-sample decode+transform
            # (the legacy allocating path; the slot path traces in _one_into)
            with self._worker_span(req):
                return fn(*a)

        return list(self._pool.map(traced, items, *extra))

    # -- direct-to-slot mapping --------------------------------------------
    def _one_into(self, fn: Callable[..., np.ndarray], item,
                  rng, row: np.ndarray, req=None) -> None:
        try:
            with self._worker_span(req):
                fn(item, rng, out=row)
        except ValueError:
            # per-sample failure policy: a truncated/corrupt member costs
            # one zero image and a counter bump, not the whole batch
            row[...] = 0
            with self._err_lock:
                self.decode_errors += 1
            global_stats.add("decode_errors")

    def submit_into(self, fn: Callable[..., np.ndarray], item, rng,
                    row: np.ndarray) -> concurrent.futures.Future:
        """One decode+transform job writing its result into *row* (the
        failure policy applied) — the unit the overlapped per-device
        delivery completes on."""
        from strom.obs import request as _request

        return self._pool.submit(self._one_into, fn, item, rng, row,
                                 _request.current())

    def map_into(self, fn: Callable[..., np.ndarray], items: Sequence,
                 rngs: Sequence, out: np.ndarray) -> np.ndarray:
        """Map fn(item, rng, out=out[i]) over the batch, every worker
        writing straight into its slot row. Returns *out*."""
        futs = [self.submit_into(fn, item, rng, out[i])
                for i, (item, rng) in enumerate(zip(items, rngs))]
        for f in futs:
            f.result()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=True)
        if _HAVE_CV2 and self._cv2_threads_prev is not None:
            cv2.setNumThreads(self._cv2_threads_prev)

    def __enter__(self) -> "DecodePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
