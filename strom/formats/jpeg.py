"""JPEG host decode + ImageNet-style transforms (SURVEY.md §7.2 step 7:
"JPEG (host decode worker pool)").

The engine lands compressed bytes in host slabs; decode runs on a thread pool
(cv2 releases the GIL inside imdecode, so threads scale) and the decoded
uint8 tensor is what gets `device_put`.  Keeping decode on host mirrors the
division of labor in the reference's consumer (PG-Strom decompresses on GPU —
strom-tpu instead keeps the TPU's MXU for the model and spends host cores on
decode; the "0 data-stall" overlap hides both).  Consumer: the ResNet-50
pipeline (BASELINE config #2, BASELINE.json:8).

Decode-path scheduling (ISSUE 2 tentpole):

- **Reduced-scale decode**: when the SAMPLED crop at 1/d scale still covers
  the target (d in 2/4/8; encoded dims read from the SOF header by
  :func:`parse_jpeg_dims` without decoding), decode via cv2's
  ``IMREAD_REDUCED_COLOR_{2,4,8}`` — libjpeg skips the corresponding IDCT
  work, up to 64x less at 1/8. The crop geometry is sampled in FULL-res
  coordinates BEFORE the denominator is chosen (RNG stream identical either
  way) and rescaled onto the reduced image; a crop that would need
  upscaling at 1/d rides a smaller d or the full path, so the knob is
  quality-neutral.  Counters: ``decode_reduced_hits_{2,4,8}``.
- **Direct-to-slot decode**: every transform takes an optional ``out=`` row
  (the final size x size x 3 destination inside a preallocated batch array)
  so the resize lands its pixels straight into the batch slot — no
  ``np.stack`` pass over the batch, no per-row output temporaries.
  :meth:`DecodePool.map_into` drives it; ``decode_slot_bytes`` counts the
  bytes delivered this way.
- **Per-sample failure policy** (slot path): a ``ValueError`` decode failure
  zeroes the row and bumps ``decode_errors`` instead of aborting the whole
  batch — one truncated JPEG in a million-sample epoch is data loss of one
  sample, not of the run.

Decode path v2 (ISSUE 12 tentpole; knobs ``decode_native`` /
``decode_fuse_runs`` / ``decode_roi`` / ``decode_cache``):

- **Native turbo bindings**: :data:`decode_native` resolves lazily to a
  ctypes wrapper over ``sc_jpeg_decode`` in strom/_core (libjpeg-turbo,
  build-probed — None when the headers are absent and every caller keeps
  the cv2 path). One C call decodes straight to RGB in a caller buffer:
  no cv2 per-call Mat setup, no BGR intermediate + cvtColor pass. Full
  decode is bit-exact against cv2 (both ride libjpeg-turbo's islow IDCT).
- **ROI / partial-MCU decode**: the crop rectangle is already fixed in
  full-res coordinates BEFORE decode (:func:`sample_rrc_geometry`), so the
  native path decodes only the crop's scanlines (``jpeg_skip_scanlines``)
  and iMCU columns (``jpeg_crop_scanline``), composing with the existing
  ``reduced_denom`` rule — RNG stream and quality semantics unchanged.
  Progressive (SOF2) members are routed to the full decode: the
  partial-scanline API silently produces wrong pixels on multi-scan files
  (:func:`parse_jpeg_info` carries the flag).
- **Fused-run dispatch**: :meth:`DecodePool.submit_run_into` decodes a run
  of samples per pool task, amortizing the per-task queue/contextvar/span
  overhead that dominates at ~1ms/image; run length auto-tunes from the
  pool's per-image decode-time EWMA (seeded off the same timing stream the
  ``decode_batch`` histogram records) and is capped for load balance.
- **Decoded-output cache**: with a :class:`~strom.formats.decoded_cache.
  DecodedCache` attached (pipelines build one over the hot cache when
  ``decode_cache`` is on), the transform serves post-decode full-frame
  pixels from RAM on repeat epochs and admits them on first decode —
  epoch >= 2 runs at predecoded speed (see decoded_cache.py for keying and
  budget accounting).
"""

from __future__ import annotations

import concurrent.futures
import ctypes
import os
import threading
import time
from typing import Callable, Iterable, NamedTuple, Sequence

import numpy as np

from strom.formats.decoded_cache import ServedFrame
from strom.obs.events import ring
from strom.utils.stats import global_stats
from strom.utils.locks import make_lock

try:
    import cv2

    _HAVE_CV2 = True
# stromlint: ignore[swallowed-exceptions] -- capability probe: cv2 can
# fail to import with non-ImportError (missing libGL raises OSError);
# either way the flag flips and every decode path branches on it
except Exception:  # pragma: no cover - cv2 is present in the target image
    _HAVE_CV2 = False

try:
    from PIL import Image
    import io

    _HAVE_PIL = True
# stromlint: ignore[swallowed-exceptions] -- capability probe, same
# contract as the cv2 probe above: the flag is the observable outcome
except Exception:  # pragma: no cover
    _HAVE_PIL = False


# -- SOF header parsing (no decode) -----------------------------------------

# SOF0..SOF15 carry frame dimensions, except DHT (C4), JPG (C8), DAC (CC)
_SOF_MARKERS = frozenset(range(0xC0, 0xD0)) - {0xC4, 0xC8, 0xCC}
# the multi-scan (progressive) subset: SOF2/6 (Huffman), SOF10/14
# (arithmetic). These decode fine at full/reduced scale, but the turbo
# partial-scanline API (jpeg_crop_scanline/jpeg_skip_scanlines) silently
# produces WRONG pixels on them — the router must send progressive members
# down the full-decode path (ISSUE 12 satellite).
_PROGRESSIVE_MARKERS = frozenset({0xC2, 0xC6, 0xCA, 0xCE})

# bench-JSON columns the decode-v2 phase set emits (cli._decode2_phases),
# single-sourced so the driver's per-arm copy loop (bench.py), the
# compare_rounds "decode v2" section and the bench_sentinel gates cannot
# drift from the producer — the same contract CACHE_BENCH_FIELDS enforces
DECODE2_FIELDS = (
    "decode_native_img_per_s",
    "decode_cv2_img_per_s",
    "decode_native_vs_cv2",
    "decode_native_imgs",
    "decode_native_fallbacks",
    "decode_fused_runs",
    "decode_fused_samples",
    "decode_roi_hits",
    "decode_roi_rows_skipped",
    "decode_cache_cold_img_per_s",
    "decode_cache_warm_img_per_s",
    "decode_cache_warm_vs_cold",
    "decode_cache_hits",
    "decode_cache_hit_bytes",
    "decode_cache_admitted_bytes",
)


class JpegInfo(NamedTuple):
    """SOF frame header facts: dimensions plus the progressive flag the
    ROI router branches on."""

    h: int
    w: int
    progressive: bool


def parse_jpeg_info(data: bytes | np.ndarray) -> JpegInfo | None:
    """Frame dims + progressive flag from a JPEG's SOF header, walking
    marker segments only — no entropy decode, no IDCT. Returns None for
    anything that is not parseable JPEG (PNG members, truncated headers):
    callers fall back to the full-scale decode path, which raises its own
    clear error."""
    if isinstance(data, np.ndarray):
        b = data.view(np.uint8).reshape(-1)
    else:
        b = np.frombuffer(data, dtype=np.uint8)
    n = b.shape[0]
    if n < 4 or b[0] != 0xFF or b[1] != 0xD8:
        return None
    i = 2
    while i + 3 < n:
        if b[i] != 0xFF:
            return None  # desynced: not walking marker segments anymore
        marker = int(b[i + 1])
        if marker == 0xFF:  # fill byte before a marker
            i += 1
            continue
        if marker == 0x01 or 0xD0 <= marker <= 0xD7:  # standalone TEM/RSTn
            i += 2
            continue
        if marker in (0xD9, 0xDA):  # EOI / SOS before any SOF: give up
            return None
        seg_len = (int(b[i + 2]) << 8) | int(b[i + 3])
        if seg_len < 2:
            return None
        if marker in _SOF_MARKERS:
            if i + 9 > n:
                return None
            h = (int(b[i + 5]) << 8) | int(b[i + 6])
            w = (int(b[i + 7]) << 8) | int(b[i + 8])
            if h <= 0 or w <= 0:
                return None
            return JpegInfo(h, w, marker in _PROGRESSIVE_MARKERS)
        i += 2 + seg_len
    return None


def parse_jpeg_dims(data: bytes | np.ndarray) -> tuple[int, int] | None:
    """(height, width) from a JPEG's SOF header (see
    :func:`parse_jpeg_info`, which also carries the progressive flag)."""
    info = parse_jpeg_info(data)
    return None if info is None else (info.h, info.w)


def reduced_denom(h: int, w: int, size: int) -> int:
    """Largest decode denominator d in (8, 4, 2) at which an (h, w) crop
    still covers the size×size target: min(h, w) >= size * d. Callers pass
    the CROP rectangle's dimensions, not the encoded image's — a reduced
    decode whose crop region lands below the target size would be bilinearly
    UPSCALED where the full path downsamples real pixels, a silent training
    -quality regression. 1 = decode full scale."""
    if size <= 0:
        return 1
    shorter = min(h, w)
    for d in (8, 4, 2):
        if shorter >= size * d:
            return d
    return 1


# -- native libjpeg-turbo binding (ISSUE 12 tentpole) ------------------------

# lazy resolution state: None = resolved-and-absent, callable = resolved;
# the sentinel means "not tried yet". The benign race (two threads both
# resolving) costs one duplicate CDLL of an already-built .so — no lock, so
# resolution can never entangle with the core build lock hierarchy.
_NATIVE_UNRESOLVED = object()
_native_decode: "Callable | None | object" = _NATIVE_UNRESOLVED


def _resolve_native() -> "Callable | None":
    """The decode_native callable, or None when the native binding is
    unavailable (no libjpeg-turbo headers at build time, no compiler, a
    poisoned include path, ...). Import of this module never builds or
    loads anything — the first *access* of ``decode_native`` does."""
    global _native_decode
    if _native_decode is not _NATIVE_UNRESOLVED:
        return _native_decode  # type: ignore[return-value]
    fn: "Callable | None" = None
    try:
        from strom._core.build import ensure_built

        lib = ctypes.CDLL(ensure_built())
        if lib.sc_jpeg_available() == 1:
            lib.sc_jpeg_decode.restype = ctypes.c_int
            lib.sc_jpeg_decode.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]

            def fn(data, *, reduced=1, roi=None, out=None,  # type: ignore[misc]
                   _lib=lib):
                return _decode_native_call(_lib, data, reduced=reduced,
                                           roi=roi, out=out)
    # stromlint: ignore[swallowed-exceptions] -- capability probe, same
    # contract as the cv2/PIL probes above: build/link/dlopen failure of
    # the OPTIONAL native path resolves to None and callers keep cv2
    except Exception:
        fn = None
    _native_decode = fn
    return fn


def native_available() -> bool:
    """True when :data:`decode_native` resolves to a live binding."""
    return _resolve_native() is not None


# horizontal widening for ROI decodes: jpeg_crop_scanline grants an
# iMCU-aligned superset, but fancy upsampling lacks context at the granted
# boundary — its left/rightmost output columns can differ from a full
# decode. Requesting 2 extra columns each side keeps the RETURNED rect
# strictly interior (where partial decode is bit-exact against full),
# except at true image edges, where full decode has no context either.
_ROI_X_MARGIN = 2


def _decode_native_call(lib, data, *, reduced: int = 1,
                        roi: "tuple[int, int, int, int] | None" = None,
                        out: "np.ndarray | None" = None) -> np.ndarray:
    """ctypes shim over ``sc_jpeg_decode``. With *roi* = (y, x, h, w) in
    SCALED (post-*reduced*) coordinates, decodes only the crop's scanlines
    / iMCU columns and returns exactly the requested (h, w, 3) rect (a view
    into a fresh decode buffer). Without, returns the full (scaled) frame,
    into *out* when given. Raises ValueError on anything undecodable —
    same contract as :func:`decode_jpeg`, so the pool's per-sample failure
    policy applies unchanged."""
    buf = np.frombuffer(data, dtype=np.uint8) \
        if isinstance(data, (bytes, bytearray, memoryview)) \
        else data.view(np.uint8).reshape(-1)
    if not buf.flags.c_contiguous:
        buf = np.ascontiguousarray(buf)
    info = parse_jpeg_info(buf)
    if info is None:
        raise ValueError("not a decodable image")
    oh, ow = -(-info.h // reduced), -(-info.w // reduced)
    got = (ctypes.c_int32 * 4)()
    if roi is None:
        dst = out
        if dst is None:
            dst = np.empty((oh, ow, 3), dtype=np.uint8)
        elif dst.shape != (oh, ow, 3) or dst.dtype != np.uint8 \
                or not dst.flags.c_contiguous:
            raise ValueError("out must be a C-contiguous uint8 array of "
                             f"shape {(oh, ow, 3)}")
        rc = lib.sc_jpeg_decode(buf.ctypes.data, buf.size, dst.ctypes.data,
                                dst.nbytes, ow * 3, reduced,
                                0, 0, 0, 0, got)
        if rc != 0 or (got[0], got[1]) != (oh, ow):
            raise ValueError(f"native jpeg decode failed (rc={rc})")
        return dst
    y, x, h, w = roi
    if not (0 <= y and 0 <= x and h > 0 and w > 0
            and y + h <= oh and x + w <= ow):
        raise ValueError(f"roi {roi} outside scaled frame {(oh, ow)}")
    rx = max(x - _ROI_X_MARGIN, 0)
    rw = min(x + w + _ROI_X_MARGIN, ow) - rx
    # granted width exceeds the request by at most one iMCU each side —
    # up to 32px with h_samp_factor 4 (4:1:1/4:1:0 chroma), so budget 62
    # extra columns; rows pack at the granted width (stride <= 0 in the
    # C ABI) and the capacity check there rejects anything wider
    flat = np.empty(h * (rw + 64) * 3, dtype=np.uint8)
    rc = lib.sc_jpeg_decode(buf.ctypes.data, buf.size, flat.ctypes.data,
                            flat.nbytes, 0, reduced, y, rx, h, rw, got)
    if rc != 0:
        raise ValueError(f"native jpeg roi decode failed (rc={rc})")
    gh, gw, gx0, _ = got
    img = flat[: gh * gw * 3].reshape(gh, gw, 3)
    return img[:, x - gx0: x - gx0 + w]


def __getattr__(name: str):
    """PEP 562: ``jpeg.decode_native`` resolves the native binding on first
    access (None when absent — the ISSUE 12 build-probe fallback contract)
    without import-time build cost."""
    if name == "decode_native":
        return _resolve_native()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def decode_jpeg(data: bytes | np.ndarray, *, reduced: int = 1) -> np.ndarray:
    """Decode JPEG/PNG bytes → HWC uint8 RGB array.

    *reduced* in (2, 4, 8) decodes JPEGs at 1/reduced scale (libjpeg
    skips the corresponding IDCT work); the caller owns rescaling any
    crop geometry onto the reduced image (:func:`make_train_transform`).
    """
    if _HAVE_CV2:
        flag = {1: cv2.IMREAD_COLOR,
                2: cv2.IMREAD_REDUCED_COLOR_2,
                4: cv2.IMREAD_REDUCED_COLOR_4,
                8: cv2.IMREAD_REDUCED_COLOR_8}[reduced]
        buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, memoryview)) \
            else data.view(np.uint8).reshape(-1)
        img = cv2.imdecode(buf, flag)
        if img is None:
            raise ValueError("not a decodable image")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if _HAVE_PIL:
        raw = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
        try:
            with Image.open(io.BytesIO(raw)) as im:
                if reduced > 1:
                    # draft mode: JPEG power-of-2 reduced decode, same trick
                    im.draft("RGB", (max(1, im.width // reduced),
                                     max(1, im.height // reduced)))
                return np.asarray(im.convert("RGB"))
        except Exception as e:  # UnidentifiedImageError etc. → one contract
            raise ValueError("not a decodable image") from e
    raise RuntimeError("no JPEG decoder available (need cv2 or PIL)")


def _resize(img: np.ndarray, h: int, w: int) -> np.ndarray:
    if _HAVE_CV2:
        return cv2.resize(img, (w, h), interpolation=cv2.INTER_LINEAR)
    return np.asarray(Image.fromarray(img).resize((w, h), Image.BILINEAR))


def _resize_into(img: np.ndarray, size: int,
                 out: np.ndarray | None) -> np.ndarray:
    """Bilinear resize to size x size, into *out* when given (cv2 writes the
    pixels straight into the destination row — the zero-copy half of the
    slot-decode story)."""
    if out is None:
        return _resize(img, size, size)
    if _HAVE_CV2:
        cv2.resize(img, (size, size), dst=out,
                   interpolation=cv2.INTER_LINEAR)
    else:
        out[:] = _resize(img, size, size)
    return out


def _flip_h(dst: np.ndarray, out: np.ndarray | None) -> np.ndarray:
    """Horizontal flip; in place (cv2.flip supports src==dst) on the slot
    path, a fresh contiguous mirror otherwise — values identical."""
    if out is None:
        return np.ascontiguousarray(dst[:, ::-1])
    if _HAVE_CV2:
        cv2.flip(dst, 1, dst=dst)
    else:
        dst[:] = dst[:, ::-1].copy()
    return dst


def center_crop_resize(img: np.ndarray, size: int,
                       *, resize_shorter: int | None = None) -> np.ndarray:
    """Eval transform: resize shorter side (default size*1.15), center crop."""
    shorter = resize_shorter or int(size * 1.15)
    h, w = img.shape[:2]
    scale = shorter / min(h, w)
    img = _resize(img, max(size, round(h * scale)), max(size, round(w * scale)))
    h, w = img.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return img[top: top + size, left: left + size]


def sample_rrc_geometry(h: int, w: int, rng: np.random.Generator,
                        *, scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3)
                        ) -> tuple[int, int, int, int]:
    """(top, left, crop_h, crop_w) of an Inception-style random area/aspect
    crop in (h, w) coordinates; falls back to the center square. Pure RNG +
    arithmetic — the full-scale and reduced-scale decode paths both sample
    here in FULL-resolution coordinates, so their random streams (and
    therefore checkpoint-resume determinism) are identical."""
    area = h * w
    for _ in range(10):
        target = area * rng.uniform(*scale)
        log_r = rng.uniform(np.log(ratio[0]), np.log(ratio[1]))
        ar = np.exp(log_r)
        cw = round(np.sqrt(target * ar))
        ch = round(np.sqrt(target / ar))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            return top, left, ch, cw
    side = min(h, w)
    return (h - side) // 2, (w - side) // 2, side, side


def random_resized_crop(img: np.ndarray, size: int, rng: np.random.Generator,
                        *, scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3),
                        out: np.ndarray | None = None) -> np.ndarray:
    """Train transform: Inception-style random area/aspect crop → size×size,
    plus a horizontal flip coin. With *out*, the result lands in the given
    row (bit-identical values to the allocating path)."""
    h, w = img.shape[:2]
    top, left, ch, cw = sample_rrc_geometry(h, w, rng, scale=scale,
                                            ratio=ratio)
    dst = _resize_into(img[top: top + ch, left: left + cw], size, out)
    if rng.random() < 0.5:
        return _flip_h(dst, out)
    return np.ascontiguousarray(dst) if out is None else dst


def _scale_crop(top: int, left: int, ch: int, cw: int,
                fh: int, fw: int, rh: int, rw: int
                ) -> tuple[int, int, int, int]:
    """Map a full-resolution crop rectangle onto a reduced decode of actual
    shape (rh, rw) (libjpeg reduced sizes are ceil(dim/d), so the exact
    ratio comes from the decoded image, not the nominal denominator).
    Clamped non-empty."""
    sy, sx = rh / fh, rw / fw
    r0 = min(int(round(top * sy)), rh - 1)
    c0 = min(int(round(left * sx)), rw - 1)
    r1 = max(r0 + 1, min(int(round((top + ch) * sy)), rh))
    c1 = max(c0 + 1, min(int(round((left + cw) * sx)), rw))
    return r0, c0, r1 - r0, c1 - c0


def make_train_transform(size: int, *, reduced_scale: bool = True,
                         scale: tuple[float, float] = (0.08, 1.0),
                         ratio: tuple[float, float] = (3 / 4, 4 / 3),
                         native: bool = True,
                         roi: bool = True,
                         dcache=None) -> Callable[..., np.ndarray]:
    """Transform(jpeg_bytes, rng, out=None, ckey=None) -> size×size×3 uint8.

    With *reduced_scale*, the crop rectangle is sampled FIRST (in full-res
    coordinates from the SOF header's dimensions — identical RNG stream to
    the full path), then the largest decode denominator at which that crop
    still covers the size×size target is chosen (:func:`reduced_denom` on
    the CROP dims: a crop that would land below the target at 1/d must not
    be upscaled from a reduced decode) and the rectangle is rescaled onto
    the reduced image. Non-JPEG members (no SOF) ride the full path.

    Decode path v2 (ISSUE 12): with *native* (and the binding built),
    decode runs through :data:`decode_native` — bit-exact against cv2 for
    full/reduced decode, falling back to cv2 per-sample on any native
    error. With *roi* on top, only the crop's scanlines/iMCU columns are
    decoded (`decode_roi_hits` / `decode_roi_rows_skipped`), skipped for
    progressive members and crops spanning the frame. With *dcache* (a
    :class:`strom.formats.decoded_cache.DecodedCache`) and a *ckey*, the
    decoded FULL frame is served from / admitted to the hot cache, so a
    repeat epoch pays only crop+resize — note this serves full-fidelity
    pixels where the reduced path would have approximated, identical to
    the ``reduced_scale=False`` path. Every knob off reproduces the
    pre-v2 transform bit-identically."""

    def tf(data, rng: np.random.Generator,
           out: np.ndarray | None = None, ckey=None) -> np.ndarray:
        if isinstance(data, ServedFrame):
            # plan-time decoded-cache hit (ISSUE 13 satellite): the image
            # member was never gathered — *data* IS the pinned full frame.
            # Same RNG draws as the in-transform cached branch below
            # (geometry, then one flip coin), so resume determinism and
            # the bit-identity contract hold whichever path a sample takes.
            img = data.img
            try:
                fh, fw = img.shape[:2]
                top, left, ch, cw = sample_rrc_geometry(
                    fh, fw, rng, scale=scale, ratio=ratio)
                dst = _resize_into(img[top: top + ch, left: left + cw],
                                   size, out)
            finally:
                data.release()
            if rng.random() < 0.5:
                return _flip_h(dst, out)
            return np.ascontiguousarray(dst) if out is None else dst
        info = parse_jpeg_info(data) if (reduced_scale or native
                                         or dcache is not None) else None
        if info is None:
            return random_resized_crop(decode_jpeg(data), size, rng,
                                       scale=scale, ratio=ratio, out=out)
        fh, fw = info.h, info.w
        top, left, ch, cw = sample_rrc_geometry(fh, fw, rng, scale=scale,
                                                ratio=ratio)

        def finish(dst):
            # one flip draw in every path, AFTER the resize — the RNG
            # stream is identical across full/reduced/native/roi/cached
            if rng.random() < 0.5:
                return _flip_h(dst, out)
            return np.ascontiguousarray(dst) if out is None else dst

        nat = _resolve_native() if native else None
        # decoded-output cache (front 4): serve post-decode pixels from
        # RAM; on a miss decode the FULL frame (cache fidelity = full-res
        # pixels; forgoing ROI/reduced on the admitting pass is what buys
        # epoch >= 2 the predecoded-speed serve)
        if dcache is not None and ckey is not None and dcache.enabled:
            hit = dcache.get(ckey, fh, fw)
            if hit is not None:
                img, pin = hit
                try:
                    dst = _resize_into(img[top: top + ch, left: left + cw],
                                       size, out)
                finally:
                    dcache.release(pin)
                return finish(dst)
            img = None
            if nat is not None:
                try:
                    img = nat(data)
                    global_stats.add("decode_native_imgs")
                except ValueError:
                    global_stats.add("decode_native_fallbacks")
            if img is None:
                img = decode_jpeg(data)
            dcache.offer(ckey, img)
            return finish(_resize_into(
                img[top: top + ch, left: left + cw], size, out))

        denom = reduced_denom(ch, cw, size) if reduced_scale else 1
        if denom == 1:
            rh, rw = fh, fw
            r0, c0, rch, rcw = top, left, ch, cw
        else:
            # libjpeg reduced sizes are ceil(dim/d) — computable without
            # decoding, so the ROI path can plan scaled coordinates upfront
            rh, rw = -(-fh // denom), -(-fw // denom)
            r0, c0, rch, rcw = _scale_crop(top, left, ch, cw, fh, fw,
                                           rh, rw)
        img = None
        if nat is not None:
            # ROI engages when partial decode actually skips work; a crop
            # spanning the frame rides the plain (full/reduced) decode.
            # Progressive members never take ROI (wrong pixels — see
            # parse_jpeg_info); full/reduced native decode handles them.
            roi_ok = roi and not info.progressive \
                and (rch < rh or rcw < rw)
            try:
                if roi_ok:
                    rect = nat(data, reduced=denom,
                               roi=(r0, c0, rch, rcw))
                    global_stats.add("decode_native_imgs")
                    global_stats.add("decode_roi_hits")
                    global_stats.add("decode_roi_rows_skipped", rh - rch)
                    if denom > 1:
                        global_stats.add(f"decode_reduced_hits_{denom}")
                    return finish(_resize_into(rect, size, out))
                img = nat(data, reduced=denom)
                global_stats.add("decode_native_imgs")
            except ValueError:
                # per-sample fallback: a member the native path rejects
                # (exotic colorspace, arithmetic coding build, truncation
                # the two libraries tolerate differently) rides cv2 — the
                # counter keeps "native silently off" diagnosable
                global_stats.add("decode_native_fallbacks")
                img = None
        if img is None:
            img = decode_jpeg(data, reduced=denom)
        if denom > 1:
            global_stats.add(f"decode_reduced_hits_{denom}")
        dst = _resize_into(img[r0: r0 + rch, c0: c0 + rcw], size, out)
        return finish(dst)

    return tf


class DecodePool:
    """Thread pool mapping decode+transform over batches of member payloads.

    Worker count is clamped to the host's core count (decode has no I/O
    waits to hide; extra threads only add GIL churn and context switches).

    cv2's internal threading is disabled while a pool lives (parallelism
    comes from this pool, not from within one image); the prior thread count
    is snapshotted at construction and restored in :meth:`close` so library
    users embedding a pipeline don't inherit a globally-mutated cv2.
    (Overlapping pool lifetimes restore whatever the LAST close sees —
    cv2 keeps one global setting, there is nothing finer to restore.)

    Fused-run dispatch (ISSUE 12 tentpole, *fuse_runs*): one pool task
    decodes a RUN of samples, amortizing the per-task future/queue/
    contextvar/span overhead that dominates at ~1ms images. Run length
    auto-tunes from a per-image decode-time EWMA the fused workers
    maintain (the same timing stream the ``decode_batch`` histogram
    aggregates) against a fixed per-task work target, capped so every
    worker still sees >= 2 runs per batch. ``fuse_runs=False`` (or run
    length 1) keeps the one-task-per-sample shape bit-identically.
    """

    # per-task decode-work target: enough decode per dispatch that the
    # ~tens-of-us task overhead amortizes below ~2%, small enough that
    # run granularity doesn't serialize a batch's tail
    _RUN_TARGET_US = 4000.0

    def __init__(self, workers: int = 8, *, fuse_runs: bool = True):
        self._cv2_threads_prev: int | None = None
        if _HAVE_CV2:
            self._cv2_threads_prev = cv2.getNumThreads()
            cv2.setNumThreads(0)
        # decode is pure CPU (no I/O waits to hide), so workers beyond the
        # core count only thrash: measured 177ms vs 126ms per 64-image batch
        # at 8 vs 2 workers on a 2-core host — oversubscription cost ate
        # more than the reduced-scale decode win. Clamp, don't trust the
        # caller's guess about this host.
        workers = max(1, min(workers, os.cpu_count() or workers))
        self.workers = workers
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="strom-decode")
        self.decode_errors = 0
        self._err_lock = make_lock("app.jpeg_errs")
        self.fuse_runs = fuse_runs
        # live per-task work target (ISSUE 19 satellite): instance-level
        # so the autotuner's decode_run_target_us knob steers run
        # granularity on a running pool; class default = the measured
        # sweet spot
        self.run_target_us = float(self._RUN_TARGET_US)
        # EWMA of per-image decode+transform micros, seeded at 1ms (the
        # measured pre-v2 cost on the bench host); updated by fused runs
        self._img_us = 1000.0
        self._closed = False

    @staticmethod
    def _worker_span(req):
        """The per-sample decode span: request-linked when the submitter
        was inside a traced request (ISSUE 8 — *req* is captured at SUBMIT
        time, because the worker thread has no contextvar of its own),
        else the plain ring span — or None when the ring is disabled
        (ISSUE 12 satellite: span construction is pure overhead with
        telemetry off, and the fused-run micro numbers must not pay it)."""
        if req is not None:
            return req.span("decode.worker", cat="decode")
        if not ring.enabled:
            return None
        return ring.span("decode.worker", cat="decode")

    def run_size(self, n: int) -> int:
        """Fused-run length for an *n*-sample batch: enough samples per
        task to hit the work target at the current per-image EWMA, capped
        for load balance. 1 = fusing off (the pre-v2 dispatch shape)."""
        if not self.fuse_runs or n <= 1:
            return 1
        with self._err_lock:
            per_img = self._img_us
        want = int(self.run_target_us / max(per_img, 1.0))
        cap = -(-n // (self.workers * 2))
        return max(1, min(want, cap))

    def map(self, fn: Callable[..., np.ndarray],
            items: Iterable, *extra: Sequence) -> list[np.ndarray]:
        from strom.obs import request as _request

        req = _request.current()

        def traced(*a) -> np.ndarray:
            # worker span on the shared timeline: per-sample decode+transform
            # (the legacy allocating path; the slot path traces in
            # _one_sample); None = telemetry off, skip the span entirely
            cm = self._worker_span(req)
            if cm is None:
                return fn(*a)
            with cm:
                return fn(*a)

        return list(self._pool.map(traced, items, *extra))

    # -- direct-to-slot mapping --------------------------------------------
    def _call(self, fn: Callable[..., np.ndarray], item, rng,
              row: np.ndarray, ckey) -> None:
        if ckey is None:
            fn(item, rng, out=row)
        else:
            fn(item, rng, out=row, ckey=ckey)

    def _one_sample(self, fn: Callable[..., np.ndarray], item, rng,
                    row: np.ndarray, req, ckey) -> None:
        try:
            cm = self._worker_span(req)
            if cm is None:  # telemetry off: no span object, no now_us
                self._call(fn, item, rng, row, ckey)
            else:
                with cm:
                    self._call(fn, item, rng, row, ckey)
        except ValueError:
            # per-sample failure policy: a truncated/corrupt member costs
            # one zero image and a counter bump, not the whole batch
            row[...] = 0
            with self._err_lock:
                self.decode_errors += 1
            global_stats.add("decode_errors")

    def _one_into(self, fn: Callable[..., np.ndarray], item,
                  rng, row: np.ndarray, req=None, ckey=None) -> None:
        self._one_sample(fn, item, rng, row, req, ckey)

    def _run_into(self, fn: Callable[..., np.ndarray], items: Sequence,
                  rngs: Sequence, rows: Sequence, req, ckeys) -> None:
        """One pool task decoding a run of samples (the failure policy per
        sample, exactly like the single-sample path). Feeds the per-image
        EWMA :meth:`run_size` tunes from."""
        t0 = time.perf_counter()
        for i, (item, rng) in enumerate(zip(items, rngs)):
            self._one_sample(fn, item, rng, rows[i], req,
                             None if ckeys is None else ckeys[i])
        n = len(items)
        per_img = (time.perf_counter() - t0) * 1e6 / max(n, 1)
        with self._err_lock:
            self._img_us += 0.2 * (per_img - self._img_us)
        if n > 1:
            global_stats.add("decode_fused_runs")
            global_stats.add("decode_fused_samples", n)

    def submit_into(self, fn: Callable[..., np.ndarray], item, rng,
                    row: np.ndarray, ckey=None) -> concurrent.futures.Future:
        """One decode+transform job writing its result into *row* (the
        failure policy applied) — the unit the overlapped per-device
        delivery completes on."""
        from strom.obs import request as _request

        return self._pool.submit(self._one_into, fn, item, rng, row,
                                 _request.current(), ckey)

    def submit_run_into(self, fn: Callable[..., np.ndarray],
                        items: Sequence, rngs: Sequence, rows: Sequence,
                        ckeys: "Sequence | None" = None
                        ) -> concurrent.futures.Future:
        """A fused run: ONE pool task decoding items[i] into rows[i] for
        the whole run (ISSUE 12 tentpole) — per-task dispatch overhead is
        paid once per run instead of once per sample."""
        from strom.obs import request as _request

        return self._pool.submit(self._run_into, fn, items, rngs, rows,
                                 _request.current(), ckeys)

    def map_into(self, fn: Callable[..., np.ndarray], items: Sequence,
                 rngs: Sequence, out: np.ndarray,
                 ckeys: "Sequence | None" = None) -> np.ndarray:
        """Map fn(item, rng, out=out[i]) over the batch, every worker
        writing straight into its slot row; contiguous runs fuse into one
        task each per :meth:`run_size`. Returns *out*."""
        n = len(items)
        run = self.run_size(n)
        if run <= 1:
            futs = [self.submit_into(fn, item, rng, out[i],
                                     None if ckeys is None else ckeys[i])
                    for i, (item, rng) in enumerate(zip(items, rngs))]
        else:
            futs = [self.submit_run_into(
                        fn, items[i: i + run], rngs[i: i + run],
                        [out[j] for j in range(i, min(i + run, n))],
                        None if ckeys is None else ckeys[i: i + run])
                    for i in range(0, n, run)]
        for f in futs:
            f.result()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=True)
        if _HAVE_CV2 and self._cv2_threads_prev is not None:
            cv2.setNumThreads(self._cv2_threads_prev)

    def __enter__(self) -> "DecodePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
