"""Parquet columnar reads: column-chunk byte ranges through the engine,
decode via pyarrow (SURVEY.md §7.2 step 7: "Parquet (column-chunk range reads
via metadata footer)").

This mirrors the reference's flagship consumer pattern — PG-Strom scans
Parquet-ish columnar blocks straight from NVMe into the accelerator
(SURVEY.md §0.5) — re-cut for TPU: the *selected columns'* compressed chunks
are gather-read (O_DIRECT, RAID0, sharded fan-out all apply), decoded on
host, and only the projected/filtered table ever reaches HBM.  Consumer: the
Parquet scan fan-out pipeline (BASELINE config #5, BASELINE.json:11).
"""

from __future__ import annotations

import bisect
import os
from typing import TYPE_CHECKING, Sequence

import numpy as np

from strom.delivery.extents import Extent, ExtentList
from strom.utils.locks import make_lock

if TYPE_CHECKING:
    import pyarrow as pa

    from strom.delivery.core import StromContext


class _RangeCache:
    """Sorted, non-overlapping (offset → bytes) ranges of one file."""

    def __init__(self) -> None:
        self._offsets: list[int] = []
        self._bufs: list[np.ndarray] = []
        self.miss_bytes = 0

    def insert(self, offset: int, buf: np.ndarray) -> None:
        i = bisect.bisect_left(self._offsets, offset)
        self._offsets.insert(i, offset)
        self._bufs.insert(i, buf)

    def read(self, offset: int, length: int, fallback) -> bytes:
        """Serve [offset, +length), stitching cached ranges; gaps fall back to
        *fallback(offset, length) -> bytes* on the real source (counted as
        miss bytes)."""
        out = bytearray(length)
        pos = offset
        end = offset + length
        while pos < end:
            i = bisect.bisect_right(self._offsets, pos) - 1
            hit = None
            if i >= 0:
                ro, rb = self._offsets[i], self._bufs[i]
                if ro <= pos < ro + len(rb):
                    hit = rb[pos - ro: pos - ro + (end - pos)]
            if hit is not None and len(hit) > 0:
                out[pos - offset: pos - offset + len(hit)] = hit.tobytes()
                pos += len(hit)
                continue
            # miss: read up to the next cached range (or to end)
            j = bisect.bisect_right(self._offsets, pos)
            stop = min(end, self._offsets[j]) if j < len(self._offsets) else end
            data = fallback(pos, stop - pos)
            if not data:
                return bytes(out[: pos - offset])  # EOF
            out[pos - offset: pos - offset + len(data)] = data
            self.miss_bytes += len(data)
            pos += len(data)
        return bytes(out)


class RangeCachedFile:
    """File-like object over a _RangeCache; what pyarrow decodes from.

    pyarrow wraps this in a PythonFile; all reads it issues for the footer and
    the selected column chunks are served from engine-prefetched ranges."""

    def __init__(self, path: str, cache: _RangeCache, *,
                 ctx: "StromContext | None" = None):
        """Misses pread the real file — or, when *ctx* aliases *path* to a
        striped set (``register_striped``), gather through the engine."""
        self._cache = cache
        striped = ctx.striped_source(path) if ctx is not None else None
        if striped is not None:
            from strom.delivery.core import source_size

            self._fd = -1
            self._size = source_size(striped)
            self._fallback = lambda off, ln: ctx.pread(
                striped, off, min(ln, self._size - off)).tobytes()
        else:
            self._fd = os.open(path, os.O_RDONLY)
            self._size = os.fstat(self._fd).st_size
            self._fallback = lambda off, ln: os.pread(self._fd, ln, off)
        self._pos = 0
        self._closed = False

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self._size - self._pos
        n = max(0, min(n, self._size - self._pos))
        data = self._cache.read(self._pos, n, self._fallback)
        self._pos += len(data)
        return data

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            self._pos = offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        elif whence == os.SEEK_END:
            self._pos = self._size + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def flush(self) -> None:
        pass

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def miss_bytes(self) -> int:
        return self._cache.miss_bytes

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._fd >= 0:
                os.close(self._fd)


# --- direct PLAIN-page decode (the I/O-bound scan path) ---------------------
#
# For uncompressed, PLAIN-encoded numeric column chunks the bytes on disk ARE
# the values (modulo small thrift page headers and an all-ones definition-
# level run), so decode can be np.frombuffer over the engine's slab — zero
# copies — instead of the pyarrow PythonFile round trip (range-cache stitch,
# arrow buffer copy, to_numpy). This is what makes config #5's selected-GB/s
# an I/O measurement rather than a codec one (VERDICT.md r4 next #1; the
# reference's scans stream straight from NVMe — SURVEY.md §0.5, UNVERIFIED).
# Anything the fast path can't prove safe (compression, dictionary pages,
# nulls, non-numeric types, v2 pages, encodings != PLAIN) falls back to the
# pyarrow path; tests cross-check both against each other.

_PHYSICAL_NP = {
    "INT32": np.dtype("<i4"),
    "INT64": np.dtype("<i8"),
    "FLOAT": np.dtype("<f4"),
    "DOUBLE": np.dtype("<f8"),
}


class _PlainDecodeUnsupported(Exception):
    """Chunk needs the pyarrow fallback (not an error)."""


def _plain_logical_ok(col_schema, physical_type: str) -> bool:
    """True iff the column's logical/converted annotation is absent or
    exactly the physical numpy meaning, so frombuffer over the raw bytes
    returns what pyarrow would. A uint32 column is physically INT32: raw
    decode would silently reinterpret 2147483653 as -2147483643; date32/
    timestamp would return raw ints where pyarrow returns datetime64
    (ADVICE.md r5 high). Only NONE and a signed INT annotation of exactly
    the physical width are provably equivalent."""
    lt = getattr(col_schema, "logical_type", None)
    kind = (getattr(lt, "type", None) or "NONE").upper()
    conv = (getattr(col_schema, "converted_type", None) or "NONE").upper()
    if kind in ("NONE", "UNDEFINED"):
        # legacy files may carry only a converted_type (e.g. UINT_32)
        return conv == "NONE"
    if kind == "INT":
        import json

        try:
            d = json.loads(lt.to_json())
        except (TypeError, ValueError, AttributeError):
            return False
        width = {"INT32": 32, "INT64": 64}.get(physical_type)
        return (width is not None and d.get("bitWidth") == width
                and d.get("isSigned") is True)
    return False


def _uvarint(buf, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 63:
            raise _PlainDecodeUnsupported("varint overflow")


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _thrift_skip(buf, pos: int, ftype: int) -> int:
    """Skip one thrift compact value of *ftype*; returns new pos."""
    if ftype in (1, 2):  # BOOLEAN_TRUE / BOOLEAN_FALSE: value is in the type
        return pos
    if ftype == 3:  # byte
        return pos + 1
    if ftype in (4, 5, 6):  # i16/i32/i64: zigzag varint
        _, pos = _uvarint(buf, pos)
        return pos
    if ftype == 7:  # double
        return pos + 8
    if ftype == 8:  # binary/string
        n, pos = _uvarint(buf, pos)
        return pos + n
    if ftype in (9, 10):  # list/set
        head = buf[pos]
        pos += 1
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size, pos = _uvarint(buf, pos)
        if etype in (1, 2):
            # bool ELEMENTS are one byte each (0x01/0x02) — unlike bool
            # struct FIELDS, whose value rides the field-type nibble; the
            # ftype 1/2 early-out above is the field case and must not be
            # reused here or the walk desynchronizes
            return pos + size
        for _ in range(size):
            pos = _thrift_skip(buf, pos, etype)
        return pos
    if ftype == 12:  # struct
        while True:
            fb = buf[pos]
            pos += 1
            if fb == 0:
                return pos
            if fb >> 4 == 0:  # long-form field id: zigzag varint follows
                _, pos = _uvarint(buf, pos)
            pos = _thrift_skip(buf, pos, fb & 0x0F)
    raise _PlainDecodeUnsupported(f"thrift type {ftype}")


def _thrift_struct(buf, pos: int) -> tuple[dict, int]:
    """Parse a thrift compact struct into {field_id: value}; nested structs
    recurse, everything else is skipped or decoded as a zigzag int. Only the
    field shapes PageHeader uses are decoded."""
    out: dict = {}
    fid = 0
    while True:
        fb = buf[pos]
        pos += 1
        if fb == 0:
            return out, pos
        delta = fb >> 4
        ftype = fb & 0x0F
        if delta:
            fid += delta
        else:
            sv, pos = _uvarint(buf, pos)
            fid = _zigzag(sv)
        if ftype in (1, 2):
            out[fid] = ftype == 1
        elif ftype in (4, 5, 6):
            sv, pos = _uvarint(buf, pos)
            out[fid] = _zigzag(sv)
        elif ftype == 12:
            out[fid], pos = _thrift_struct(buf, pos)
        else:
            pos = _thrift_skip(buf, pos, ftype)
            out[fid] = None
    # unreachable


def _defs_all_present(buf, num_values: int) -> bool:
    """True iff an RLE/bit-packed (bit width 1) definition-level block is all
    ones — i.e. no nulls. *buf* is the block AFTER its 4-byte length prefix."""
    pos = 0
    seen = 0
    while seen < num_values and pos < len(buf):
        header, pos = _uvarint(buf, pos)
        if header & 1:  # bit-packed run: (header>>1) groups of 8 values
            n_groups = header >> 1
            n_bytes = n_groups  # bit width 1: one byte per 8 values
            take = min(n_groups * 8, num_values - seen)
            full, rem = divmod(take, 8)
            block = buf[pos: pos + n_bytes]
            if any(b != 0xFF for b in block[:full]):
                return False
            if rem and (block[full] & ((1 << rem) - 1)) != (1 << rem) - 1:
                return False
            pos += n_bytes
            seen += take
        else:  # RLE run: value repeated (header>>1) times, 1 byte at width 1
            count = header >> 1
            if count == 0:
                return False  # malformed; be conservative
            if buf[pos] != 1:
                return False
            pos += 1
            seen += min(count, num_values - seen)
    return seen >= num_values


def decode_plain_pages(col_meta, col_schema, buf: np.ndarray
                       ) -> list[np.ndarray]:
    """Decode one uncompressed PLAIN numeric column chunk into per-page
    numpy VIEWS over its raw bytes (zero copies; the page list is the
    chunk's row order).

    *col_meta*: pyarrow ColumnChunkMetaData; *col_schema*: the matching
    ParquetColumnSchema (for max def/rep levels); *buf*: the chunk's bytes
    (np.uint8, offset 0 = the chunk's first page header).
    Raises _PlainDecodeUnsupported when any page needs the pyarrow path.
    """
    if col_meta.compression != "UNCOMPRESSED":
        raise _PlainDecodeUnsupported(col_meta.compression)
    if col_meta.dictionary_page_offset is not None:
        raise _PlainDecodeUnsupported("dictionary-encoded")
    np_dtype = _PHYSICAL_NP.get(col_meta.physical_type)
    if np_dtype is None:
        raise _PlainDecodeUnsupported(col_meta.physical_type)
    if not _plain_logical_ok(col_schema, col_meta.physical_type):
        raise _PlainDecodeUnsupported(
            f"logical type {col_schema.logical_type} != physical "
            f"{col_meta.physical_type}")
    if col_schema.max_repetition_level:
        raise _PlainDecodeUnsupported("nested (repetition levels)")
    max_def = col_schema.max_definition_level
    stats = col_meta.statistics
    nulls_known_zero = stats is not None and stats.has_null_count \
        and stats.null_count == 0
    if max_def > 1 and not nulls_known_zero:
        # _defs_all_present parses bit-width-1 blocks only; a wider def
        # level (optional leaf inside an optional group) would be misparsed
        # — conservatism by coincidence, not by construction (ADVICE.md r5)
        raise _PlainDecodeUnsupported("max_definition_level > 1")
    mv = buf if isinstance(buf, (bytes, memoryview)) else memoryview(buf)
    try:
        return _walk_plain_pages(mv, col_meta.num_values, np_dtype, max_def,
                                 nulls_known_zero)
    except (IndexError, ValueError, TypeError, RecursionError) as e:
        # truncated/corrupt chunk bytes (header walk past the buffer,
        # frombuffer over a short page, malformed def-level block, a
        # missing header field arithmetic'd as None, or bytes that nest
        # thrift structs past the recursion limit — 0x1C repeated recurses
        # once per byte) are a "can't prove safe" case like any other —
        # fall back to pyarrow (whose own decode then produces the
        # authoritative error) instead of leaking a bare error out of
        # library code
        raise _PlainDecodeUnsupported(f"malformed chunk: {e!r}") from None


def _walk_plain_pages(mv, total: int, np_dtype, max_def: int,
                      nulls_known_zero: bool) -> list[np.ndarray]:
    parts: list[np.ndarray] = []
    pos = 0
    decoded = 0
    while decoded < total:
        header, pos = _thrift_struct(mv, pos)
        page_type = header.get(1)
        comp_size = header.get(3)
        # negative sizes/counts are crafted-input territory: comp_size < 0
        # walks the cursor BACKWARD onto the same header and num_values <= 0
        # never advances `decoded` (frombuffer treats any negative count as
        # "all") — an infinite loop, not an exception, so guard explicitly
        if not isinstance(comp_size, int) or comp_size < 0:
            raise _PlainDecodeUnsupported(f"bad page size {comp_size}")
        page_end = pos + comp_size
        if page_type != 0:  # 0 = DATA_PAGE (v1); v2/dict/index -> fallback
            raise _PlainDecodeUnsupported(f"page type {page_type}")
        dph = header.get(5)
        if not isinstance(dph, dict):
            raise _PlainDecodeUnsupported("no data page header")
        num_values = dph.get(1)
        encoding = dph.get(2)
        def_enc = dph.get(3)
        if not isinstance(num_values, int) or num_values <= 0:
            raise _PlainDecodeUnsupported(f"bad num_values {num_values}")
        if encoding != 0:  # PLAIN
            raise _PlainDecodeUnsupported(f"encoding {encoding}")
        vpos = pos
        if max_def:
            if def_enc != 3:  # RLE
                raise _PlainDecodeUnsupported(f"def-level encoding {def_enc}")
            dlen = int.from_bytes(mv[vpos: vpos + 4], "little")
            if not nulls_known_zero and not _defs_all_present(
                    mv[vpos + 4: vpos + 4 + dlen], num_values):
                raise _PlainDecodeUnsupported("nulls present")
            vpos += 4 + dlen
        want = num_values * np_dtype.itemsize
        if vpos + want > page_end:
            raise _PlainDecodeUnsupported("page shorter than its values")
        parts.append(np.frombuffer(mv, np_dtype, count=num_values,
                                   offset=vpos))
        decoded += num_values
        pos = page_end
    return parts


def decode_plain_chunk(col_meta, col_schema, buf: np.ndarray) -> np.ndarray:
    """:func:`decode_plain_pages` joined to one array (a view when the chunk
    is a single page, else one concatenation)."""
    parts = decode_plain_pages(col_meta, col_schema, buf)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


class ParquetShard:
    """One Parquet file: metadata once, column chunks as ExtentLists."""

    def __init__(self, path: str, *, ctx: "StromContext | None" = None):
        """*ctx*: when it aliases *path* to a striped set
        (``register_striped``), metadata is read through the engine and every
        chunk/footer gather stripe-decodes — the file need not exist on disk.
        """
        import pyarrow.parquet as pq

        self.path = path
        self._ctx = ctx
        self._striped = ctx.striped_source(path) if ctx is not None else None
        if self._striped is not None:
            from strom.delivery.core import SourceIO

            self.metadata = pq.read_metadata(SourceIO(ctx, self._striped))
        else:
            self.metadata = pq.read_metadata(path)
        self._footer_bytes: np.ndarray | None = None  # engine-read once, reused
        # scan decode pools read row groups of one shard concurrently; the
        # lock keeps "read once" true under that concurrency
        import threading

        self._footer_lock = make_lock("app.parquet_footer")
        self._col_index = {
            self.metadata.schema.column(i).path: i
            for i in range(self.metadata.num_columns)
        }

    @property
    def num_row_groups(self) -> int:
        return self.metadata.num_row_groups

    @property
    def num_rows(self) -> int:
        return self.metadata.num_rows

    @property
    def column_names(self) -> list[str]:
        return list(self._col_index)

    def _col_indices(self, columns: Sequence[str] | None) -> list[int]:
        if columns is None:
            return list(range(self.metadata.num_columns))
        out = []
        for c in columns:
            if c not in self._col_index:
                raise KeyError(f"no column {c!r} in {self.path} "
                               f"(have {self.column_names})")
            out.append(self._col_index[c])
        return out

    def column_chunk_extents(self, row_group: int,
                             columns: Sequence[str] | None = None) -> ExtentList:
        """Byte ranges of the selected columns' compressed chunks in one row
        group (dictionary page included when present)."""
        rg = self.metadata.row_group(row_group)
        exts = []
        for ci in self._col_indices(columns):
            col = rg.column(ci)
            start = col.data_page_offset
            if col.dictionary_page_offset is not None:
                start = min(start, col.dictionary_page_offset)
            exts.append(Extent(self.path, start, col.total_compressed_size))
        return ExtentList(exts)

    def footer_extent(self) -> ExtentList:
        """The footer region. pyarrow speculatively reads the trailing 64KiB
        to find the footer, so cover at least that (or the whole thrift
        metadata + 4-byte length + 'PAR1' when it's bigger)."""
        if self._striped is not None:
            from strom.delivery.core import source_size

            fsize = source_size(self._striped)
        else:
            fsize = os.stat(self.path).st_size
        flen = min(fsize, max(self.metadata.serialized_size + 8, 64 * 1024))
        return ExtentList([Extent(self.path, fsize - flen, flen)])

    def read_row_group(self, ctx: "StromContext", row_group: int,
                       columns: Sequence[str] | None = None, *,
                       tenant: str | None = None) -> "pa.Table":
        """Engine-read the selected chunks + footer, decode to a pyarrow
        Table. Everything pyarrow touches was prefetched through strom."""
        import pyarrow.parquet as pq

        chunk_ext = self.column_chunk_extents(row_group, columns)
        footer_ext = self.footer_extent()
        with self._footer_lock:
            if self._footer_bytes is None:
                # immutable, read once — but billed to the REQUESTING
                # tenant: an interactive tenant's cold-start metadata read
                # must ride its own (priority) queue, not the default
                # tenant's training-class FIFO
                self._footer_bytes = ctx.pread(footer_ext, tenant=tenant)
        buf = ctx.pread(chunk_ext, tenant=tenant)
        cache = _RangeCache()
        cache.insert(footer_ext.extents[0].offset, self._footer_bytes)
        pos = 0
        for e in chunk_ext.extents:
            cache.insert(e.offset, buf[pos: pos + e.length])
            pos += e.length
        f = RangeCachedFile(self.path, cache, ctx=self._ctx)
        try:
            pf = pq.ParquetFile(f)
            table = pf.read_row_group(
                row_group, columns=list(columns) if columns is not None else None)
        finally:
            f.close()
        if cache.miss_bytes:
            from strom.utils.stats import global_stats

            global_stats.add("parquet_cache_miss_bytes", cache.miss_bytes)
        return table

    def read_row_group_arrays(self, ctx: "StromContext", row_group: int,
                              columns: Sequence[str], *,
                              tenant: str | None = None) -> dict:
        """Selected columns of one row group as host numpy arrays — the scan
        pipeline's read unit.

        Uncompressed PLAIN numeric chunks take the direct-decode path: ONE
        engine gather of the selected chunks, then ``decode_plain_pages``
        returns frombuffer views into that slab — no pyarrow round trip, no
        stitching copies, so the scan's cost is the I/O (VERDICT.md r4 next
        #1). Any column the fast path can't prove safe routes the whole
        group through :meth:`read_row_group` (results identical; tests
        cross-check). The ``parquet_plain_bytes`` / ``parquet_decode_bytes``
        stats counters record which path bytes took.
        """
        from strom.utils.stats import global_stats

        rg = self.metadata.row_group(row_group)
        cis = self._col_indices(columns)
        eligible = True
        for ci in cis:
            col = rg.column(ci)
            cs = self.metadata.schema.column(ci)
            if (col.compression != "UNCOMPRESSED"
                    or col.dictionary_page_offset is not None
                    or col.physical_type not in _PHYSICAL_NP
                    or not _plain_logical_ok(cs, col.physical_type)
                    or cs.max_repetition_level):
                eligible = False
                break
        if eligible:
            chunk_ext = self.column_chunk_extents(row_group, columns)
            buf = ctx.pread(chunk_ext, tenant=tenant)
            out = {}
            pos = 0
            try:
                for name, ci, ext in zip(columns, cis, chunk_ext.extents):
                    out[name] = decode_plain_chunk(
                        rg.column(ci), self.metadata.schema.column(ci),
                        buf[pos: pos + ext.length])
                    pos += ext.length
            except _PlainDecodeUnsupported:
                eligible = False  # data-level surprise: fall through
            else:
                global_stats.add("parquet_plain_bytes", int(buf.nbytes))
                return out
        table = self.read_row_group(ctx, row_group, columns=columns,
                                    tenant=tenant)
        out = {c: np.ascontiguousarray(table[c].to_numpy(zero_copy_only=False))
               for c in columns}
        global_stats.add("parquet_decode_bytes",
                         int(sum(a.nbytes for a in out.values())))
        return out


def write_parquet(ctx, path: str, columns: "dict[str, np.ndarray]", *,
                  row_group_rows: "int | None" = None,
                  compression: str = "NONE",
                  tenant: "str | None" = None,
                  fsync: bool = True) -> int:
    """Write *columns* as a Parquet file through the ENGINE write path
    (ISSUE 13 front 4): pyarrow serializes the table into an in-memory
    buffer, and the bytes land on disk via ``ctx.pwrite`` — the same
    scheduler-granted O_DIRECT machinery :class:`ParquetShard` reads them
    back with, so bench fixtures are generated and consumed by one I/O
    stack. ``compression="NONE"`` (the default) keeps the column chunks
    PLAIN-decodable by the zero-copy fast path. Returns bytes written."""
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - pyarrow is a test dep
        raise RuntimeError("write_parquet needs pyarrow") from e

    table = pa.table({k: pa.array(np.asarray(v)) for k, v in columns.items()})
    sink = pa.BufferOutputStream()
    pq.write_table(table, sink, compression=compression.lower(),
                   use_dictionary=False,
                   row_group_size=row_group_rows or len(table))
    buf = sink.getvalue()
    return ctx.pwrite(path, memoryview(buf), tenant=tenant, fsync=fsync)
