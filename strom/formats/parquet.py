"""Parquet columnar reads: column-chunk byte ranges through the engine,
decode via pyarrow (SURVEY.md §7.2 step 7: "Parquet (column-chunk range reads
via metadata footer)").

This mirrors the reference's flagship consumer pattern — PG-Strom scans
Parquet-ish columnar blocks straight from NVMe into the accelerator
(SURVEY.md §0.5) — re-cut for TPU: the *selected columns'* compressed chunks
are gather-read (O_DIRECT, RAID0, sharded fan-out all apply), decoded on
host, and only the projected/filtered table ever reaches HBM.  Consumer: the
Parquet scan fan-out pipeline (BASELINE config #5, BASELINE.json:11).
"""

from __future__ import annotations

import bisect
import os
from typing import TYPE_CHECKING, Sequence

import numpy as np

from strom.delivery.extents import Extent, ExtentList

if TYPE_CHECKING:
    import pyarrow as pa

    from strom.delivery.core import StromContext


class _RangeCache:
    """Sorted, non-overlapping (offset → bytes) ranges of one file."""

    def __init__(self) -> None:
        self._offsets: list[int] = []
        self._bufs: list[np.ndarray] = []
        self.miss_bytes = 0

    def insert(self, offset: int, buf: np.ndarray) -> None:
        i = bisect.bisect_left(self._offsets, offset)
        self._offsets.insert(i, offset)
        self._bufs.insert(i, buf)

    def read(self, offset: int, length: int, fallback) -> bytes:
        """Serve [offset, +length), stitching cached ranges; gaps fall back to
        *fallback(offset, length) -> bytes* on the real source (counted as
        miss bytes)."""
        out = bytearray(length)
        pos = offset
        end = offset + length
        while pos < end:
            i = bisect.bisect_right(self._offsets, pos) - 1
            hit = None
            if i >= 0:
                ro, rb = self._offsets[i], self._bufs[i]
                if ro <= pos < ro + len(rb):
                    hit = rb[pos - ro: pos - ro + (end - pos)]
            if hit is not None and len(hit) > 0:
                out[pos - offset: pos - offset + len(hit)] = hit.tobytes()
                pos += len(hit)
                continue
            # miss: read up to the next cached range (or to end)
            j = bisect.bisect_right(self._offsets, pos)
            stop = min(end, self._offsets[j]) if j < len(self._offsets) else end
            data = fallback(pos, stop - pos)
            if not data:
                return bytes(out[: pos - offset])  # EOF
            out[pos - offset: pos - offset + len(data)] = data
            self.miss_bytes += len(data)
            pos += len(data)
        return bytes(out)


class RangeCachedFile:
    """File-like object over a _RangeCache; what pyarrow decodes from.

    pyarrow wraps this in a PythonFile; all reads it issues for the footer and
    the selected column chunks are served from engine-prefetched ranges."""

    def __init__(self, path: str, cache: _RangeCache, *,
                 ctx: "StromContext | None" = None):
        """Misses pread the real file — or, when *ctx* aliases *path* to a
        striped set (``register_striped``), gather through the engine."""
        self._cache = cache
        striped = ctx.striped_source(path) if ctx is not None else None
        if striped is not None:
            from strom.delivery.core import source_size

            self._fd = -1
            self._size = source_size(striped)
            self._fallback = lambda off, ln: ctx.pread(
                striped, off, min(ln, self._size - off)).tobytes()
        else:
            self._fd = os.open(path, os.O_RDONLY)
            self._size = os.fstat(self._fd).st_size
            self._fallback = lambda off, ln: os.pread(self._fd, ln, off)
        self._pos = 0
        self._closed = False

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self._size - self._pos
        n = max(0, min(n, self._size - self._pos))
        data = self._cache.read(self._pos, n, self._fallback)
        self._pos += len(data)
        return data

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            self._pos = offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        elif whence == os.SEEK_END:
            self._pos = self._size + offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def flush(self) -> None:
        pass

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def miss_bytes(self) -> int:
        return self._cache.miss_bytes

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._fd >= 0:
                os.close(self._fd)


class ParquetShard:
    """One Parquet file: metadata once, column chunks as ExtentLists."""

    def __init__(self, path: str, *, ctx: "StromContext | None" = None):
        """*ctx*: when it aliases *path* to a striped set
        (``register_striped``), metadata is read through the engine and every
        chunk/footer gather stripe-decodes — the file need not exist on disk.
        """
        import pyarrow.parquet as pq

        self.path = path
        self._ctx = ctx
        self._striped = ctx.striped_source(path) if ctx is not None else None
        if self._striped is not None:
            from strom.delivery.core import SourceIO

            self.metadata = pq.read_metadata(SourceIO(ctx, self._striped))
        else:
            self.metadata = pq.read_metadata(path)
        self._footer_bytes: np.ndarray | None = None  # engine-read once, reused
        # scan decode pools read row groups of one shard concurrently; the
        # lock keeps "read once" true under that concurrency
        import threading

        self._footer_lock = threading.Lock()
        self._col_index = {
            self.metadata.schema.column(i).path: i
            for i in range(self.metadata.num_columns)
        }

    @property
    def num_row_groups(self) -> int:
        return self.metadata.num_row_groups

    @property
    def num_rows(self) -> int:
        return self.metadata.num_rows

    @property
    def column_names(self) -> list[str]:
        return list(self._col_index)

    def _col_indices(self, columns: Sequence[str] | None) -> list[int]:
        if columns is None:
            return list(range(self.metadata.num_columns))
        out = []
        for c in columns:
            if c not in self._col_index:
                raise KeyError(f"no column {c!r} in {self.path} "
                               f"(have {self.column_names})")
            out.append(self._col_index[c])
        return out

    def column_chunk_extents(self, row_group: int,
                             columns: Sequence[str] | None = None) -> ExtentList:
        """Byte ranges of the selected columns' compressed chunks in one row
        group (dictionary page included when present)."""
        rg = self.metadata.row_group(row_group)
        exts = []
        for ci in self._col_indices(columns):
            col = rg.column(ci)
            start = col.data_page_offset
            if col.dictionary_page_offset is not None:
                start = min(start, col.dictionary_page_offset)
            exts.append(Extent(self.path, start, col.total_compressed_size))
        return ExtentList(exts)

    def footer_extent(self) -> ExtentList:
        """The footer region. pyarrow speculatively reads the trailing 64KiB
        to find the footer, so cover at least that (or the whole thrift
        metadata + 4-byte length + 'PAR1' when it's bigger)."""
        if self._striped is not None:
            from strom.delivery.core import source_size

            fsize = source_size(self._striped)
        else:
            fsize = os.stat(self.path).st_size
        flen = min(fsize, max(self.metadata.serialized_size + 8, 64 * 1024))
        return ExtentList([Extent(self.path, fsize - flen, flen)])

    def read_row_group(self, ctx: "StromContext", row_group: int,
                       columns: Sequence[str] | None = None) -> "pa.Table":
        """Engine-read the selected chunks + footer, decode to a pyarrow
        Table. Everything pyarrow touches was prefetched through strom."""
        import pyarrow.parquet as pq

        chunk_ext = self.column_chunk_extents(row_group, columns)
        footer_ext = self.footer_extent()
        with self._footer_lock:
            if self._footer_bytes is None:
                self._footer_bytes = ctx.pread(footer_ext)  # immutable: once
        buf = ctx.pread(chunk_ext)
        cache = _RangeCache()
        cache.insert(footer_ext.extents[0].offset, self._footer_bytes)
        pos = 0
        for e in chunk_ext.extents:
            cache.insert(e.offset, buf[pos: pos + e.length])
            pos += e.length
        f = RangeCachedFile(self.path, cache, ctx=self._ctx)
        try:
            pf = pq.ParquetFile(f)
            table = pf.read_row_group(
                row_group, columns=list(columns) if columns is not None else None)
        finally:
            f.close()
        if cache.miss_bytes:
            from strom.utils.stats import global_stats

            global_stats.add("parquet_cache_miss_bytes", cache.miss_bytes)
        return table
