"""WebDataset ``.tar`` shards: index once, then range-read members
(SURVEY.md §7.2 step 7: "WebDataset .tar (index then range-read members)").

The tar container is only touched for header metadata at index time (cached
in a sidecar, like the reference caches extent maps per file — SURVEY.md
§3.3 "probe: extent map (cached)"); payload bytes flow through the engine as
plain byte ranges, so member reads get O_DIRECT / RAID0 / sharding for free.
Consumer: the ViT training loader (BASELINE config #3, BASELINE.json:9).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import tarfile
from typing import Any, Iterator, Mapping, Sequence

from strom.delivery.extents import Extent, ExtentList


from strom.delivery.core import SourceIO  # noqa: F401  (re-export: tar
# indexing over striped sets uses it; the adapter lives in the delivery
# layer it operates on)

_IDX_SUFFIX = ".stromidx.json"
_IDX_VERSION = 1


@dataclasses.dataclass(frozen=True)
class TarMember:
    name: str
    offset: int    # byte offset of the member's *data* (past the 512B header)
    size: int


@dataclasses.dataclass(frozen=True)
class WdsSample:
    """One WebDataset sample: every member sharing a basename key."""

    key: str
    shard: str                         # tar path
    members: Mapping[str, TarMember]   # extension -> member

    def extents(self, exts: Sequence[str] | None = None) -> ExtentList:
        """Gather plan for this sample's payload bytes, members concatenated
        in the given extension order (default: sorted)."""
        order = list(exts) if exts is not None else sorted(self.members)
        ext_list = []
        for e in order:
            m = self.members[e]
            if m.size > 0:
                ext_list.append(Extent(self.shard, m.offset, m.size))
        return ExtentList(ext_list)


def split_key(name: str) -> tuple[str, str]:
    """WebDataset naming: key = name up to the first '.' of the basename,
    extension = the rest ('a/b.cls.txt' → ('a/b', 'cls.txt'))."""
    dirname, _, base = name.rpartition("/")
    stem, _, ext = base.partition(".")
    key = f"{dirname}/{stem}" if dirname else stem
    return key, ext


class TarIndex:
    """Member table of one tar shard, built once and cached in a sidecar."""

    def __init__(self, path: str, members: list[TarMember]):
        self.path = path
        self.members = members

    @classmethod
    def build(cls, path: str, *, cache: bool = True,
              fileobj: io.RawIOBase | None = None) -> "TarIndex":
        """Index the shard at *path*. With *fileobj* (e.g. a :class:`SourceIO`
        over a striped set aliased to *path*), headers are read through it and
        the sidecar cache is skipped — the path need not exist on disk."""
        if fileobj is not None:
            cache = False
        cached = cls._load_cache(path) if cache else None
        if cached is not None:
            return cached
        members: list[TarMember] = []
        # tarfile in stream-less mode seeks header→header, never reads payloads
        # (fileobj=None → tarfile opens the path itself)
        with tarfile.open(path, "r:", fileobj=fileobj) as tf:
            for m in tf:
                if m.isfile():
                    members.append(TarMember(m.name, m.offset_data, m.size))
        idx = cls(path, members)
        if cache:
            idx._save_cache()
        return idx

    # -- sidecar cache ------------------------------------------------------
    def _cache_path(self) -> str:
        return self.path + _IDX_SUFFIX

    def _save_cache(self) -> None:
        st = os.stat(self.path)
        blob = {
            "version": _IDX_VERSION,
            "tar_size": st.st_size,
            "tar_mtime_ns": st.st_mtime_ns,
            "members": [[m.name, m.offset, m.size] for m in self.members],
        }
        tmp = self._cache_path() + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(blob, f)
            os.replace(tmp, self._cache_path())
        except OSError:
            pass  # read-only dataset dir: index stays in-memory only

    @classmethod
    def _load_cache(cls, path: str) -> "TarIndex | None":
        try:
            with open(path + _IDX_SUFFIX) as f:
                blob = json.load(f)
            st = os.stat(path)
            if (blob.get("version") != _IDX_VERSION
                    or blob.get("tar_size") != st.st_size
                    or blob.get("tar_mtime_ns") != st.st_mtime_ns):
                return None
            return cls(path, [TarMember(n, o, s) for n, o, s in blob["members"]])
        except (OSError, ValueError, KeyError):
            return None

    # -- sample grouping ----------------------------------------------------
    def samples(self) -> list[WdsSample]:
        """Group members into WebDataset samples, preserving shard order."""
        grouped: dict[str, dict[str, TarMember]] = {}
        order: list[str] = []
        for m in self.members:
            key, ext = split_key(m.name)
            if key not in grouped:
                grouped[key] = {}
                order.append(key)
            grouped[key][ext] = m
        return [WdsSample(k, self.path, grouped[k]) for k in order]


class WdsShardSet:
    """Multiple tar shards addressed as one sample collection."""

    def __init__(self, paths: Sequence[str], *, cache_index: bool = True,
                 ctx: Any = None):
        """*ctx*: a StromContext; shard paths it aliases to striped sets
        (``ctx.register_striped``) are indexed through the engine instead of
        the (non-existent) plain path — the samples' extents keep the aliased
        path, so payload gathers stripe-decode in the delivery layer."""
        if not paths:
            raise ValueError("need at least one shard")
        self.paths = tuple(paths)
        self.indexes = []
        for p in self.paths:
            sf = ctx.striped_source(p) if ctx is not None else None
            self.indexes.append(
                TarIndex.build(p, cache=cache_index,
                               fileobj=SourceIO(ctx, sf) if sf is not None
                               else None))
        self._samples: list[WdsSample] = []
        for idx in self.indexes:
            self._samples.extend(idx.samples())

    @property
    def samples(self) -> list[WdsSample]:
        return self._samples

    def __len__(self) -> int:
        return len(self._samples)

    def __iter__(self) -> Iterator[WdsSample]:
        return iter(self._samples)

    def batch_extents(self, sample_indices: Sequence[int],
                      exts: Sequence[str] | None = None) -> ExtentList:
        """One gather plan covering a whole batch of samples."""
        return ExtentList.concat(
            [self._samples[i].extents(exts) for i in sample_indices])
