"""Decoded-output cache (ISSUE 12 tentpole, front 4): predecoded-on-the-fly.

The hot cache (strom/delivery/hotcache.py) serves repeat COMPRESSED bytes
from RAM, but a JPEG pipeline still pays the full entropy-decode + IDCT on
every epoch — the wall BENCH_r05 measured at ~6.5x the predecoded arm. This
adapter admits first-epoch decode OUTPUT (post-decode, pre-transform
full-frame RGB8 pixels) into the same :class:`~strom.delivery.hotcache.
HotCache`, so epoch >= 2 pays only crop + resize per sample: the predecoded
arm's economics without the offline staging pass.

Design points:

- **Keys.** ``("jpegdec", shard_path, member_lo, member_hi, fingerprint)``
  — the member's PHYSICAL extent (stable across epochs, exactly like the
  extent cache's keys) plus a decode-params fingerprint (decoder engine +
  colorspace), so pixels decoded under different semantics can never serve
  each other. The byte range within a key is ``[0, h*w*3)`` with h/w read
  from the member's SOF header — self-describing at both admit and lookup
  without a stored header.
- **Fidelity.** Cached pixels are FULL-frame, full-resolution decodes: a
  cache hit serves pixels identical to the ``reduced_scale=False`` path
  (bit-identical to the full-decode transform), never the reduced-decode
  approximation. The admitting pass therefore decodes full even where
  ROI/reduced would have engaged — that one-epoch cost is what buys every
  later epoch the RAM serve.
- **Budget + partitions.** Entries ride the shared HotCache budget and
  slab pool like every other tenant (slab-size-class billed), and charge
  the owning pipeline's tenant partition (ISSUE 7) — a decode-cache-happy
  tenant self-evicts before it can displace another tenant's hot set.
  Admission follows the cache's policy (second-touch observes the first
  epoch, admits the second; ``always`` admits on first decode — the bench
  pair's mode).
- **Pinning.** A served frame stays pinned for exactly the crop+resize
  window (the caller releases), the same lifetime handshake every other
  cache reader uses — eviction can never recycle a slab mid-transform.

Counters (``decode_cache_*``) are kept separate from the extent cache's
``cache_*`` set (lookups run ``record=False``): mixing them would distort
the hit ratio the warm/cold epoch analysis reads.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any

import numpy as np

from strom.utils.locks import make_lock
from strom.utils.stats import global_stats

_DIMS_CAP = 1 << 16  # bounded (ckey -> (h, w)) ledger for plan-time probes


class ServedFrame:
    """A decoded frame served from the cache at PLAN time (ISSUE 13
    satellite): carries the pinned full-frame view straight into the decode
    pool in place of the JPEG bytes that were never gathered. The transform
    (and the batch's error path) release it; release is idempotent — the
    pin drops exactly once however many paths race to clean up."""

    __slots__ = ("img", "_pin", "_dcache")

    def __init__(self, img: np.ndarray, pin, dcache: "DecodedCache"):
        self.img = img
        self._pin = pin
        self._dcache = dcache

    def release(self) -> None:
        self._dcache._release_frame(self)


class DecodedCache:
    """Thin, counter-bearing adapter between the JPEG transform and a
    :class:`~strom.delivery.hotcache.HotCache` partition holding decoded
    frames. Thread-safe: the tally lock (``cache.decoded``) is a leaf
    held only for counter updates, never across cache calls."""

    def __init__(self, cache, *, tenant: "str | None" = None,
                 fingerprint: str = "rgb8", scope=None):
        self._hot_cache = cache
        self._tenant = tenant
        self._fp = fingerprint
        self._lock = make_lock("cache.decoded")
        self._scope = scope if scope is not None else global_stats
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.admitted_bytes = 0
        # plan-time probe support (ISSUE 13 satellite): frame dims per key,
        # learned at offer/get — the pre-gather probe has no JPEG header to
        # read h/w from, so it consults this bounded ledger instead
        self._dims: "OrderedDict[Any, tuple[int, int]]" = OrderedDict()
        self.plan_hits = 0
        self.plan_skipped_bytes = 0

    @property
    def enabled(self) -> bool:
        """Follows the backing cache's phase gate: a disabled hot cache
        serves/admits no decoded frames either (the bench arms scope both
        to their epoch pairs through the one flag)."""
        return self._hot_cache is not None and self._hot_cache.enabled

    def key(self, path: str, lo: int, hi: int) -> tuple:
        """Cache key for the member occupying file bytes [lo, hi) of
        *path* — extent-stable across epochs, fingerprint-split across
        decode semantics."""
        return ("jpegdec", path, lo, hi, self._fp)

    def get(self, ckey: Any, h: int, w: int):
        """(pinned (h, w, 3) view, pin) on a hit, None on a miss. The
        caller MUST :meth:`release` the pin once it stops reading the
        view (after the crop+resize)."""
        n = h * w * 3
        got = self._hot_cache.view(ckey, 0, n, record=False)
        self._note_dims(ckey, h, w)
        if got is None:
            with self._lock:
                self.misses += 1
            self._scope.add("decode_cache_misses")
            return None
        buf, entry = got
        with self._lock:
            self.hits += 1
            self.hit_bytes += n
        self._scope.add("decode_cache_hits")
        self._scope.add("decode_cache_hit_bytes", n)
        return buf.reshape(h, w, 3), entry

    def _note_dims(self, ckey: Any, h: int, w: int) -> None:
        with self._lock:
            self._dims[ckey] = (h, w)
            self._dims.move_to_end(ckey)
            while len(self._dims) > _DIMS_CAP:
                self._dims.popitem(last=False)

    def probe(self, ckey: Any, skipped_bytes: int = 0
              ) -> "ServedFrame | None":
        """Plan-time probe (ISSUE 13 satellite): a pinned
        :class:`ServedFrame` when the FULL frame for *ckey* is resident —
        the caller then skips gathering the image member entirely (labels +
        misses only reach the engine) and hands the frame to the transform
        in place of the bytes. None when the frame (or its dims ledger
        entry) is absent: the member is gathered and the in-transform
        serve/offer path runs as before — a stale ledger can only cost a
        wasted gather, never wrong pixels. *skipped_bytes* (the member size
        the hit avoids gathering) feeds the observability counters."""
        if not self.enabled:
            return None
        with self._lock:
            dims = self._dims.get(ckey)
        if dims is None:
            return None
        h, w = dims
        got = self._hot_cache.view(ckey, 0, h * w * 3, record=False)
        if got is None:
            return None
        buf, entry = got
        with self._lock:
            self.hits += 1
            self.hit_bytes += h * w * 3
            self.plan_hits += 1
            self.plan_skipped_bytes += skipped_bytes
        self._scope.add("decode_cache_hits")
        self._scope.add("decode_cache_hit_bytes", h * w * 3)
        self._scope.add("decode_cache_plan_hits")
        if skipped_bytes:
            self._scope.add("decode_cache_plan_skipped_bytes",
                            skipped_bytes)
        return ServedFrame(buf.reshape(h, w, 3), entry, self)

    def export(self, path: str, lo: int, hi: int,
               fingerprint: "str | None" = None
               ) -> "tuple[int, int, bytes] | None":
        """Peer-serving lookup (ISSUE 20): ``(h, w, rgb bytes)`` for the
        member at [*lo*, *hi*) of *path* when the full decoded frame is
        resident, else None. Unlike :meth:`probe` the pixels are COPIED
        out (the peer server writes them to a socket after the call
        returns, far outside any pin window) and the requester's decode
        *fingerprint* must match ours — pixels decoded under different
        semantics never cross the wire either."""
        if not self.enabled:
            return None
        if fingerprint and fingerprint != self._fp:
            return None
        ckey = ("jpegdec", path, lo, hi, self._fp)
        with self._lock:
            dims = self._dims.get(ckey)
        if dims is None:
            return None
        h, w = dims
        got = self._hot_cache.view(ckey, 0, h * w * 3, record=False)
        if got is None:
            return None
        buf, entry = got
        try:
            out = bytes(buf)
        finally:
            self._hot_cache.unpin((entry,))
        with self._lock:
            self.hits += 1
            self.hit_bytes += h * w * 3
        self._scope.add("decode_cache_hits")
        self._scope.add("decode_cache_hit_bytes", h * w * 3)
        return h, w, out

    def release(self, pin) -> None:
        self._hot_cache.unpin((pin,))

    def _release_frame(self, frame: ServedFrame) -> None:
        """Idempotent ServedFrame release: the pin drops exactly once even
        when the transform's finally and the batch abort path both run."""
        with self._lock:
            pin, frame._pin = frame._pin, None
        if pin is not None:
            self._hot_cache.unpin((pin,))

    def offer(self, ckey: Any, img: np.ndarray) -> int:
        """Offer a decoded full frame for admission (subject to the
        cache's policy, budget, and the owning tenant's partition).
        Returns bytes admitted (0 = refused/duplicate)."""
        if img.ndim == 3:
            self._note_dims(ckey, img.shape[0], img.shape[1])
        flat = np.ascontiguousarray(img).reshape(-1)
        admitted = self._hot_cache.admit(ckey, 0, flat.size, flat,
                                         tenant=self._tenant)
        if admitted:
            with self._lock:
                self.admitted_bytes += admitted
            self._scope.add("decode_cache_admitted_bytes", admitted)
        return admitted
