"""Decoded-output cache (ISSUE 12 tentpole, front 4): predecoded-on-the-fly.

The hot cache (strom/delivery/hotcache.py) serves repeat COMPRESSED bytes
from RAM, but a JPEG pipeline still pays the full entropy-decode + IDCT on
every epoch — the wall BENCH_r05 measured at ~6.5x the predecoded arm. This
adapter admits first-epoch decode OUTPUT (post-decode, pre-transform
full-frame RGB8 pixels) into the same :class:`~strom.delivery.hotcache.
HotCache`, so epoch >= 2 pays only crop + resize per sample: the predecoded
arm's economics without the offline staging pass.

Design points:

- **Keys.** ``("jpegdec", shard_path, member_lo, member_hi, fingerprint)``
  — the member's PHYSICAL extent (stable across epochs, exactly like the
  extent cache's keys) plus a decode-params fingerprint (decoder engine +
  colorspace), so pixels decoded under different semantics can never serve
  each other. The byte range within a key is ``[0, h*w*3)`` with h/w read
  from the member's SOF header — self-describing at both admit and lookup
  without a stored header.
- **Fidelity.** Cached pixels are FULL-frame, full-resolution decodes: a
  cache hit serves pixels identical to the ``reduced_scale=False`` path
  (bit-identical to the full-decode transform), never the reduced-decode
  approximation. The admitting pass therefore decodes full even where
  ROI/reduced would have engaged — that one-epoch cost is what buys every
  later epoch the RAM serve.
- **Budget + partitions.** Entries ride the shared HotCache budget and
  slab pool like every other tenant (slab-size-class billed), and charge
  the owning pipeline's tenant partition (ISSUE 7) — a decode-cache-happy
  tenant self-evicts before it can displace another tenant's hot set.
  Admission follows the cache's policy (second-touch observes the first
  epoch, admits the second; ``always`` admits on first decode — the bench
  pair's mode).
- **Pinning.** A served frame stays pinned for exactly the crop+resize
  window (the caller releases), the same lifetime handshake every other
  cache reader uses — eviction can never recycle a slab mid-transform.

Counters (``decode_cache_*``) are kept separate from the extent cache's
``cache_*`` set (lookups run ``record=False``): mixing them would distort
the hit ratio the warm/cold epoch analysis reads.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from strom.utils.locks import make_lock
from strom.utils.stats import global_stats


class DecodedCache:
    """Thin, counter-bearing adapter between the JPEG transform and a
    :class:`~strom.delivery.hotcache.HotCache` partition holding decoded
    frames. Thread-safe: the tally lock (``cache.decoded``) is a leaf
    held only for counter updates, never across cache calls."""

    def __init__(self, cache, *, tenant: "str | None" = None,
                 fingerprint: str = "rgb8", scope=None):
        self._hot_cache = cache
        self._tenant = tenant
        self._fp = fingerprint
        self._lock = make_lock("cache.decoded")
        self._scope = scope if scope is not None else global_stats
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.admitted_bytes = 0

    @property
    def enabled(self) -> bool:
        """Follows the backing cache's phase gate: a disabled hot cache
        serves/admits no decoded frames either (the bench arms scope both
        to their epoch pairs through the one flag)."""
        return self._hot_cache is not None and self._hot_cache.enabled

    def key(self, path: str, lo: int, hi: int) -> tuple:
        """Cache key for the member occupying file bytes [lo, hi) of
        *path* — extent-stable across epochs, fingerprint-split across
        decode semantics."""
        return ("jpegdec", path, lo, hi, self._fp)

    def get(self, ckey: Any, h: int, w: int):
        """(pinned (h, w, 3) view, pin) on a hit, None on a miss. The
        caller MUST :meth:`release` the pin once it stops reading the
        view (after the crop+resize)."""
        n = h * w * 3
        got = self._hot_cache.view(ckey, 0, n, record=False)
        if got is None:
            with self._lock:
                self.misses += 1
            self._scope.add("decode_cache_misses")
            return None
        buf, entry = got
        with self._lock:
            self.hits += 1
            self.hit_bytes += n
        self._scope.add("decode_cache_hits")
        self._scope.add("decode_cache_hit_bytes", n)
        return buf.reshape(h, w, 3), entry

    def release(self, pin) -> None:
        self._hot_cache.unpin((pin,))

    def offer(self, ckey: Any, img: np.ndarray) -> int:
        """Offer a decoded full frame for admission (subject to the
        cache's policy, budget, and the owning tenant's partition).
        Returns bytes admitted (0 = refused/duplicate)."""
        flat = np.ascontiguousarray(img).reshape(-1)
        admitted = self._hot_cache.admit(ckey, 0, flat.size, flat,
                                         tenant=self._tenant)
        if admitted:
            with self._lock:
                self.admitted_bytes += admitted
            self._scope.add("decode_cache_admitted_bytes", admitted)
        return admitted
