"""T2 format readers: record extraction over block reads (SURVEY.md §1 layer
T2, §7.2 step 7).

Each reader compiles its container format's record layout into
:class:`strom.delivery.extents.ExtentList` byte-range plans; the delivery
layer (T3) does the actual I/O, so every format automatically gets O_DIRECT,
RAID0 striping, sharded reads and async handles.
"""

from strom.formats.rawbin import TokenShardSet  # noqa: F401
from strom.formats.wds import TarIndex, TarMember, WdsSample, WdsShardSet  # noqa: F401
from strom.formats.jpeg import (  # noqa: F401
    DecodePool, center_crop_resize, decode_jpeg, make_train_transform,
    parse_jpeg_dims, random_resized_crop, reduced_denom)
from strom.formats.parquet import ParquetShard  # noqa: F401
