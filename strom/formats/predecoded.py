"""Pre-decoded image shards: decode once offline, train decode-free.

BASELINE config #2's 0-data-stall demonstration is JPEG-decode-bound on
single-core hosts (the decode pool and the consumer share the CPU, so decode
only progresses while the consumer idles — BASELINE.md §C). This format
moves the decode offline, the same trade the reference's flagship deployment
makes by staging decoded tensors on flash (SURVEY.md §7.1 "zero-copy"
pipeline shape; reference cite UNVERIFIED — empty mount, SURVEY.md §0): a
shard is a flat array of ``HxWx3`` uint8 records plus a tiny ``.labels.npy``
sidecar, so the training loader is a pure engine gather + device_put — byte
-identical mechanics to the packed-token Llama loader, which demonstrably
reaches 0 stalls on this box.

On-disk layout for ``foo.pdec``:
  foo.pdec             packed records, record = image_size*image_size*3 bytes
  foo.pdec.labels.npy  int32 [n] labels, loaded whole at pipeline build
  foo.pdec.meta.json   {"image_size": S, "n": N} (sanity-checked at load)
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Sequence

import numpy as np

from strom.delivery.extents import ExtentList
from strom.formats.rawbin import TokenShardSet

LABELS_SUFFIX = ".labels.npy"
META_SUFFIX = ".meta.json"


def predecode_wds(ctx, tar_paths: Sequence[str], out_path: str, *,
                  image_size: int,
                  image_ext: str = "jpg",
                  label_ext: str = "cls",
                  decode_workers: int = 8) -> str:
    """Decode every sample of the WDS *tar_paths* once: resize to
    ``image_size`` (deterministic — augmentation belongs to training-time
    transforms, not the staged bytes) and write the packed shard at
    *out_path*. Reads ride the engine (striped aliases included). Returns
    *out_path*."""
    from strom.formats.jpeg import DecodePool, center_crop_resize, decode_jpeg
    from strom.formats.wds import WdsShardSet

    ss = WdsShardSet(tar_paths, ctx=ctx)
    record_bytes = image_size * image_size * 3
    labels = np.zeros(len(ss), dtype=np.int32)
    pool = DecodePool(decode_workers)

    def decode_one(blob: np.ndarray) -> np.ndarray:
        return center_crop_resize(decode_jpeg(blob), image_size)

    try:
        with open(out_path + ".tmp", "wb") as f:
            batch = 64
            for lo in range(0, len(ss), batch):
                idxs = list(range(lo, min(lo + batch, len(ss))))
                el = ss.batch_extents(idxs, [image_ext, label_ext])
                buf = ctx.pread(el)
                blobs, pos = [], 0
                for i in idxs:
                    s = ss.samples[i]
                    isz = s.members[image_ext].size
                    lsz = s.members[label_ext].size
                    blobs.append(buf[pos: pos + isz])
                    labels[i] = int(buf[pos + isz: pos + isz + lsz].tobytes()
                                    or b"0")
                    pos += isz + lsz
                for img in pool.map(decode_one, blobs):
                    assert img.nbytes == record_bytes
                    f.write(np.ascontiguousarray(img).tobytes())
    finally:
        pool.close()
    # Sidecars are staged at .tmp names and only renamed AFTER the records
    # rename (records first): a crash anywhere in this sequence leaves either
    # the complete old triple, or new records with old sidecars — never old
    # records paired with new labels (ADVICE.md r3 #1). The loader detects
    # the new-records/old-sidecars window whenever the record COUNT changed
    # (per-shard labels-length check); an equal-count re-stage whose content
    # changed is outside this protocol's reach and is covered by the
    # caller-level source fingerprint (_ensure_predecoded and the like) —
    # callers re-staging over an existing shard should keep one.
    np.save(out_path + LABELS_SUFFIX + ".tmp.npy", labels)
    with open(out_path + META_SUFFIX + ".tmp", "w") as f:
        json.dump({"image_size": image_size, "n": len(ss)}, f)
    os.replace(out_path + ".tmp", out_path)
    os.replace(out_path + LABELS_SUFFIX + ".tmp.npy", out_path + LABELS_SUFFIX)
    os.replace(out_path + META_SUFFIX + ".tmp", out_path + META_SUFFIX)
    return out_path


def stage_striped_predecoded(ctx, pdec: str, members: Sequence[str],
                             chunk: int, virt: str | None = None, *,
                             stripe: bool = True) -> str:
    """Stripe the packed shard *pdec* over *members* RAID0-style (skip with
    ``stripe=False`` when the members are already fresh — e.g. a
    fingerprint-cached bench fixture), register the path alias, and place
    alias-named sidecar copies so :class:`PredecodedShardSet` finds
    labels/meta — the whole staging protocol in one place (the sidecar copy
    is easy to forget and only fails at pipeline build). Returns the alias
    path to load from."""
    import shutil

    from strom.engine.raid0 import stripe_file

    virt = virt or pdec + ".raid0"
    if stripe:
        stripe_file(pdec, list(members), chunk)
    ctx.register_striped(virt, list(members), chunk,
                         size=os.path.getsize(pdec))
    for sfx in (LABELS_SUFFIX, META_SUFFIX):
        shutil.copyfile(pdec + sfx, virt + sfx)
    return virt


@dataclasses.dataclass(frozen=True)
class PredecodedShardSet:
    """Pre-decoded image shards addressed as one global record array.

    Record addressing and gather planning are exactly the packed-token
    layout, so this composes :class:`TokenShardSet` with uint8 pixel
    records; labels live host-side (they are 4 bytes/sample — engine reads
    are for the 150KiB images).

    *paths* may be striped-set aliases (``StromContext.register_striped``):
    pass ``shard_sizes`` with the logical sizes (the pipeline resolves them
    through the context) and keep the ``.labels.npy`` / ``.meta.json``
    sidecars at the ALIAS names — sidecars are host-read tiny files, only
    the pixel records ride the engine's stripe decode."""

    paths: tuple[str, ...]
    image_size: int
    shard_sizes: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        object.__setattr__(self, "paths", tuple(self.paths))
        for p in self.paths:
            meta = None
            try:
                with open(p + META_SUFFIX) as f:
                    meta = json.load(f)
            except (OSError, json.JSONDecodeError):
                pass  # meta sidecar is advisory; record math is the contract
            if meta is not None and meta.get("image_size") != self.image_size:
                raise ValueError(
                    f"{p}: predecoded at image_size {meta.get('image_size')},"
                    f" loader wants {self.image_size}")
        inner = TokenShardSet(self.paths, record_tokens=self.record_bytes,
                              dtype=np.dtype(np.uint8),
                              shard_sizes=self.shard_sizes)
        object.__setattr__(self, "_inner", inner)
        labels = []
        for i, p in enumerate(self.paths):
            lp = p + LABELS_SUFFIX
            if not os.path.exists(lp):
                # refusing beats silently training against label 0 for every
                # sample (a lost sidecar would be invisible in the loss curve
                # until far too late)
                raise FileNotFoundError(
                    f"{p}: labels sidecar {lp} is missing — re-run "
                    f"predecode_wds (records and labels are written together)")
            arr = np.load(lp).astype(np.int32)
            n_records = inner.records_in_shard(i)
            if len(arr) != n_records:
                # catches a predecode interrupted between the records rename
                # and the sidecar renames (new records, stale labels)
                raise ValueError(
                    f"{p}: labels sidecar has {len(arr)} entries but the "
                    f"records file holds {n_records} records — sidecars are "
                    f"stale; re-run predecode_wds")
            labels.append(arr)
        object.__setattr__(self, "_labels", np.concatenate(labels)
                           if labels else np.zeros(0, np.int32))

    @property
    def record_bytes(self) -> int:
        return self.image_size * self.image_size * 3

    @property
    def num_records(self) -> int:
        return self._inner.num_records  # type: ignore[attr-defined]

    def labels(self, records: Sequence[int]) -> np.ndarray:
        return self._labels[np.asarray(records, dtype=np.int64)]  # type: ignore[attr-defined]

    def extents(self, records: Sequence[int]) -> ExtentList:
        return self._inner.extents(records)  # type: ignore[attr-defined]
