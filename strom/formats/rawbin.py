"""Packed-token ``.bin`` shards — the simplest record format (SURVEY.md §7.2
step 7: "raw packed-token .bin (trivial slicing — do first)").

A shard is a flat on-disk array of token ids (fixed dtype). Records are
fixed-length windows of ``record_tokens`` tokens; shard boundaries never split
a record (the tail remainder of each shard is dropped, like the reference
drops partial trailing blocks). Consumer: the Llama pretrain pipeline
(BASELINE config #4, BASELINE.json:10).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import numpy as np

from strom.delivery.extents import Extent, ExtentList


@dataclasses.dataclass(frozen=True)
class TokenShardSet:
    """A set of packed-token shards addressed as one global record array."""

    paths: tuple[str, ...]
    record_tokens: int                 # tokens per record (seq_len + 1 for LM loss)
    dtype: np.dtype = np.dtype(np.int32)
    # per-shard byte sizes, for paths that aren't plain files (e.g. aliased
    # to a RAID0 striped set via StromContext.register_striped); None → stat
    shard_sizes: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        if not self.paths:
            raise ValueError("need at least one shard")
        if self.record_tokens <= 0:
            raise ValueError("record_tokens must be positive")
        object.__setattr__(self, "paths", tuple(self.paths))
        object.__setattr__(self, "dtype", np.dtype(self.dtype))
        sizes = self.shard_sizes
        if sizes is not None:
            sizes = tuple(sizes)  # keep the frozen dataclass hashable
            object.__setattr__(self, "shard_sizes", sizes)
            if len(sizes) != len(self.paths):
                raise ValueError("shard_sizes must match paths")
        counts = []
        for i, p in enumerate(self.paths):
            nbytes = sizes[i] if sizes is not None else os.stat(p).st_size
            counts.append(nbytes // self.record_bytes)
        object.__setattr__(self, "_records_per_shard", tuple(counts))
        starts = [0]
        for c in counts:
            starts.append(starts[-1] + c)
        object.__setattr__(self, "_record_starts", tuple(starts))

    @property
    def record_bytes(self) -> int:
        return self.record_tokens * self.dtype.itemsize

    @property
    def num_records(self) -> int:
        return self._record_starts[-1]  # type: ignore[attr-defined]

    def records_in_shard(self, shard: int) -> int:
        return self._records_per_shard[shard]  # type: ignore[attr-defined]

    def locate(self, record: int) -> tuple[str, int]:
        """(shard path, byte offset) of a global record index."""
        if not 0 <= record < self.num_records:
            raise IndexError(f"record {record} out of range [0, {self.num_records})")
        starts = self._record_starts  # type: ignore[attr-defined]
        # shards are typically few; linear scan is fine and branch-predictable
        shard = 0
        while starts[shard + 1] <= record:
            shard += 1
        return self.paths[shard], (record - starts[shard]) * self.record_bytes

    def extents(self, records: Sequence[int]) -> ExtentList:
        """Gather plan for a batch of (possibly shuffled) record indices.

        Adjacent records in the same shard coalesce into one extent, so a
        sequential batch is a handful of large reads.
        """
        out: list[Extent] = []
        for r in records:
            path, off = self.locate(int(r))
            if out and out[-1].path == path and \
                    out[-1].offset + out[-1].length == off:
                out[-1] = Extent(path, out[-1].offset,
                                 out[-1].length + self.record_bytes)
            else:
                out.append(Extent(path, off, self.record_bytes))
        return ExtentList(out)

    def batch_shape(self, n_records: int) -> tuple[int, int]:
        return (n_records, self.record_tokens)
