#!/usr/bin/env python
"""strom_top — a live per-tenant dashboard over the strom HTTP surface.

``top`` for the data plane: polls a running strom process (a daemon, a
bench with ``--metrics-port``, any StromContext serving /metrics) and
renders one row per tenant — queue depth and wait, granted byte rate,
cache hit ratio, engine inflight, SLO burn rate — plus a global header.

Usage:
    python tools/strom_top.py --port 9000               # curses live view
    python tools/strom_top.py --port 9000 --once        # one plain table
    python tools/strom_top.py --url http://host:9000 --interval 1
    python tools/strom_top.py --port 9000 --cluster     # fleet view

``--cluster`` points at a coordinator serving ``/cluster`` (a context
with ``attach_cluster``, ISSUE 18) and renders one row per HOST instead
of per tenant: health, heartbeat age, goodput, peer hit ratio, queue
p99 and burn state, under a header of the federation gauges
(hosts/unhealthy/trace-linked ratio/scrape lag).

Data sources (all server-side-filtered so a poll never pays for the
expensive stall-attribution section):
- ``/stats?sections=sched,cache,tune`` — scheduler/cache/autotuner
  sections + the scoped (per-tenant labeled) registry snapshots;
- ``/tenants`` — per-tenant queue/budget rows + the slo_burning flag;
- ``/slo``     — burn rates per tenant.

Byte/step rates are computed from deltas between consecutive polls (the
server-side ``/history`` ring exists for external scrapers; strom_top
keeps its own two-sample window instead of depending on it).

Needs nothing beyond the stdlib; curses degrades to a repainted plain
table when unavailable (``--once`` never touches curses at all).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.error
import urllib.request

# columns of the per-tenant table, in render order
COLUMNS = ("tenant", "prio", "queued", "active", "wait_p99_ms",
           "granted_mb_s", "hit_pct", "burn_fast", "burn_slow", "slo")

_TENANT_LABEL = re.compile(r'tenant="([^"]+)"')


def fetch_json(base: str, route: str, timeout: float = 5.0):
    """GET one route; None on 404 (feature off) — anything else raises."""
    try:
        with urllib.request.urlopen(base + route, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def _scope_tenants(scopes: dict) -> dict[str, dict]:
    """{tenant: scoped snapshot} from the /stats scopes map (label strings
    like ``pipeline="resnet",tenant="t0"`` — tenant-only scopes win over
    refined ones so counters aren't double-read)."""
    out: dict[str, dict] = {}
    for lbl, snap in scopes.items():
        m = _TENANT_LABEL.search(lbl)
        if not m:
            continue
        name = m.group(1)
        # prefer the pure tenant scope (exact label) over refined ones
        if lbl == f'tenant="{name}"' or name not in out:
            out[name] = snap
    return out


def sample(base: str) -> dict:
    """One poll: everything the table needs, already tenant-keyed."""
    stats = fetch_json(base, "/stats?sections=sched,cache,tune") or {}
    tenants = fetch_json(base, "/tenants") or {}
    slo = fetch_json(base, "/slo") or {}
    return {
        "t": time.monotonic(),
        "global": stats.get("global", {}),
        "sections": stats.get("sections", {}),
        "scopes": _scope_tenants(stats.get("scopes", {})),
        "tenants": tenants.get("tenants", {}),
        "admission": tenants.get("admission", {}),
        "slo": slo.get("tenants", {}),
    }


def rows(cur: dict, prev: "dict | None") -> list[dict]:
    """Per-tenant table rows from one (or two, for rates) samples."""
    names = sorted(set(cur["tenants"]) | set(cur["scopes"]))
    dt = (cur["t"] - prev["t"]) if prev else 0.0
    out = []
    for name in names:
        trow = cur["tenants"].get(name, {})
        scope = cur["scopes"].get(name, {})
        srow = cur["slo"].get(name, {})
        granted = None
        if prev and dt > 0:
            b1 = scope.get("sched_granted_bytes")
            b0 = prev["scopes"].get(name, {}).get("sched_granted_bytes")
            if b1 is not None and b0 is not None:
                granted = max(b1 - b0, 0) / dt / 1e6
        hit = miss = None
        hb, mb = scope.get("cache_hit_bytes"), scope.get("cache_miss_bytes")
        if hb is not None or mb is not None:
            hit, miss = hb or 0, mb or 0
        out.append({
            "tenant": name,
            "prio": trow.get("priority", "-"),
            "queued": trow.get("queued_ops", 0),
            "active": trow.get("active_grants", 0),
            "wait_p99_ms": (scope.get("sched_queue_wait_p99_us") or 0) / 1e3,
            "granted_mb_s": granted,
            "hit_pct": (100.0 * hit / (hit + miss)
                        if hit is not None and (hit + miss) else None),
            "burn_fast": srow.get("slo_burn_fast"),
            "burn_slow": srow.get("slo_burn_slow"),
            "slo": ("BURNING" if (srow.get("slo_burning")
                                  or trow.get("slo_burning")) else "ok"),
        })
    return out


def _fmt(v, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _tune_line(tune: dict) -> "str | None":
    """One status row for the closed-loop autotuner (absent when the
    context runs without ``tune=True`` — the section simply isn't
    served)."""
    if not tune:
        return None
    state = "RUNNING" if tune.get("tune_active") else "stopped"
    return (f"tune: {state}"
            f"  profile={tune.get('tune_profile', '-') or '-'}"
            f"  x{_fmt(tune.get('tuned_vs_baseline'), 3)} vs baseline"
            f"  moves={tune.get('tune_moves', 0)}"
            f" reverts={tune.get('tune_reverts', 0)}"
            f" holds={tune.get('tune_holds', 0)}"
            f"  last: {tune.get('tune_last_move', '-') or '-'}")


def render(cur: dict, prev: "dict | None") -> str:
    """The whole screen as text (shared by --once, plain loop and curses)."""
    g = cur["global"]
    sched = cur["sections"].get("sched", {})
    lines = [
        f"strom_top  pipeline_steps={g.get('pipeline_steps', 0)}"
        f"  ssd2tpu_bytes={g.get('ssd2tpu_bytes', 0)}"
        f"  inflight={sched.get('sched_active_grants', '-')}"
        f"  queued={sched.get('sched_queued_ops', '-')}"
        f"  admission_waits={sched.get('slab_pool_admission_waits', '-')}",
    ]
    tline = _tune_line(cur["sections"].get("tune", {}))
    if tline:
        lines.append(tline)
    lines += [
        "",
        (f"{'tenant':<14}{'prio':<13}{'queued':>7}{'active':>7}"
         f"{'wait_p99_ms':>13}{'MB/s':>9}{'hit%':>7}"
         f"{'burn_f':>8}{'burn_s':>8}  slo"),
    ]
    n_header = len(lines)
    for r in rows(cur, prev):
        lines.append(
            f"{r['tenant']:<14}{r['prio']:<13}{r['queued']:>7}"
            f"{r['active']:>7}{_fmt(r['wait_p99_ms']):>13}"
            f"{_fmt(r['granted_mb_s']):>9}{_fmt(r['hit_pct']):>7}"
            f"{_fmt(r['burn_fast'], 2):>8}{_fmt(r['burn_slow'], 2):>8}"
            f"  {r['slo']}")
    if len(lines) == n_header:
        lines.append("(no tenants registered — single-tenant context?)")
    return "\n".join(lines)


def sample_cluster(base: str) -> dict:
    """One /cluster poll — the coordinator's federated fleet snapshot."""
    doc = fetch_json(base, "/cluster")
    if doc is None:
        raise RuntimeError(
            "no /cluster route (coordinator needs attach_cluster)")
    doc["t"] = time.monotonic()
    return doc


def render_cluster(cur: dict, prev: "dict | None" = None) -> str:
    """The fleet screen: federation gauges up top, one row per host."""
    lines = [
        f"strom_top --cluster  hosts={cur.get('cluster_hosts', 0)}"
        f"  unhealthy={cur.get('cluster_hosts_unhealthy', 0)}"
        f"  trace_linked={_fmt(cur.get('cluster_trace_linked_ratio'), 2)}"
        f"  scrape_lag_p99_ms="
        f"{_fmt((cur.get('cluster_scrape_lag_p99_us') or 0) / 1e3)}",
        "",
        (f"{'host':<12}{'addr':<22}{'health':<11}{'hb_age_s':>9}"
         f"{'goodput%':>10}{'peer_hit%':>11}{'queue_p99_ms':>14}"
         f"  burn"),
    ]
    n_header = len(lines)
    for name in sorted(cur.get("hosts", {})):
        h = cur["hosts"][name]
        hit = h.get("peer_hit_ratio")
        lines.append(
            f"{name:<12}{h.get('addr', '-'):<22}"
            f"{'ok' if h.get('healthy') else 'UNHEALTHY':<11}"
            f"{_fmt(h.get('age_s')):>9}"
            f"{_fmt(h.get('goodput_pct')):>10}"
            f"{_fmt(100.0 * hit if hit is not None else None):>11}"
            f"{_fmt((h.get('sched_queue_wait_p99_us') or 0) / 1e3):>14}"
            f"  {'BURNING' if h.get('slo_burning') else 'ok'}")
    if len(lines) == n_header:
        lines.append("(no hosts in the cluster view)")
    return "\n".join(lines)


def run_once(base: str, settle_s: float = 0.5, *,
             sample_fn=sample, render_fn=render) -> int:
    """Two quick polls (rates need a delta), one printed table."""
    prev = sample_fn(base)
    time.sleep(settle_s)
    cur = sample_fn(base)
    print(render_fn(cur, prev))
    return 0


def run_plain(base: str, interval: float, *,
              sample_fn=sample, render_fn=render) -> int:
    prev = None
    try:
        while True:
            cur = sample_fn(base)
            sys.stdout.write("\x1b[2J\x1b[H" + render_fn(cur, prev) + "\n")
            sys.stdout.flush()
            prev = cur
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def run_curses(base: str, interval: float, *,
               sample_fn=sample, render_fn=render) -> int:
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        prev = None
        while True:
            cur = sample_fn(base)
            scr.erase()
            for i, line in enumerate(render_fn(cur, prev).split("\n")):
                try:
                    scr.addnstr(i, 0, line, max(scr.getmaxyx()[1] - 1, 1))
                except curses.error:
                    break  # terminal shorter than the table
            scr.refresh()
            prev = cur
            t_end = time.monotonic() + interval
            while time.monotonic() < t_end:
                if scr.getch() in (ord("q"), 27):
                    return
                time.sleep(0.05)

    curses.wrapper(loop)
    return 0


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(prog="strom_top", description=__doc__)
    ap.add_argument("--url", default=None,
                    help="base URL (default http://127.0.0.1:<port>)")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one table and exit (no curses)")
    ap.add_argument("--cluster", action="store_true",
                    help="fleet view: poll the coordinator's /cluster "
                         "route, one row per host")
    args = ap.parse_args(argv)
    base = args.url or f"http://{args.host}:{args.port}"
    base = base.rstrip("/")
    fns = dict(sample_fn=sample_cluster, render_fn=render_cluster) \
        if args.cluster else {}
    try:
        if args.once:
            return run_once(base, **fns)
        try:
            import curses  # noqa: F401
        except ImportError:
            return run_plain(base, args.interval, **fns)
        if not sys.stdout.isatty():
            return run_plain(base, args.interval, **fns)
        return run_curses(base, args.interval, **fns)
    except (RuntimeError, urllib.error.URLError, ConnectionError,
            OSError) as e:
        print(f"strom_top: cannot reach {base}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
