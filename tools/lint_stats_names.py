#!/usr/bin/env python
"""Lint the global stats namespace for near-duplicate metric names.

``global_stats`` keys are created on first use, so a typo'd or restyled
name (``coalesce_ops_in`` vs ``coalesceOpsIn`` vs ``coalesce_opsin``)
silently forks a metric: the producer feeds one spelling while dashboards,
bench JSON columns and compare_rounds read the other — both "work", both
read zero half the time. This tool finds every string-literal name passed
to ``global_stats.add / observe_us / set_gauge / counter / gauge /
histogram / timer_us`` and FAILS when two distinct literals normalize to
the same name modulo case and underscores.

Since ISSUE 11 this runs on the stromlint AST core
(tools/stromlint/core.py) instead of regexes: metric names come from real
call expressions (receiver-aware — the global registry OR any scoped
view/threaded scope: ``self.scope``, ``ctx.scope``, ``pscope``,
``self._stats``; scoped writes land in the SAME aggregate namespace, so a
restyled spelling through a scope forks a metric exactly like one through
``global_stats``), f-strings contribute their literal parts, scope LABEL
keys come from real ``.scoped(...)`` keyword arguments, and the
single-sourced ``*_FIELDS``/``*_KEYS``/``*_COUNTERS`` tuples are walked
as assignments rather than bracket-matched text.

Run directly (``python tools/lint_stats_names.py``) or via the tier-1 test
that wires it into the suite (tests/test_lint_stats_names.py). Exit 0 =
clean, 1 = collisions, 2 = usage error.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from collections import defaultdict

# the stromlint AST core (shared parse/walk layer); bootstrap the repo
# root onto sys.path so this file also works when loaded standalone by
# importlib (the tier-1 test does exactly that)
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
try:
    from tools.stromlint import core as _core
except ImportError:  # loaded by path, repo root not importable yet
    sys.path.insert(0, _REPO_ROOT)
    from tools.stromlint import core as _core

# metric-writing methods on the registry / any scoped view
_METRIC_METHODS = frozenset(
    ("add", "observe_us", "set_gauge", "counter", "gauge", "histogram",
     "timer_us"))

# receiver shapes that feed the global namespace: the registry itself, or
# any scope/threaded-scope spelling (self.scope, pscope, op_scope,
# self._stats, ctx.stats — ISSUE 6: every scope write fans into the
# global series, so a restyled spelling through a scope forks a metric
# exactly like one through global_stats)
def _is_metric_receiver(recv: "str | None") -> bool:
    if recv is None:
        return False
    return (recv == "global_stats" or recv.endswith("global_stats")
            or recv.endswith("scope") or recv.endswith("_stats")
            or recv.endswith(".stats"))


# single-sourced metric-name tuples (STALL_FIELDS, CACHE_BENCH_FIELDS,
# STREAM_FIELDS, FLIGHT_FIELDS, SENTINEL_FIELDS, SCHED_FIELDS,
# DIST_FIELDS/DIST_BENCH_FIELDS (strom/dist/peers.py, ISSUE 15),
# FED_FIELDS (strom/obs/federation.py, ISSUE 18), the
# compare_rounds *_KEYS column lists, cli _DECODE_COUNTERS, ...): their
# literals name the SAME series the producers feed, so a restyled
# spelling here forks a dashboard column exactly like a restyled call
# site (ISSUE 4 satellite: bench/report columns are linted tier-1)
_FIELDS_NAME = re.compile(r"^_?[A-Z][A-Z0-9_]*_(?:FIELDS|KEYS|COUNTERS)$")

# source roots that feed the global registry
DEFAULT_ROOTS = ("strom", "tools", "bench.py")

# HTTP route literals in the live server's handlers: `path == "/metrics"`
# comparisons inside strom/obs/server.py. Every one must be documented in
# README.md — an undocumented route is an API nobody can find until they
# read the handler (ISSUE 8 satellite).
_ROUTE_LIT = re.compile(r"^/[a-z_]*$")
SERVER_SOURCE = os.path.join("strom", "obs", "server.py")
ROUTE_DOC = "README.md"


def _literal_of(node: ast.AST) -> "str | None":
    """The metric-name literal of a call's first argument: a plain string,
    or an f-string's literal parts with ``{}`` placeholders (a templated
    name like ``decode_reduced_hits_{denom}`` can still case-collide on
    its literal part)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("{}")
        return "".join(parts)
    return None


def scan_routes(root_dir: str) -> tuple[set[str], list[str]]:
    """(documented routes needed, missing-from-README routes). Routes come
    from ``path == "/..."`` comparison expressions in the server source;
    README.md is matched on the literal route string."""
    src = os.path.join(root_dir, SERVER_SOURCE)
    try:
        with open(src) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError, ValueError):
        return set(), []
    routes: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            continue
        left = _core.dotted(node.left)
        if left is None or _core.tail_of(left) != "path":
            continue
        comp = node.comparators[0]
        if isinstance(comp, ast.Constant) and isinstance(comp.value, str) \
                and _ROUTE_LIT.match(comp.value):
            routes.add(comp.value)
    routes.discard("/")  # a bare-root comparison is not an API surface
    try:
        with open(os.path.join(root_dir, ROUTE_DOC)) as f:
            readme = f.read()
    except OSError:
        readme = ""
    missing = sorted(r for r in routes if r not in readme)
    return routes, missing


def _dict_keys(node: "ast.AST | None") -> list[str]:
    """String keys of a dict LITERAL (else nothing — dynamic dicts can't
    be linted)."""
    if not isinstance(node, ast.Dict):
        return []
    return [k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)]


def _normalize(name: str) -> str:
    return name.replace("_", "").lower()


def scan_sources(root_dir: str, roots=DEFAULT_ROOTS
                 ) -> tuple[dict[str, set[tuple[str, str]]],
                            dict[str, set[tuple[str, str]]]]:
    """(metric_names, label_keys): each {normalized: {(literal, file:line),
    ...}} over every .py under *roots* (relative to *root_dir*). Metric
    names come from registry/scope call expressions AND single-sourced
    *_FIELDS/*_KEYS/*_COUNTERS tuples (FLIGHT_FIELDS, SENTINEL_FIELDS
    included — they name the same series the producers feed); label keys
    come from ``.scoped(...)`` kwargs and live in their own collision
    domain (``pipeline`` vs ``pipe_line`` would fork every labeled series
    on /metrics)."""
    found: dict[str, set[tuple[str, str]]] = defaultdict(set)
    labels: dict[str, set[tuple[str, str]]] = defaultdict(set)
    for mod in _core.load_modules(root_dir, roots):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                # label dicts at the pipeline API surface (ANY call):
                # scope={"pipeline": ..., "tenant": ...} kwargs flow
                # verbatim into scoped(**d), so their KEYS are label keys
                # exactly like scoped() kwargs
                for kw in node.keywords:
                    if kw.arg == "scope":
                        for key in _dict_keys(kw.value):
                            labels[_normalize(key)].add(
                                (key, f"{mod.rel}:{node.lineno}"))
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                recv = _core.dotted(node.func.value)
                meth = node.func.attr
                if meth in _METRIC_METHODS and _is_metric_receiver(recv) \
                        and node.args:
                    lit = _literal_of(node.args[0])
                    if lit is not None:
                        found[_normalize(lit)].add(
                            (lit, f"{mod.rel}:{node.lineno}"))
                elif meth == "scoped":
                    for kw in node.keywords:
                        if kw.arg is None:
                            # **expansion: a literal dict contributes its
                            # keys; anything dynamic is skipped
                            for key in _dict_keys(kw.value):
                                labels[_normalize(key)].add(
                                    (key, f"{mod.rel}:{node.lineno}"))
                            continue
                        labels[_normalize(kw.arg)].add(
                            (kw.arg, f"{mod.rel}:{node.lineno}"))
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if not any(isinstance(t, ast.Name)
                           and _FIELDS_NAME.match(t.id) for t in targets):
                    continue
                if node.value is None:
                    continue
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str) \
                            and "\n" not in sub.value:
                        found[_normalize(sub.value)].add(
                            (sub.value, f"{mod.rel}:{sub.lineno}"))
    return found, labels


def collisions(found: dict[str, set[tuple[str, str]]]
               ) -> list[tuple[str, set[tuple[str, str]]]]:
    """Normalized groups containing more than one DISTINCT literal."""
    out = []
    for norm, uses in sorted(found.items()):
        literals = {lit for lit, _ in uses}
        if len(literals) > 1:
            out.append((norm, uses))
    return out


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else _REPO_ROOT
    if not os.path.isdir(root):
        print(f"lint_stats_names: not a directory: {root}", file=sys.stderr)
        return 2
    found, labels = scan_sources(root)
    bad = collisions(found)
    bad_labels = collisions(labels)
    routes, undocumented = scan_routes(root)
    if not bad and not bad_labels and not undocumented:
        print(f"lint_stats_names: {len(found)} distinct metric names + "
              f"{len(labels)} scope label keys, no case/underscore "
              f"collisions; {len(routes)} server routes all documented")
        return 0
    for norm, uses in bad:
        print(f"metric name collision (normalized '{norm}'):",
              file=sys.stderr)
        for lit, where in sorted(uses):
            print(f"  {lit!r} at {where}", file=sys.stderr)
    for norm, uses in bad_labels:
        print(f"scope label key collision (normalized '{norm}'):",
              file=sys.stderr)
        for lit, where in sorted(uses):
            print(f"  {lit!r} at {where}", file=sys.stderr)
    for r in undocumented:
        print(f"undocumented server route: {r!r} handled in "
              f"{SERVER_SOURCE} but absent from {ROUTE_DOC}",
              file=sys.stderr)
    n_bad = len(bad) + len(bad_labels)
    if n_bad:
        print(f"lint_stats_names: {n_bad} collision group(s) — pick ONE "
              "spelling per metric/label", file=sys.stderr)
    if undocumented:
        print(f"lint_stats_names: {len(undocumented)} undocumented "
              f"route(s) — add them to {ROUTE_DOC}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
