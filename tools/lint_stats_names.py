#!/usr/bin/env python
"""Lint the global stats namespace for near-duplicate metric names.

``global_stats`` keys are created on first use, so a typo'd or restyled
name (``coalesce_ops_in`` vs ``coalesceOpsIn`` vs ``coalesce_opsin``)
silently forks a metric: the producer feeds one spelling while dashboards,
bench JSON columns and compare_rounds read the other — both "work", both
read zero half the time. This tool greps the source for string-literal
names passed to ``global_stats.add / observe_us / set_gauge / counter /
gauge / histogram / timer_us`` and FAILS when two distinct literals
normalize to the same name modulo case and underscores.

Run directly (``python tools/lint_stats_names.py``) or via the tier-1 test
that wires it into the suite (tests/test_lint_stats_names.py). Exit 0 =
clean, 1 = collisions, 2 = usage error.
"""

from __future__ import annotations

import os
import re
import sys
from collections import defaultdict

# literal first-argument of a metric call; f-strings count too (a templated
# name like decode_reduced_hits_{denom} can still case-collide on its
# literal part). The receiver may be the global registry OR any scoped
# view/threaded scope (self.scope, ctx.scope, pscope, self._scope,
# op_scope...): scoped writes land in the SAME aggregate namespace (ISSUE 6
# — every scope write fans into the global series), so a restyled spelling
# through a scope forks a metric exactly like one through global_stats.
_CALL = re.compile(
    r"""(?:\bglobal_stats|[A-Za-z_][\w.]*(?:scope|_stats|\.stats))\s*\.\s*
        (?:add|observe_us|set_gauge|counter|gauge|histogram|timer_us)
        \(\s*f?["']([^"']+)["']""",
    re.VERBOSE)

# label kwargs of .scoped(...) calls: scope LABEL KEYS (pipeline=, tenant=)
# are their own namespace rendered into every labeled series — `pipeline`
# vs `pipe_line` would fork the per-tenant series exactly like a restyled
# metric name, so they're linted in a separate collision domain
_SCOPED_CALL = re.compile(r"\.scoped\(\s*([^()]*)\)")
_KWARG = re.compile(r"(?:^|,)\s*(\*\*)?([A-Za-z_]\w*)\s*=")

# single-sourced metric-name tuples (STALL_FIELDS, CACHE_BENCH_FIELDS,
# STREAM_FIELDS, FLIGHT_FIELDS, SENTINEL_FIELDS, SCHED_FIELDS — the
# multi-tenant bench arm's per-tenant column suffixes, coverage asserted in
# tests/test_sched.py — the compare_rounds *_KEYS column lists, cli
# _DECODE_COUNTERS, ...): their
# literals name the SAME series the producers feed, so a restyled spelling
# here forks a dashboard column exactly like a restyled call site — scan
# every string literal inside the declaration's bracket (ISSUE 4 satellite:
# the cache bench/report columns are linted tier-1 alongside the counters)
_FIELDS_DECL = re.compile(
    r"^_?[A-Z][A-Z0-9_]*_(?:FIELDS|KEYS|COUNTERS)\s*=\s*(?:tuple|list)?\s*[\(\[]",
    re.MULTILINE)
_STR_LIT = re.compile(r"""["']([^"'\n]+)["']""")

# source roots that feed the global registry
DEFAULT_ROOTS = ("strom", "tools", "bench.py")

# HTTP route literals in the live server's handlers: `path == "/metrics"`
# comparisons inside do_GET/do_POST (strom/obs/server.py). Every one must
# be documented in README.md — an undocumented route is an API nobody can
# find until they read the handler (ISSUE 8 satellite).
_ROUTE_LIT = re.compile(r"""path\s*(?:==|!=)\s*["'](/[a-z_]*)["']""")
SERVER_SOURCE = os.path.join("strom", "obs", "server.py")
ROUTE_DOC = "README.md"


def scan_routes(root_dir: str) -> tuple[set[str], list[str]]:
    """(documented routes needed, missing-from-README routes). Routes come
    from path-comparison literals in the server source; README.md is
    matched on the literal route string."""
    src = os.path.join(root_dir, SERVER_SOURCE)
    doc = os.path.join(root_dir, ROUTE_DOC)
    try:
        with open(src) as f:
            routes = set(_ROUTE_LIT.findall(f.read()))
    except OSError:
        return set(), []
    routes.discard("/")  # a bare-root comparison is not an API surface
    try:
        with open(doc) as f:
            readme = f.read()
    except OSError:
        readme = ""
    missing = sorted(r for r in routes if r not in readme)
    return routes, missing


def _normalize(name: str) -> str:
    return name.replace("_", "").lower()


def scan_sources(root_dir: str, roots=DEFAULT_ROOTS
                 ) -> tuple[dict[str, set[tuple[str, str]]],
                            dict[str, set[tuple[str, str]]]]:
    """(metric_names, label_keys): each {normalized: {(literal, file:line),
    ...}} over every .py under *roots* (relative to *root_dir*). Metric
    names come from registry/scope calls AND single-sourced *_FIELDS/
    *_KEYS/*_COUNTERS tuples (FLIGHT_FIELDS, SENTINEL_FIELDS included —
    they name the same series the producers feed); label keys come from
    ``.scoped(...)`` kwargs and live in their own collision domain."""
    found: dict[str, set[tuple[str, str]]] = defaultdict(set)
    labels: dict[str, set[tuple[str, str]]] = defaultdict(set)
    files: list[str] = []
    for r in roots:
        p = os.path.join(root_dir, r)
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, _, names in os.walk(p):
                if "__pycache__" in dirpath:
                    continue
                files.extend(os.path.join(dirpath, n) for n in names
                             if n.endswith(".py"))
    for path in files:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root_dir)
        for m in _CALL.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            found[_normalize(m.group(1))].add((m.group(1), f"{rel}:{line}"))
        for m in _SCOPED_CALL.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            for km in _KWARG.finditer(m.group(1)):
                if km.group(1):  # **expansion: keys are dynamic, skip
                    continue
                labels[_normalize(km.group(2))].add(
                    (km.group(2), f"{rel}:{line}"))
        for m in _FIELDS_DECL.finditer(text):
            # scan to the declaration's closing bracket (nesting-aware:
            # list-comprehension tuples like STALL_FIELDS nest brackets)
            depth, end = 1, m.end()
            while end < len(text) and depth:
                c = text[end]
                if c in "([":
                    depth += 1
                elif c in ")]":
                    depth -= 1
                end += 1
            for s in _STR_LIT.finditer(text, m.end(), end):
                line = text.count("\n", 0, s.start()) + 1
                found[_normalize(s.group(1))].add(
                    (s.group(1), f"{rel}:{line}"))
    return found, labels


def collisions(found: dict[str, set[tuple[str, str]]]
               ) -> list[tuple[str, set[tuple[str, str]]]]:
    """Normalized groups containing more than one DISTINCT literal."""
    out = []
    for norm, uses in sorted(found.items()):
        literals = {lit for lit, _ in uses}
        if len(literals) > 1:
            out.append((norm, uses))
    return out


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(root):
        print(f"lint_stats_names: not a directory: {root}", file=sys.stderr)
        return 2
    found, labels = scan_sources(root)
    bad = collisions(found)
    bad_labels = collisions(labels)
    routes, undocumented = scan_routes(root)
    if not bad and not bad_labels and not undocumented:
        print(f"lint_stats_names: {len(found)} distinct metric names + "
              f"{len(labels)} scope label keys, no case/underscore "
              f"collisions; {len(routes)} server routes all documented")
        return 0
    for norm, uses in bad:
        print(f"metric name collision (normalized '{norm}'):",
              file=sys.stderr)
        for lit, where in sorted(uses):
            print(f"  {lit!r} at {where}", file=sys.stderr)
    for norm, uses in bad_labels:
        print(f"scope label key collision (normalized '{norm}'):",
              file=sys.stderr)
        for lit, where in sorted(uses):
            print(f"  {lit!r} at {where}", file=sys.stderr)
    for r in undocumented:
        print(f"undocumented server route: {r!r} handled in "
              f"{SERVER_SOURCE} but absent from {ROUTE_DOC}",
              file=sys.stderr)
    n_bad = len(bad) + len(bad_labels)
    if n_bad:
        print(f"lint_stats_names: {n_bad} collision group(s) — pick ONE "
              "spelling per metric/label", file=sys.stderr)
    if undocumented:
        print(f"lint_stats_names: {len(undocumented)} undocumented "
              f"route(s) — add them to {ROUTE_DOC}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
