"""errno-exhaustiveness: every injectable errno is classified.

``strom/faults/plan.py`` is the source of injected errnos (rule
defaults, the chaos preset, and any errno literal a plan spelling can
reach); ``strom.engine.resilience.classify_errno`` decides transient vs
permanent from two frozensets. An errno the fault plan can inject but
neither set names falls into classify_errno's "unknown → transient"
default — which is a POLICY for errnos the real world produces, not a
license for the repo's own chaos source to inject errnos nobody
classified. This pass statically collects every errno referenced in the
fault-plan module (``errno.EXXX`` attributes and ``"EXXX"`` string
literals) and fails unless each appears in TRANSIENT_ERRNOS or
PERMANENT_ERRNOS.
"""

from __future__ import annotations

import ast
import re

from tools.stromlint.core import Finding, LockModel, Module

RULE = "errno-exhaustiveness"

PLAN_REL = "strom/faults/plan.py"
RESIL_REL = "strom/engine/resilience.py"
_SETS = ("TRANSIENT_ERRNOS", "PERMANENT_ERRNOS")
_ERRNO_STR = re.compile(r"^E[A-Z0-9]{1,12}$")


def _errno_attrs(tree: ast.AST) -> "dict[str, int]":
    """{errno name: first line} for every ``errno.EXXX``/``_errno.EXXX``
    attribute in *tree*."""
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in ("errno", "_errno") \
                and _ERRNO_STR.match(node.attr):
            out.setdefault(node.attr, node.lineno)
    return out


def injectable_errnos(plan_mod: Module) -> "dict[str, int]":
    """Every errno the fault-plan module references: attribute spellings
    plus ``"EIO"``-style string literals (FaultRule accepts both)."""
    out = _errno_attrs(plan_mod.tree)
    for node in ast.walk(plan_mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _ERRNO_STR.match(node.value):
            out.setdefault(node.value, node.lineno)
    return out


def classified_errnos(resil_mod: Module) -> "set[str]":
    """Names inside the TRANSIENT_ERRNOS / PERMANENT_ERRNOS frozensets."""
    out: set[str] = set()
    for node in ast.walk(resil_mod.tree):
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if any(n in _SETS for n in names):
                out.update(_errno_attrs(node.value))
    return out


def run(modules: "list[Module]", root: str,
        model: LockModel) -> "list[Finding]":
    by_rel = {m.rel: m for m in modules}
    plan = by_rel.get(PLAN_REL)
    resil = by_rel.get(RESIL_REL)
    if plan is None:
        return []  # nothing to audit in this scan set (fixture runs)
    if resil is None:
        return [Finding(RULE, PLAN_REL, 1,
                        f"fault plan present but {RESIL_REL} (the "
                        f"classify_errno tables) is not in the scan set")]
    classified = classified_errnos(resil)
    out = []
    for name, line in sorted(injectable_errnos(plan).items()):
        if name not in classified:
            out.append(Finding(
                RULE, plan.rel, line,
                f"errno {name} is injectable by the fault plan but "
                f"appears in neither TRANSIENT_ERRNOS nor "
                f"PERMANENT_ERRNOS ({RESIL_REL}): classify it explicitly "
                f"instead of riding the unknown-errno default"))
    return out
