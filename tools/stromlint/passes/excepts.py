"""swallowed-exceptions: a broad handler must re-raise or count.

The repo's convention since PR 4: an advisory path that eats an
exception increments an ``*_errors`` counter (cache_readahead_errors,
decode_errors, ...) so "silently broken" stays distinguishable from
"nothing happened". This pass flags every broad handler —
``except Exception`` / ``except BaseException`` / bare ``except:`` —
whose body neither

- re-raises (any ``raise`` in the handler body, nested defs excluded),
  nor
- marks the error somewhere observable: a call or reference whose
  identifier mentions errors (``note_error``, ``mark_error``,
  ``logger.error``, ``self._pending_error``, ``errs.append``) or a
  string literal naming an error channel (``events.put(("error", e))``,
  ``scope.add("..._errors")``).

``contextlib.suppress(...)`` blocks are out of scope: that spelling is
an explicit, greppable statement of intent; the silent killer is the
handler that LOOKS like handling.
"""

from __future__ import annotations

import ast
import re

from tools.stromlint.core import Finding, LockModel, Module

RULE = "swallowed-exceptions"

_BROAD = ("Exception", "BaseException")
_ERRORISH = re.compile(
    r"(error|errors|errored|fail(ed|ure|s)?\b|\berr\b|^errs?$|_errs?$)",
    re.IGNORECASE)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _marks_error(body: "list[ast.stmt]") -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Name) and _ERRORISH.search(node.id):
                return True
            if isinstance(node, ast.Attribute) \
                    and _ERRORISH.search(node.attr):
                return True
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _ERRORISH.search(node.value):
                return True
    return False


def run(modules: "list[Module]", root: str,
        model: LockModel) -> "list[Finding]":
    out: list[Finding] = []
    for m in modules:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _marks_error(node.body):
                continue
            what = "bare except:" if node.type is None else \
                f"except {getattr(node.type, 'id', 'Exception')}"
            out.append(Finding(
                RULE, m.rel, node.lineno,
                f"{what} neither re-raises nor marks the error (the "
                f"repo convention is an *_errors counter / note_error "
                f"call) — a failure here is invisible"))
    return out
