"""lock-order: nested acquisitions vs the canonical hierarchy.

Checks, per module:

- every statically visible nested acquisition (``with a: ... with b:``,
  multi-item withs, and the call summaries in hierarchy.CALL_ACQUIRES)
  must go strictly DOWN the declared hierarchy — acquiring an
  equal-or-earlier-ranked lock while holding a later one is an
  inversion;
- a lock participating in a nested acquisition must be DECLARED (built
  via ``make_lock``/``make_condition`` with a name the hierarchy table
  ranks) — an undeclared pair is a finding on its own, because an
  unnamed lock is invisible to both the table and the runtime witness;
- every ``make_lock`` name must exist in the hierarchy table (the table
  stays exhaustive by construction);
- acquisitions whose lifetime is not a with-scope
  (``stack.enter_context(lock)``, bare ``lock.acquire()``) are flagged:
  the analyzer cannot bound what runs under them, so each such site
  carries a pragma with its justification (e.g. StreamingGather's
  token-lifetime engine ownership).
"""

from __future__ import annotations

import ast

from tools.stromlint import hierarchy
from tools.stromlint.core import Finding, LockModel, Module, dotted, scan_locks

RULE = "lock-order"


def run(modules: "list[Module]", root: str,
        model: LockModel) -> "list[Finding]":
    out: list[Finding] = []
    seen_undeclared_names = set()
    # 1. table exhaustiveness: every make_lock name must be ranked
    for rel, line, name in model.sites:
        if hierarchy.rank(name) is None and name not in seen_undeclared_names:
            seen_undeclared_names.add(name)
            out.append(Finding(
                RULE, rel, line,
                f"lock name '{name}' is not in the declared hierarchy "
                f"(tools/stromlint/hierarchy.py LOCK_RANKS) — add it with "
                f"a rank, or rename it to an existing role"))
    for m in modules:
        scan = scan_locks(m, model, hierarchy.CM_HOLDS,
                          call_summary=hierarchy.call_summary)
        for outer, inner in scan.pairs:
            out.extend(_check_pair(m, outer.text, outer.name,
                                   inner.text, inner.name, inner.line))
        for held, call, cls in scan.calls_under:
            fn = call.func
            recv = meth = None
            if isinstance(fn, ast.Attribute):
                recv, meth = dotted(fn.value), fn.attr
            elif isinstance(fn, ast.Name):
                meth = fn.id
            acquired: dict[str, str] = {}
            direct = hierarchy.call_summary(m.rel, recv, meth)
            if direct is not None:
                acquired[direct] = f"{recv}.{meth}()"
            if meth is not None and (recv in (None, "self")):
                # same-module helper: it acquires what its body acquires
                for name in scan.func_acquires.get((cls, meth), ()):
                    acquired.setdefault(
                        name, f"{(recv + '.') if recv else ''}{meth}() "
                              f"(helper acquires it)")
            for acq, via in acquired.items():
                for h in held:
                    out.extend(_check_pair(
                        m, h.text, h.name, via, acq,
                        call.lineno, transient=True))
        for ref in scan.unscoped:
            out.append(Finding(
                RULE, m.rel, ref.line,
                f"acquisition of '{ref.name or ref.text}' outside a "
                f"with-statement: its scope is not statically bounded, so "
                f"the lock-order analysis cannot see what runs under it"))
    return out


def _check_pair(m: Module, outer_text: str, outer_name: "str | None",
                inner_text: str, inner_name: "str | None", line: int,
                transient: bool = False) -> "list[Finding]":
    chain = " -> ".join(hierarchy.CANONICAL)
    if outer_name is None or inner_name is None:
        missing = outer_text if outer_name is None else inner_text
        return [Finding(
            RULE, m.rel, line,
            f"undeclared lock pair: '{outer_text}' -> '{inner_text}' — "
            f"'{missing}' is not built via make_lock, so the hierarchy "
            f"({chain}) cannot rank it")]
    if outer_name == inner_name:
        if transient:
            return []  # re-entering a role through a summary: not a pair
        return [Finding(
            RULE, m.rel, line,
            f"same-role nesting: '{inner_text}' acquired while already "
            f"holding a '{outer_name}' lock — two instances of one role "
            f"have no defined order")]
    ro, ri = hierarchy.rank(outer_name), hierarchy.rank(inner_name)
    if ro is None or ri is None:
        return []  # unranked names already reported at the declaration
    if ro >= ri:
        what = "call into" if transient else "acquisition of"
        return [Finding(
            RULE, m.rel, line,
            f"lock-order inversion: {what} '{inner_name}' "
            f"(rank {ri}, via {inner_text}) while holding '{outer_name}' "
            f"(rank {ro}, via {outer_text}); the canonical hierarchy is "
            f"{chain}")]
    return []
