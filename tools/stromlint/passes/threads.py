"""thread-lifecycle: every spawned thread is named and reclaimed.

The flight recorder's per-thread stack dumps (``thread_stacks``) key on
``Thread.name`` — an anonymous ``Thread-12`` in a stall bundle is a
diagnosis dead end. And a non-daemon thread nobody joins wedges
interpreter shutdown (threading._shutdown waits on it forever), which is
exactly the rc=124 shape the recorder exists to explain. So every
``threading.Thread(...)`` construction must:

- carry a stable ``name=`` (f-strings are fine — the stable prefix is
  what the stack dump needs), and
- either be daemonized (``daemon=True``) or be joined somewhere in the
  module (a close/finally path) — approximated as the module containing
  a ``.join(`` call.

Executors are covered by their own ``thread_name_prefix`` convention and
are not this pass's business.
"""

from __future__ import annotations

import ast

from tools.stromlint.core import Finding, LockModel, Module, dotted

RULE = "thread-lifecycle"


def _is_thread_join(call: ast.Call) -> bool:
    """A ``Thread.join``-shaped call: ``t.join()``, ``t.join(5)``,
    ``t.join(timeout=...)`` — NOT ``", ".join(parts)`` (str.join always
    takes exactly one iterable positional, never zero args, a numeric
    constant, or a timeout kwarg)."""
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr != "join":
        return False
    if isinstance(fn.value, ast.Constant):  # "sep".join(...)
        return False
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if not call.args and not call.keywords:
        return True
    return (len(call.args) == 1
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, (int, float)))


def run(modules: "list[Module]", root: str,
        model: LockModel) -> "list[Finding]":
    out: list[Finding] = []
    for m in modules:
        module_joins = any(isinstance(n, ast.Call) and _is_thread_join(n)
                           for n in ast.walk(m.tree))
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            text = dotted(node.func)
            if text is None or not (text == "Thread"
                                    or text.endswith("threading.Thread")):
                continue
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            if "name" not in kwargs:
                out.append(Finding(
                    RULE, m.rel, node.lineno,
                    "threading.Thread(...) without name=: the flight "
                    "recorder's stack dumps key on thread names"))
            daemon = kwargs.get("daemon")
            is_daemon = isinstance(daemon, ast.Constant) \
                and daemon.value is True
            if not is_daemon and not module_joins:
                out.append(Finding(
                    RULE, m.rel, node.lineno,
                    "thread is neither daemon=True nor joined anywhere in "
                    "this module: it can wedge interpreter shutdown"))
    return out
