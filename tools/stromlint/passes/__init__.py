"""stromlint rule passes. Each module exposes ``RULE`` (its slug) and
``run(modules, root, model) -> list[Finding]``."""

from tools.stromlint.passes import (blocking, errnos, excepts, lock_order,
                                    threads)

ALL_PASSES = (lock_order, blocking, threads, errnos, excepts)
