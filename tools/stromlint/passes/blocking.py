"""blocking-under-lock: no unbounded waits inside a held-lock body.

A blocking call under a held lock turns one slow/wedged operation into a
pile-up behind the mutex — the exact shape the flight recorder keeps
finding in stall bundles. Inside any statically visible held-lock body
(with-statement over a declared or lock-like object, including the
``sched.grant`` ownership pseudo-lock), flag:

- ``time.sleep(...)``
- ``.wait()`` with no timeout (Condition/Event/thread waits)
- ``.join()`` with no timeout
- ``.get()`` / ``.result()`` with no arguments (queue/future blocking
  reads; ``dict.get`` always has arguments, so zero-arg ``.get()`` is a
  queue)
- file/socket I/O: ``open``, blocking ``os.*`` reads/writes/syncs,
  ``socket.*``, ``.recv``/``.accept``/``.connect``/``.sendall``
- unbounded ``.poll(...)`` (no timeout argument) and ``.drain(...)``
  without a timeout keyword

``Condition.wait(timeout)`` and friends with explicit bounds pass; a
site whose wait is bounded by a different mechanism (an engine watchdog)
carries a pragma saying so.
"""

from __future__ import annotations

import ast

from tools.stromlint import hierarchy
from tools.stromlint.core import Finding, LockModel, Module, dotted, scan_locks

RULE = "blocking-under-lock"

_OS_BLOCKING = {"read", "write", "pread", "pwrite", "preadv", "pwritev",
                "fsync", "fdatasync", "sendfile", "open"}
_SOCK_METHODS = {"recv", "recvfrom", "recv_into", "accept", "connect",
                 "sendall", "makefile"}


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg and "timeout" in kw.arg for kw in call.keywords)


def run(modules: "list[Module]", root: str,
        model: LockModel) -> "list[Finding]":
    out: list[Finding] = []
    for m in modules:
        scan = scan_locks(m, model, hierarchy.CM_HOLDS)
        for held, call, _cls in scan.calls_under:
            msg = _blocking_reason(call)
            if msg is None:
                continue
            held_names = ", ".join(h.name or h.text for h in held)
            out.append(Finding(
                RULE, m.rel, call.lineno,
                f"{msg} while holding [{held_names}]"))
    return out


def _blocking_reason(call: ast.Call) -> "str | None":
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "file open()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    recv = dotted(fn.value) or ""
    meth = fn.attr
    if recv == "time" and meth == "sleep":
        return "time.sleep()"
    if recv == "os" and meth in _OS_BLOCKING:
        return f"os.{meth}() I/O"
    if recv.startswith("socket") or meth in _SOCK_METHODS:
        if meth in _SOCK_METHODS or meth == "socket":
            return f"socket I/O (.{meth})"
    if meth == "wait" and not call.args and not _has_timeout_kw(call):
        return f"unbounded {recv}.wait()"
    if meth == "join" and not call.args and not _has_timeout_kw(call):
        return f"unbounded {recv}.join()"
    if meth in ("get", "result") and not call.args \
            and not _has_timeout_kw(call):
        return f"blocking {recv}.{meth}() with no timeout"
    if meth == "poll" and len(call.args) < 3 and not _has_timeout_kw(call):
        return f"unbounded {recv}.poll()"
    if meth == "drain" and not _has_timeout_kw(call):
        return f"unbounded {recv}.drain()"
    return None
