"""stromlint: AST-based concurrency-discipline analyzer (ISSUE 11).

Five tier-1-wired passes over one shared AST core
(tools/stromlint/core.py):

- ``lock-order`` — every statically visible nested acquisition checked
  against the canonical hierarchy ``scheduler → engine → slab pool →
  hot cache → stats/ring`` (tools/stromlint/hierarchy.py); inversions,
  undeclared lock pairs, and unscoped acquisitions fail.
- ``blocking-under-lock`` — time.sleep, timeout-less waits/joins/gets,
  file/socket I/O, unbounded poll/drain inside a held-lock body.
- ``thread-lifecycle`` — every ``threading.Thread(...)`` carries
  ``name=`` (flight-recorder stack dumps key on it) and is daemonized
  or joined.
- ``errno-exhaustiveness`` — every errno the fault plan can inject is
  classified by ``resilience.classify_errno``'s tables.
- ``swallowed-exceptions`` — broad handlers must re-raise or mark the
  error (the repo's ``*_errors`` counter convention).

Suppressions: ``# stromlint: ignore[rule] -- reason`` — the reason is
mandatory (an unexplained pragma is a finding of rule ``pragma``).

CLI::

    python -m tools.stromlint --check [--json] [--select R[,R..]]
        [--ignore R[,R..]] [--paths FILE..] [ROOT]

Exit 0 = clean, 1 = findings, 2 = usage error. The dynamic complement is
``strom.utils.locks.WitnessLock`` (``STROM_DEBUG_LOCKS=1``): the static
hierarchy and the runtime lock-order witness cross-validate each other.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.stromlint.core import (DEFAULT_ROOTS, RULES, Finding, LockModel,
                                  Module, load_modules)

__all__ = ["main", "run_rules", "RULES", "Finding"]


def run_rules(root: str, *, select: "list[str] | None" = None,
              ignore: "list[str] | None" = None,
              paths: "list[str] | None" = None) -> dict:
    """Run the selected passes; returns the findings document:
    ``{"findings": [...], "suppressed": n, "files": n, "ok": bool}``.
    Findings covered by a justified pragma are dropped (counted in
    ``suppressed``); pragmas missing their ``-- reason`` surface as
    rule ``pragma`` findings, which cannot be suppressed."""
    from tools.stromlint.passes import ALL_PASSES

    wanted = set(select) if select else set(RULES)
    wanted -= set(ignore or ())
    bad = wanted - set(RULES)
    if bad:
        raise ValueError(f"unknown rule(s): {sorted(bad)} "
                         f"(rules: {', '.join(RULES)})")
    modules = load_modules(root, DEFAULT_ROOTS, paths=paths)
    by_rel = {m.rel: m for m in modules}
    model = LockModel()
    model.scan(modules)
    findings: list[Finding] = []
    suppressed = 0
    for p in ALL_PASSES:
        if p.RULE not in wanted:
            continue
        for f in p.run(modules, root, model):
            m = by_rel.get(f.path)
            if m is not None and m.suppressed(f.rule, f.line):
                suppressed += 1
                continue
            findings.append(f)
    if "pragma" in wanted:
        for m in modules:
            for line, rules in sorted(m.pragmas.items()):
                for rule, reason in sorted(rules.items()):
                    if reason is None:
                        findings.append(Finding(
                            "pragma", m.rel, line,
                            f"suppression of [{rule}] without a reason: "
                            f"write '# stromlint: ignore[{rule}] -- why'"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return {"findings": findings, "suppressed": suppressed,
            "files": len(modules), "locks": len(model.sites),
            "ok": not findings}


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="stromlint",
        description="AST concurrency-discipline analyzer for strom")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root (default: this checkout)")
    ap.add_argument("--check", action="store_true",
                    help="exit non-zero on findings (the default behavior; "
                         "the flag exists for explicit CI spelling)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings document on stdout")
    ap.add_argument("--select", default=None,
                    help="comma-separated rules to run (default: all)")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rules to skip")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="scan exactly these files/dirs instead of the "
                         "default roots (fixture tests use this)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule slugs and exit")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    if args.list_rules:
        print("\n".join(RULES))
        return 0
    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if not os.path.isdir(root):
        print(f"stromlint: not a directory: {root}", file=sys.stderr)
        return 2
    select = [s.strip() for s in args.select.split(",")] \
        if args.select else None
    ignore = [s.strip() for s in args.ignore.split(",")] \
        if args.ignore else None
    try:
        doc = run_rules(root, select=select, ignore=ignore,
                        paths=args.paths)
    except ValueError as e:
        print(f"stromlint: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({
            "ok": doc["ok"], "files": doc["files"], "locks": doc["locks"],
            "suppressed": doc["suppressed"],
            "findings": [f.doc() for f in doc["findings"]],
        }, indent=2))
    else:
        for f in doc["findings"]:
            print(f.render(), file=sys.stderr)
        if doc["ok"]:
            print(f"stromlint: {doc['files']} files, {doc['locks']} "
                  f"declared locks, {doc['suppressed']} justified "
                  f"suppression(s), 0 findings")
        else:
            print(f"stromlint: {len(doc['findings'])} finding(s)",
                  file=sys.stderr)
    return 0 if doc["ok"] else 1
