import sys

from tools.stromlint import main

if __name__ == "__main__":
    sys.exit(main())
