"""The canonical lock hierarchy (ISSUE 11) — the single declared order.

The spine is the five bands the repo's concurrency story is built
around::

    scheduler  →  engine  →  slab pool  →  hot cache  →  stats/ring

A thread holding a lock may only acquire locks of strictly HIGHER rank
(further right). Auxiliary bands slot between the spine's members:
front-door serialization (``app.*``) before everything, the resilience
and fault-injection layers (``resil.*`` / ``faults.*``) between the
scheduler and the engine they wrap, and the observability leaves
(``obs.*``) just before ``stats/ring``. Every lock the runtime
constructs via ``strom.utils.locks.make_lock(name)`` must appear here —
the lock-order pass fails on a declaration it cannot rank, so this table
stays exhaustive by construction, and the runtime witness (which learns
order from actual execution) can be diffed against it.

Two pseudo-locks model ownership windows that are not raw mutexes:
``sched.grant`` (holding an engine grant — a ``with scheduler.grant():``
body) and ``engine.internal`` (any engine method call: engines take
their own internal locks, so calling one while holding a lock ranked at
or past the engine band is an inversion).
"""

from __future__ import annotations

import re

# the documented spine, in order (ARCHITECTURE.md "Lock discipline")
CANONICAL = ("scheduler", "engine", "slab pool", "hot cache", "stats/ring")

LOCK_RANKS = {
    # -- band: app (front door; outside the spine, before everything) -------
    "app.ctx": 0,              # strom.__init__ process-default context
    "app.uring_lib": 1,        # native lib load (takes app.core_build)
    "app.core_build": 2,       # _core build/cache lock
    "app.server_cache": 3,     # MetricsServer exposition cache
    "app.files": 4,            # ctx file registry (takes engine internals)
    "app.tenant_reg": 5,       # ctx tenant registration (takes sched)
    "app.steps_cache": 6,      # ctx stall-attribution TTL cache
    "app.demand": 7,           # demand-read gate counter
    "app.put": 8,              # serialize_device_put
    "app.prefetch": 9,         # Prefetcher queue state
    "app.handle": 10,          # DMAHandle completion stamp
    "app.vision_futs": 11,     # streamed-batch decode future list
    "app.jpeg_errs": 12,       # DecodePool error tally
    "app.parquet_footer": 13,  # footer read-once (takes engine reads)
    "app.ckpt_async": 14,      # AsyncCheckpointer writer bookkeeping
                               # (ISSUE 14; holds only for latch/future
                               # swaps — commits run outside it)
    "app.tune": 15,            # Autotuner counters/state (ISSUE 16): a
                               # leaf in practice — metrics_fn and
                               # knob.set both run OUTSIDE it (metrics
                               # walks the context's stats locks)
    # -- band: scheduler -----------------------------------------------------
    "sched.arbiter": 20,       # IoScheduler._cond (the fair-drain core)
    "sched.admission": 21,     # AdmissionGate._cond
    "sched.grant": 22,         # PSEUDO: holding an engine grant
    "budget.bucket": 23,       # TokenBucket balance (taken under arbiter)
    # -- resilience wraps the engine (fallback holds while engine reads) ----
    "resil.fallback": 30,      # fallback engine creation + fi map
    "resil.fallback_serial": 31,  # one fallback gather at a time
    "resil.breaker": 32,       # circuit-breaker window
    "resil.hedge": 33,         # hedge latency reservoir
    # -- fault injection wraps the engine too --------------------------------
    "faults.proxy": 36,        # FaultyEngine bookkeeping
    "faults.plan": 37,         # FaultPlan decide/unwind
    # -- band: engine --------------------------------------------------------
    "engine.transfer": 40,     # ctx._engine_lock (whole-transfer serial)
    "engine.multi_reg": 41,    # MultiRing file registry
    "engine.multi_ring": 42,   # per-ring transfer locks
    "engine.python": 44,       # PythonEngine in-flight counter
    "engine.uring_dest": 45,   # uring dest-registration table
    "engine.internal": 46,     # PSEUDO: any engine method call
    # -- band: slab pool -----------------------------------------------------
    "slab.pool": 50,
    # -- band: hot cache -----------------------------------------------------
    "dist.directory": 55,      # ExtentDirectory dead-set/ring/epoch swap
                               # (ISSUE 20): a leaf — listdir and marker
                               # writes happen outside it, and the tier
                               # releases dist.peer before mark_dead so
                               # the two never nest
    "dist.peer": 56,           # PeerTier conn-pool checkout (ISSUE 15):
                               # NEVER held across socket I/O — the fetch
                               # checks a connection out, releases, does
                               # the wire round-trip, re-takes to return
                               # it; under it only counters move
    "dist.server": 57,         # PeerServer serve tallies (ISSUE 15): a
                               # leaf held around counter updates after
                               # the billed local read returned — never
                               # across the grant, the tiers, or the
                               # socket send
    "cache.decoded": 58,       # DecodedCache tallies (ISSUE 12): a leaf
                               # held only for counter updates, ranked
                               # before cache.meta so a tally-then-admit
                               # sequence could nest legally if it ever
                               # needed to (it doesn't today)
    "cache.spill": 59,         # SpillTier index/allocator (ISSUE 13): a
                               # sibling tier consulted AFTER cache.meta
                               # releases (never nested under it — spill
                               # pwrites/preads run outside every cache
                               # lock), writing only stats under itself
    "cache.meta": 60,
    # -- observability (leaves, but may write stats under themselves) --------
    "obs.flight": 70,
    "obs.history": 71,
    "obs.slo": 72,
    "obs.exemplars": 73,
    "obs.request_observers": 74,
    "obs.request": 75,
    "obs.federation": 76,      # ClusterView state; NEVER held across a
                               # scrape socket (poll_now fetches first,
                               # locks after), writes stats under itself
    "ops.graph": 78,           # CompiledOpGraph tally lock (ISSUE 19): a
                               # leaf on the decode pool workers guarding
                               # per-op counters only, flushed into the
                               # stats band (rank 80+) under itself
    # -- band: stats/ring (the terminal leaves) ------------------------------
    "stats.registries": 80,    # module-level registry set
    "stats.registry": 81,      # per-registry name tables
    "stats.series": 82,        # per-counter/gauge/histogram
    "ring.events": 85,         # event-ring slots
}

# context-manager methods whose with-body holds a pseudo-lock
CM_HOLDS = {
    "grant": "sched.grant",
    "engine_exclusive": "sched.grant",
}

# call summaries: a call matching (module_re, receiver_re, method_re)
# transiently acquires the named lock — the cross-subsystem acquisitions
# a with-statement walk alone cannot see (pool.release under the cache
# lock, engine reads under the fallback serializer, ...).
CALL_ACQUIRES = (
    (r".*", r"(^|\.)(_?slab_pool|pool)$", r"^(acquire|release)$",
     "slab.pool"),
    # HotCache's indirections to its backing pool
    (r"delivery/hotcache\.py$", r"^self$", r"^(_free|_alloc)$",
     "slab.pool"),
    (r".*", r"(^|\.)(_?hot_cache|cache)$",
     r"^(lookup|admit|unpin|view|clear)$", "cache.meta"),
    (r".*", r"(^|\.)(engine|inner|fb|child)$",
     r"^(read_vectored|submit_vectored|submit|submit_raw|poll|drain|"
     r"cancel|wait|close|register_file|unregister_file|register_dest|"
     r"unregister_dest|unregister_dest_addr)$", "engine.internal"),
    (r".*", r"(^|\.)(_?scheduler|sched)$",
     r"^(grant|acquire|release|register|tenant|resolve|drain|drain_all|"
     r"tenants_info)$", "sched.arbiter"),
    (r".*", r"(scope|_stats|global_stats)$",
     r"^(add|observe_us|set_gauge|counter|gauge|histogram|timer_us|"
     r"snapshot|scopes_snapshot)$", "stats.registry"),
    (r".*", r"(^|\.)(ring|_ring|_events_ring)$",
     r"^(complete|instant|flow|span|snapshot)$", "ring.events"),
    (r".*", r"(^|\.)(_?plan)$", r"^(decide|unwind)$", "faults.plan"),
)

_COMPILED = [(re.compile(mre), re.compile(rre), re.compile(fre), name)
             for mre, rre, fre, name in CALL_ACQUIRES]


def rank(name: str) -> "int | None":
    return LOCK_RANKS.get(name)


def call_summary(module_rel: str, receiver: "str | None",
                 method: "str | None") -> "str | None":
    """The lock a call transiently acquires per CALL_ACQUIRES, or None."""
    if receiver is None or method is None:
        return None
    for mre, rre, fre, name in _COMPILED:
        if mre.search(module_rel) and rre.search(receiver) \
                and fre.match(method):
            return name
    return None
