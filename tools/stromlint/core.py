"""stromlint shared AST core (ISSUE 11 tentpole).

One parse per file, shared by every pass (and by the ported stats-name
lint, tools/lint_stats_names.py): module walking, pragma handling, dotted
expression rendering, ``make_lock``/``make_condition`` declaration
discovery, and the held-lock walker that extracts every statically
visible nested acquisition plus every call made under a held lock.

Pragma format (the ONLY sanctioned suppression spelling)::

    some_code()  # stromlint: ignore[lock-order] -- reason the rule is wrong here

- ``rule`` is one of :data:`RULES` (comma-separate several).
- The ``-- reason`` clause is MANDATORY: a pragma without a written
  justification is itself a finding (rule ``pragma``), so the tree can
  lint clean only when every suppression explains itself.
- A pragma suppresses findings of its rules on its own line, or — for a
  standalone comment line — on the next code line below it (multi-line
  statements anchor findings at their first line).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

RULES = (
    "lock-order",
    "blocking-under-lock",
    "thread-lifecycle",
    "errno-exhaustiveness",
    "swallowed-exceptions",
    "pragma",
)

# source roots stromlint audits (tests are exercised separately via
# explicit paths; fixture modules under tests/lint_fixtures must never
# count against the tree)
DEFAULT_ROOTS = ("strom", "tools", "bench.py")

_PRAGMA_RE = re.compile(
    r"#\s*stromlint:\s*ignore\[([a-zA-Z0-9_,\- ]+)\]\s*(?:--\s*(\S.*))?")
_COMMENT_ONLY_RE = re.compile(r"^\s*(#.*)?$")

# with-item / acquisition heuristic for locks that did NOT come from
# make_lock: anything whose final component looks like a mutex. Such a
# lock participating in a nested acquisition is an "undeclared lock"
# finding — the fix is make_lock (which names and ranks it) or a pragma.
_LOCKLIKE_RE = re.compile(r"(^|_)(lock|locks|cond|mutex|sem)(\[\])?$",
                          re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # root-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def doc(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


class Module:
    """One parsed source file + its pragma index."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        self.lines = source.split("\n")
        # line -> {rule: reason-or-None}
        self.pragmas: dict[int, dict[str, "str | None"]] = {}
        self._comment_only: set[int] = set()
        for i, line in enumerate(self.lines, start=1):
            if _COMMENT_ONLY_RE.match(line):
                self._comment_only.add(i)
            m = _PRAGMA_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                reason = (m.group(2) or "").strip() or None
                self.pragmas[i] = {r: reason for r in rules}

    def pragma_for(self, rule: str, line: int) -> "dict | None":
        """The pragma covering findings of *rule* at *line*: same line, or
        standalone pragma comment lines directly above."""
        p = self.pragmas.get(line)
        if p is not None and (rule in p or "all" in p):
            return p
        ln = line - 1
        while ln > 0 and ln in self._comment_only:
            p = self.pragmas.get(ln)
            if p is not None and (rule in p or "all" in p):
                return p
            ln -= 1
        return None

    def suppressed(self, rule: str, line: int) -> bool:
        return self.pragma_for(rule, line) is not None


def iter_py_files(root: str, roots=DEFAULT_ROOTS) -> list[str]:
    files: list[str] = []
    for r in roots:
        p = os.path.join(root, r)
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, _dirs, names in os.walk(p):
            if "__pycache__" in dirpath:
                continue
            files.extend(os.path.join(dirpath, n) for n in sorted(names)
                         if n.endswith(".py"))
    return files


def load_modules(root: str, roots=DEFAULT_ROOTS,
                 paths: "list[str] | None" = None) -> list[Module]:
    """Parse every .py under *roots* (or exactly *paths* when given).
    Unparseable files are skipped — stromlint audits concurrency
    discipline, the interpreter audits syntax."""
    if paths is not None:
        files = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(iter_py_files(p, ("",)))
            else:
                files.append(p)
    else:
        files = iter_py_files(root, roots)
    out = []
    for path in files:
        try:
            with open(path) as f:
                src = f.read()
            rel = os.path.relpath(path, root)
            out.append(Module(path, rel, src))
        except (OSError, SyntaxError, ValueError):
            continue
    return out


def dotted(node: ast.AST) -> "str | None":
    """Render a Name/Attribute/Subscript chain: ``self._lock``,
    ``ctx._engine_lock``, ``self._ring_locks[]``. None for anything
    else (calls, literals)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    if isinstance(node, ast.Subscript):
        base = dotted(node.value)
        return None if base is None else f"{base}[]"
    return None


def tail_of(text: str) -> str:
    """Final component of a dotted rendering, subscript marker dropped."""
    t = text.rsplit(".", 1)[-1]
    return t[:-2] if t.endswith("[]") else t


def locklike(text: "str | None") -> bool:
    return text is not None and bool(_LOCKLIKE_RE.search(tail_of(text)))


# -- make_lock declaration discovery -----------------------------------------

_FACTORIES = ("make_lock", "make_condition", "_make_lock",
              "_make_condition")


class LockModel:
    """Declared locks discovered from ``make_lock("band.role")`` call
    sites: (module-rel, class-or-None, attr) → name, plus a global
    attr→names index for cross-module references (``ctx._engine_lock``
    seen from stream.py resolves through the unique global attr)."""

    def __init__(self) -> None:
        self.decls: dict[tuple[str, "str | None", str], str] = {}
        self.by_attr: dict[str, set[str]] = {}
        # (rel, line, name) per declaration, for exhaustiveness checks
        self.sites: list[tuple[str, int, str]] = []

    def scan(self, modules: "list[Module]") -> None:
        for m in modules:
            self._scan_module(m)

    @staticmethod
    def _factory_name(value: ast.AST) -> "tuple[str, int] | None":
        """(lock name, line) when *value*'s subtree contains a make_lock /
        make_condition call with a literal name (list comprehensions like
        ``[make_lock(..) for _ in range(n)]`` count)."""
        for node in ast.walk(value):
            if isinstance(node, ast.Call):
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if fname in _FACTORIES and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    return node.args[0].value, node.lineno
        return None

    def _scan_module(self, m: Module) -> None:
        def record(target: ast.AST, name: str, line: int,
                   cls: "str | None") -> None:
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                key = (m.rel, cls, target.attr)
                attr = target.attr
            elif isinstance(target, ast.Name):
                key = (m.rel, None, target.id)
                attr = target.id
            else:
                return
            self.decls[key] = name
            self.by_attr.setdefault(attr, set()).add(name)
            self.sites.append((m.rel, line, name))

        def walk(node: ast.AST, cls: "str | None") -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                    continue
                if isinstance(child, ast.Assign):
                    hit = self._factory_name(child.value)
                    if hit:
                        for t in child.targets:
                            record(t, hit[0], hit[1], cls)
                elif isinstance(child, ast.AnnAssign) and child.value:
                    hit = self._factory_name(child.value)
                    if hit:
                        record(child.target, hit[0], hit[1], cls)
                walk(child, cls)

        walk(m.tree, None)

    def resolve(self, m: Module, cls: "str | None",
                text: str) -> "str | None":
        """Declared name for a lock expression rendering, or None."""
        attr = tail_of(text)
        for key in ((m.rel, cls, attr), (m.rel, None, attr)):
            if key in self.decls:
                return self.decls[key]
        names = self.by_attr.get(attr)
        if names and len(names) == 1:
            return next(iter(names))
        return None


# -- the held-lock walker -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LockRef:
    text: str                 # source rendering ("self._lock")
    name: "str | None"        # declared make_lock name, or None
    line: int


@dataclasses.dataclass
class LockScan:
    """Per-module lock facts every pass consumes."""

    # (outer, inner) for every statically visible nested acquisition
    pairs: list = dataclasses.field(default_factory=list)
    # (held tuple, ast.Call, class-name) for every call under >=1 held lock
    calls_under: list = dataclasses.field(default_factory=list)
    # acquisitions whose lifetime is not a with-scope:
    # stack.enter_context(lock) / lock.acquire()
    unscoped: list = dataclasses.field(default_factory=list)
    # (class-or-None, func-name) -> {lock names the function acquires
    # somewhere in its body} — used for one-module interprocedural
    # propagation (a `*_locked` helper that frees a slab makes its caller
    # a cache->pool nesting even though the `with` and the free are in
    # different functions)
    func_acquires: dict = dataclasses.field(default_factory=dict)


def scan_locks(m: Module, model: LockModel,
               cm_holds: "dict[str, str] | None" = None,
               call_summary=None) -> LockScan:
    """Walk every function, tracking the with-statement held-lock stack.

    *cm_holds* maps context-manager method names to pseudo-lock names
    (``{"grant": "sched.grant"}``): a ``with x.grant(...):`` body is
    treated as holding that pseudo-lock, so engine ownership windows
    participate in ordering checks even though no raw mutex is visible.

    *call_summary* is ``hierarchy.call_summary``-shaped: ``(module_rel,
    receiver, method) -> lock-name-or-None``. When given, each
    function's transient acquisitions feed ``func_acquires``, and
    same-module ``self.helper()`` calls propagate their helper's
    acquisitions to the caller (one-module fixpoint) — this is what
    catches a ``*_locked`` helper freeing a pool slab on behalf of a
    caller that holds the cache lock.
    """
    cm_holds = cm_holds or {}
    out = LockScan()
    # (cls, func) -> [(receiver, method)] same-module call edges
    func_calls: dict[tuple, list] = {}
    cur_func: list[tuple] = []  # stack of (cls, funcname) keys

    def note_acquire(name: "str | None") -> None:
        if name is not None and cur_func:
            out.func_acquires.setdefault(cur_func[-1], set()).add(name)

    def lock_of(expr: ast.AST, cls: "str | None") -> "LockRef | None":
        text = dotted(expr)
        if text is None:
            return None
        name = model.resolve(m, cls, text)
        if name is None and not locklike(text):
            return None
        return LockRef(text, name, expr.lineno)

    def visit_stmts(stmts, held: tuple, cls: "str | None") -> None:
        for s in stmts:
            visit(s, held, cls)

    def visit(node: ast.AST, held: tuple, cls: "str | None") -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, not under the current holds
            cur_func.append((cls, node.name))
            visit_stmts(node.body, (), cls)
            cur_func.pop()
            return
        if isinstance(node, ast.ClassDef):
            visit_stmts(node.body, (), node.name)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = list(held)
            for item in node.items:
                ref = lock_of(item.context_expr, cls)
                if ref is None and isinstance(item.context_expr, ast.Call):
                    fn = item.context_expr.func
                    meth = fn.attr if isinstance(fn, ast.Attribute) else (
                        fn.id if isinstance(fn, ast.Name) else None)
                    if meth in cm_holds:
                        ref = LockRef(dotted(fn) or meth, cm_holds[meth],
                                      item.context_expr.lineno)
                    else:
                        scan_expr(item.context_expr, tuple(acquired), cls)
                elif ref is None:
                    scan_expr(item.context_expr, tuple(acquired), cls)
                if ref is not None:
                    note_acquire(ref.name)
                    for h in acquired:
                        out.pairs.append((h, ref))
                    acquired.append(ref)
            visit_stmts(node.body, tuple(acquired), cls)
            return
        # statements with nested bodies keep the current holds
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(node, field, None)
            if sub:
                for child in sub:
                    if isinstance(child, ast.ExceptHandler):
                        visit_stmts(child.body, held, cls)
                    else:
                        visit(child, held, cls)
        if not any(getattr(node, f, None)
                   for f in ("body", "orelse", "finalbody")):
            scan_expr(node, held, cls)
        else:
            # expression parts of compound statements (test, iter, items)
            for field in ("test", "iter", "subject"):
                sub = getattr(node, field, None)
                if sub is not None:
                    scan_expr(sub, held, cls)

    def scan_expr(node: ast.AST, held: tuple, cls: "str | None") -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            meth = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            # unscoped acquisitions: enter_context(lock) / lock.acquire()
            if meth == "enter_context" and sub.args:
                ref = lock_of(sub.args[0], cls)
                if ref is not None:
                    out.unscoped.append(ref)
            elif meth == "acquire" and isinstance(fn, ast.Attribute):
                recv = dotted(fn.value)
                if locklike(recv) or (
                        recv is not None
                        and model.resolve(m, cls, recv) is not None):
                    out.unscoped.append(
                        LockRef(recv, model.resolve(m, cls, recv),
                                sub.lineno))
            if isinstance(fn, ast.Attribute):
                recv = dotted(fn.value)
                if call_summary is not None:
                    note_acquire(call_summary(m.rel, recv, meth))
                if recv == "self" and cur_func:
                    func_calls.setdefault(cur_func[-1], []).append(
                        (cls, meth))
            elif isinstance(fn, ast.Name) and cur_func:
                func_calls.setdefault(cur_func[-1], []).append(
                    (cls, fn.id))
            if held:
                out.calls_under.append((held, sub, cls))

    visit_stmts(m.tree.body, (), None)
    # one-module fixpoint: a caller inherits its same-module callees'
    # acquisitions (self.helper() and bare helper() edges)
    changed = True
    while changed:
        changed = False
        for key, edges in func_calls.items():
            mine = out.func_acquires.setdefault(key, set())
            before = len(mine)
            for edge in edges:
                mine |= out.func_acquires.get(edge, set())
            if len(mine) > before:
                changed = True
    return out
