#!/usr/bin/env python
"""Compare BENCH_r*.json artifacts on the weather-independent binding set.

The driver records one bench artifact per round; absolute GB/s in them is
relay weather (>50x run-to-run swings — BASELINE.md §C), so round-over-round
comparison must use the `"binding"` sub-object (same-run ratios and stall
counts) plus a few stable context fields. This prints exactly that, one
column per round, so a judge or dashboard never has to re-derive which
fields are comparable.

Usage: python tools/compare_rounds.py [BENCH_r01.json BENCH_r02.json ...]
(no args: every BENCH_r*.json in the repo root, sorted)
"""

from __future__ import annotations

import glob
import json
import os
import sys

# binding fields first (the metric of record), then context rows that help
# interpret them; older artifacts predate some keys and print "-"
BINDING_KEYS = [
    "vs_baseline_host",
    "vs_link",
    "link_busy_frac",
    "reader_idle_frac",
    "train_data_stalls",
    "bounded_train_data_stalls",
    "resnet_predecoded_stalls",
    "resnet_predecoded_stalls_bounded",
    "vit_predecoded_stalls",
    "vit_predecoded_stalls_bounded",
]
CONTEXT_KEYS = [
    "raw_gbps",            # denominator (disk weather, NOT comparable)
    "value",               # delivered GB/s (relay weather, NOT comparable)
    "parquet_rows_per_s",
    "parquet_wide_selected_gbps",
]


def unwrap(d: dict) -> dict:
    """The driver records {'cmd', 'rc', 'parsed', 'tail', ...}; prefer the
    pre-parsed inner dict (immune to tail-window truncation), then fall
    back to scraping the JSON line out of 'tail', then to a bare bench.py
    line."""
    if isinstance(d.get("parsed"), dict) and "metric" in d["parsed"]:
        return d["parsed"]
    if "metric" in d or "tail" not in d:
        return d
    for line in reversed(str(d.get("tail", "")).splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                inner = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in inner:
                return inner
    return d


def cell(d: dict, key: str):
    binding = d.get("binding") or {}
    v = binding.get(key, d.get(key))
    if v is None:
        return "-"
    return f"{v:.4f}" if isinstance(v, float) else str(v)


def main(argv: list[str]) -> int:
    paths = argv or sorted(
        glob.glob(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_r*.json")))
    if not paths:
        print("no BENCH_r*.json artifacts found", file=sys.stderr)
        return 1
    rounds = []
    for p in paths:
        try:
            with open(p) as f:
                rounds.append((os.path.basename(p), unwrap(json.load(f))))
        except (OSError, json.JSONDecodeError) as e:
            print(f"skipping {p}: {e}", file=sys.stderr)
    if not rounds:
        return 1
    name_w = max(len(k) for k in BINDING_KEYS + CONTEXT_KEYS) + 2
    col_w = max(max(len(n) for n, _ in rounds) + 2, 12)
    header = " " * name_w + "".join(n.rjust(col_w) for n, _ in rounds)
    print(header)
    print("binding (comparable round-over-round):")
    for k in BINDING_KEYS:
        print(k.ljust(name_w)
              + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    print("context (weather / fixture-bound — NOT comparable):")
    for k in CONTEXT_KEYS:
        print(k.ljust(name_w)
              + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
