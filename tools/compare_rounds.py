#!/usr/bin/env python
"""Compare BENCH_r*.json artifacts on the weather-independent binding set.

The driver records one bench artifact per round; absolute GB/s in them is
relay weather (>50x run-to-run swings — BASELINE.md §C), so round-over-round
comparison must use the `"binding"` sub-object (same-run ratios and stall
counts) plus a few stable context fields. This prints exactly that, one
column per round, so a judge or dashboard never has to re-derive which
fields are comparable.

Usage: python tools/compare_rounds.py [BENCH_r01.json BENCH_r02.json ...]
(no args: every BENCH_r*.json in the repo root, sorted)
"""

from __future__ import annotations

import glob
import json
import os
import sys

# Binding rows come from the artifacts' own "binding" objects (r5+ JSONs
# are self-describing — VERDICT.md r4 next #8); this list only fixes the
# display ORDER for known keys, with unknown binding keys appended. Context
# rows stay a short curated set: the full "context" object is the
# complement of binding and too wide to tabulate.
BINDING_ORDER = [
    "vs_baseline_host",
    "vs_baseline_host_raid",
    "vs_link",
    "link_busy_frac",
    "reader_idle_frac",
    "train_data_stalls",
    "bounded_train_data_stalls",
    "resnet_predecoded_stalls",
    "resnet_predecoded_stalls_bounded",
    "vit_predecoded_stalls",
    "vit_predecoded_stalls_bounded",
    "parquet_plain_vs_disk",
]
CONTEXT_KEYS = [
    "raw_gbps",            # denominator (disk weather, NOT comparable)
    "value",               # delivered GB/s (relay weather, NOT comparable)
    "parquet_rows_per_s",
    "parquet_wide_selected_gbps",
    "parquet_plain_selected_gbps",
]
# decode-path rows (ISSUE 2 tentpole): JPEG vision arm throughput plus the
# counters proving which decode optimizations engaged that round. img/s here
# is fixture-bound but host-CPU-decode-bound (not relay weather), so the
# round-over-round trend of these rows IS the decode speedup.
DECODE_KEYS = [
    "resnet_images_per_s",
    "resnet_train_images_per_s",
    "vit_images_per_s",
    "vit_train_images_per_s",
    "resnet_decode_reduced_hits_2",
    "resnet_decode_reduced_hits_4",
    "resnet_decode_reduced_hits_8",
    "resnet_decode_slot_bytes",
    "resnet_decode_errors",
    "resnet_decode_put_overlap_ms",
    "resnet_decode_batch_p50_us",
]
# decode path v2 (ISSUE 12 tentpole): the native-vs-cv2 A/B epochs and the
# decoded-output-cache cold/warm pair on the JPEG vision arms.
# decode_native_vs_cv2 and decode_cache_warm_vs_cold are same-run ratios
# (weather-independent, like warm_vs_cold); decode_native_img_per_s is
# fixture-bound but host-CPU-decode-bound, so its round-over-round trend IS
# the decode speedup (the ISSUE 12 acceptance metric: >= 2x the r05
# 322 img/s baseline). The counter rows prove WHICH mechanism engaged
# (native decodes, fused runs, ROI scanlines skipped, cache hits). Suffixes
# single-sourced in strom.formats.jpeg.DECODE2_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the decode/stall/cache
# sections).
DECODE2_KEYS = [
    "resnet_decode_native_img_per_s",
    "resnet_decode_cv2_img_per_s",
    "resnet_decode_native_vs_cv2",
    "resnet_decode_native_imgs",
    "resnet_decode_native_fallbacks",
    "resnet_decode_fused_runs",
    "resnet_decode_fused_samples",
    "resnet_decode_roi_hits",
    "resnet_decode_roi_rows_skipped",
    "resnet_decode_cache_cold_img_per_s",
    "resnet_decode_cache_warm_img_per_s",
    "resnet_decode_cache_warm_vs_cold",
    "resnet_decode_cache_hit_bytes",
    "resnet_decode_cache_admitted_bytes",
    "vit_decode_native_img_per_s",
    "vit_decode_native_vs_cv2",
    "vit_decode_roi_rows_skipped",
    "vit_decode_cache_warm_img_per_s",
    "vit_decode_cache_warm_vs_cold",
]
# per-step stall attribution (ISSUE 3 tentpole): goodput_pct = the fraction
# of train-step wall the consumer spent computing (100 = the 0-stall north
# star restated), and the bucket p50s say WHICH subsystem the waits went to
# (ingest-wait split into decode / put / engine-read overlap). These are
# ratios and per-step medians of same-run timers — weather-independent, so
# the round-over-round trend IS the overlap story. This section is the tool
# the next perf PR is chosen with.
STALL_KEYS = [
    "train_goodput_pct",
    "train_step_ingest_wait_p50_us",
    "train_step_put_p50_us",
    "train_step_read_p50_us",
    "resnet_goodput_pct",
    "resnet_step_ingest_wait_p50_us",
    "resnet_step_decode_p50_us",
    "resnet_step_put_p50_us",
    "resnet_step_read_p50_us",
    "resnet_step_compute_p50_us",
    "resnet_predecoded_goodput_pct",
    "resnet_predecoded_step_ingest_wait_p50_us",
    "vit_goodput_pct",
    "vit_step_ingest_wait_p50_us",
    "vit_predecoded_goodput_pct",
]
# hot-set cache (ISSUE 4 tentpole): the cold/warm epoch pair per vision
# arm. warm_vs_cold is a same-run ratio (weather-independent: both epochs
# ride the same relay/disk state seconds apart) and the hit/miss byte
# deltas prove WHERE warm traffic came from — warm misses ~ 0 means the
# engine (and the read stall bucket) collapsed on repeat traffic. Suffixes
# are single-sourced in strom.delivery.hotcache.CACHE_BENCH_FIELDS
# (parity-tested in tests/test_compare_rounds.py, same contract as the
# decode/stall sections).
CACHE_KEYS = [
    "resnet_warm_vs_cold",
    "resnet_cold_images_per_s",
    "resnet_warm_images_per_s",
    "resnet_cache_hit_bytes",
    "resnet_cache_miss_bytes",
    "resnet_cache_readahead_bytes",
    "resnet_predecoded_warm_vs_cold",
    "resnet_predecoded_cold_images_per_s",
    "resnet_predecoded_warm_images_per_s",
    "resnet_predecoded_cache_hit_bytes",
    "resnet_predecoded_cache_miss_bytes",
    "vit_warm_vs_cold",
    "vit_cache_hit_bytes",
    "vit_cache_miss_bytes",
    "vit_predecoded_warm_vs_cold",
    "vit_predecoded_cache_hit_bytes",
]
# intra-batch streaming (ISSUE 5 tentpole): the completion-driven
# read→decode→put dataflow on the JPEG vision arms. stream_samples_early
# counts decodes dispatched while later extents were still in flight (the
# overlap, as a counter); first_decode_lat is gather-start → first decode
# dispatch (the latency the old barrier padded to the slowest extent);
# tail_extent_p50 is the first→last completion spread that work now
# overlaps. The resnet_nostream_* columns are the same arm with --no-stream
# (bit-identical batches), so resnet vs resnet_nostream ingest-wait/stall
# rows price exactly the streaming dataflow. Suffixes single-sourced in
# strom.delivery.stream.STREAM_FIELDS (parity-tested, same contract as the
# decode/stall/cache sections).
STREAM_KEYS = [
    "resnet_stream_intra_batch",
    "resnet_stream_batches",
    "resnet_stream_samples_early",
    "resnet_stream_inflight_peak",
    "resnet_stream_instant_bytes",
    "resnet_stream_first_decode_lat_p50_us",
    "resnet_stream_tail_extent_p50_us",
    "resnet_nostream_train_images_per_s",
    "resnet_nostream_data_stalls",
    "resnet_nostream_step_ingest_wait_p50_us",
    "resnet_nostream_goodput_pct",
    "vit_stream_batches",
    "vit_stream_samples_early",
    "vit_stream_first_decode_lat_p50_us",
    "vit_stream_tail_extent_p50_us",
]
# multi-tenant scheduler (ISSUE 7 tentpole): the 2-vision + 1-parquet
# concurrency arm's per-tenant columns. mt_vs_solo_mean is the aggregate
# multiplexing efficiency (mean of per-tenant concurrent/solo ratios —
# same-run, weather-independent); mt_pq_* is the light INTERACTIVE tenant
# whose bounded queue-wait p99 is the no-starvation evidence while the two
# training tenants flood the engine. Suffixes single-sourced in
# strom.sched.scheduler.SCHED_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the decode/stall/cache/
# stream sections).
SCHED_KEYS = [
    "mt_vs_solo_mean",
    "mt_pq_items_per_s",
    "mt_pq_vs_solo",
    "mt_pq_sched_queue_wait_p99_us",
    "mt_vis0_items_per_s",
    "mt_vis0_vs_solo",
    "mt_vis0_sched_queue_wait_p50_us",
    "mt_vis0_sched_queue_wait_p99_us",
    "mt_vis0_sched_granted_bytes",
    "mt_vis0_sched_throttle_waits",
    "mt_vis0_engine_op_lat_p99_us",
    "mt_vis1_items_per_s",
    "mt_vis1_vs_solo",
    "mt_vis1_sched_queue_wait_p99_us",
]
# request latency / SLO (ISSUE 8 tentpole): per-arm request-level latency
# percentiles over the traced gather/batch requests (req_lat — the
# causal-tracing req_id lane, not the per-op engine clock) and the SLO
# verdict (slo_ok = no tenant burning its error budget at arm end).
# Suffixes single-sourced in strom.obs.slo.SLO_BENCH_FIELDS
# (parity-tested in tests/test_compare_rounds.py, same contract as the
# decode/stall/cache/stream/sched sections).
SLO_KEYS = [
    "resnet_req_lat_p50_us",
    "resnet_req_lat_p99_us",
    "resnet_slo_ok",
    "vit_req_lat_p50_us",
    "vit_req_lat_p99_us",
    "vit_slo_ok",
]
# resilience / chaos (ISSUE 9 tentpole): the seeded-fault-plan resnet arm.
# chaos_ok = the run completed with batches bit-identical to fault-free
# (the whole retry/failover/hedge story as one bit); chaos_slowdown is the
# bounded price paid (same-run ratio, weather-independent); the counter
# columns prove WHICH mechanism absorbed the injected faults. Keys are
# single-sourced in strom.engine.resilience.CHAOS_BENCH_FIELDS
# (parity-tested in tests/test_compare_rounds.py, same contract as the
# decode/stall/cache/stream/sched/slo sections).
RESIL_KEYS = [
    "chaos_ok",
    "chaos_slowdown",
    "chaos_clean_images_per_s",
    "chaos_faulty_images_per_s",
    "chaos_faults_injected",
    "chaos_chunk_retries",
    "chaos_failover_reads",
    "chaos_breaker_trips",
    "chaos_hedges_fired",
]
# write path (ISSUE 13 tentpole): the checkpoint arm's engine save/restore
# of the llama train state vs the pickle baseline (ckpt_save_vs_pickle is
# a same-run ratio — weather-independent; roundtrip_ok = restored bit-
# exact through write+read) and the warm-spill epoch pair
# (spill_cache_miss_bytes = 0 is the acceptance bit: repeat traffic never
# reached the source engine; spill_hit_ratio is the tier's serve share).
# Suffixes single-sourced in strom.ckpt.checkpoint.CKPT_FIELDS and
# strom.delivery.spill.SPILL_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the other sections).
WRITE_KEYS = [
    "ckpt_bytes",
    "ckpt_save_mb_per_s",
    "ckpt_restore_mb_per_s",
    "ckpt_pickle_save_mb_per_s",
    "ckpt_save_vs_pickle",
    "ckpt_roundtrip_ok",
    "spill_hit_bytes",
    "spill_spilled_bytes",
    "spill_hit_ratio",
    "spill_cache_miss_bytes",
    "spill_promote_bytes",
    "spill_engine_ops",
]
# preemption-safe training (ISSUE 14 tentpole): the resume arm's
# kill/restart verdict (resume_ok folds bit-identity + no-epoch-replay +
# no-orphans into one bit; replayed_batches is the bounded
# un-checkpointed tail) and the async-save stall columns
# (ckpt_async_stall_frac is the same-run stall/sync-wall ratio — the
# <25% acceptance, weather-independent; stall p99 is host-memcpy-bound).
# Suffixes single-sourced in strom.ckpt.jobstate.RESUME_FIELDS and
# strom.ckpt.async_save.CKPT_ASYNC_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the other sections).
RESUME_KEYS = [
    "resume_ok",
    "resume_kill_step",
    "resume_restart_step",
    "resume_replayed_batches",
    "resume_batches_checked",
    "resume_orphan_tmps",
    "resume_wall_s",
    "ckpt_async_saves",
    "ckpt_async_stall_p99_us",
    "ckpt_async_stall_mean_us",
    "ckpt_sync_save_wall_us",
    "ckpt_async_stall_frac",
    "ckpt_async_commit_mb_per_s",
]
# distributed data plane (ISSUE 15 tentpole): the dist arm's N-process
# CPU-mesh ingest — dist_ok folds the acceptance into one bit (every
# worker exited 0 AND every per-host batch stream bit-identical to the
# single-process pipeline), dist_peer_hit_ratio is the share of assembled
# batch bytes served over the peer extent service instead of a duplicate
# SSD read (same-run ratio, weather-independent), and the wait/rtt p99s
# bound the assembly tail. Suffixes single-sourced in
# strom.dist.peers.DIST_BENCH_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the other sections).
DIST_KEYS = [
    "dist_ok",
    "dist_procs",
    "dist_items_per_s",
    "dist_single_items_per_s",
    "dist_vs_single",
    "dist_peer_hit_ratio",
    "dist_peer_hit_bytes",
    "dist_peer_served_bytes",
    "dist_engine_ingest_bytes",
    "dist_assembly_wait_p99_us",
    "dist_peer_rtt_p99_us",
]
# cluster observability (ISSUE 18): the dist arm's federation gauges —
# rank 0's ClusterView scrapes every worker's /stats during the run;
# cluster_hosts_unhealthy must be 0 on a clean run (bench_sentinel gates
# it exactly-zero), cluster_trace_linked_ratio is the share of peer
# serves that carried trace context (1.0 = every peer span flow-linked
# across hosts), and the scrape-lag p99 bounds how stale the fleet view
# can be. Suffixes single-sourced in strom.obs.federation.FED_FIELDS
# (parity-tested in tests/test_compare_rounds.py).
CLUSTER_KEYS = [
    "cluster_hosts",
    "cluster_hosts_unhealthy",
    "cluster_trace_linked_ratio",
    "cluster_scrape_lag_p99_us",
]
# kernel bypass & autotune (ISSUE 16): the tune arm's hand-vs-tuned A/B
# (tuned_vs_hand >= 1.0 is the controller contract — guarded revert plus
# a final interleaved validation means the tuner never ships knobs that
# measured worse) plus the nvme arm's SQPOLL submit-syscall A/B and the
# fixed-buffer registration coverage. Suffixes single-sourced in
# strom.tune.TUNE_BENCH_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the other sections).
TUNE_KEYS = [
    "hand_items_per_s",
    "tuned_items_per_s",
    "tuned_vs_hand",
    "tune_moves",
    "tune_reverts",
    "tune_holds",
    "engine_fixed_buf_ratio",
    "engine_unregistered_reads",
    "plain_submit_syscalls_per_gb",
    "sqpoll_submit_syscalls_per_gb",
    "sqpoll_active",
]
# near-data pushdown (ISSUE 19): the parquet arm's pushed-vs-unpushed A/B
# (pushdown_ok=1 = identical aggregates with stats-refuted row groups
# never submitted and strictly fewer bytes moved) plus the dist arm's
# compressed-vs-raw peer wire pair (comp_vs_raw > 1 = fewer bytes crossed
# the socket for the same bit-identical batches). Suffixes single-sourced
# in strom.ops.pushdown.PUSHDOWN_BENCH_FIELDS (parity-tested in
# tests/test_compare_rounds.py, same contract as the other sections).
PUSHDOWN_KEYS = [
    "pushdown_ok",
    "parquet_pushdown_rows_per_s",
    "parquet_unpushed_rows_per_s",
    "parquet_pushdown_vs_unpushed",
    "parquet_pushdown_skipped_bytes",
    "parquet_pushdown_submitted_bytes",
    "parquet_pushdown_groups_skipped",
    "parquet_pushdown_groups_total",
    "dist_peer_raw_wire_bytes",
    "dist_peer_comp_wire_bytes",
    "dist_peer_comp_vs_raw",
    "peer_comp_ratio",
]
# peer fabric v2 (ISSUE 20): the dist arm's batched-vs-unbatched transport
# A/B (dist_batch_vs_single > 1 = riding a gather's worth of peer misses
# on one round trip bought real rate at bit-identical batches), the
# per-extent round-trip cost it amortises, decoded-frame bytes served
# cluster-wide, and how well the persistent conn pool replaced per-fetch
# dials. Suffixes single-sourced in strom.dist.peers.DIST_BENCH_FIELDS
# (parity-tested in tests/test_compare_rounds.py, same contract as the
# other sections).
FABRIC_KEYS = [
    "dist_batch_vs_single",
    "dist_unbatched_items_per_s",
    "peer_rtt_per_extent_us",
    "peer_frame_hit_bytes",
    "peer_conn_reuse_ratio",
]
# per-attempt / per-pass audit arrays (VERDICT.md r4 next #3): printed so
# the best-of selection's discards are visible in the comparison too
AUDIT_SUFFIXES = ("_attempts", "_passes")


def round_status(raw: dict, unwrapped: dict) -> str:
    """"ok" or an INVALID marker for the status row: an artifact with a
    nonzero driver rc or no recoverable metrics (BENCH_r05's ``rc: 124,
    parsed: null``) keeps its column — every cell "-" — with the reason
    visible up top, instead of silently reading as "nothing measured"
    (ISSUE 6 satellite: invalid rounds are verdicts, not holes)."""
    rc = raw.get("rc")
    has_metrics = isinstance(unwrapped, dict) and (
        "metric" in unwrapped or "binding" in unwrapped)
    if rc not in (None, 0):
        return f"INVALID(rc={rc})" if has_metrics \
            else f"INVALID(rc={rc},parsed=null)"
    if not has_metrics:
        return "INVALID(no-metrics)"
    return "ok"


def unwrap(d: dict) -> dict:
    """The driver records {'cmd', 'rc', 'parsed', 'tail', ...}; prefer the
    pre-parsed inner dict (immune to tail-window truncation), then fall
    back to scraping the JSON line out of 'tail', then to a bare bench.py
    line."""
    if isinstance(d.get("parsed"), dict) and "metric" in d["parsed"]:
        return d["parsed"]
    if "metric" in d or "tail" not in d:
        return d
    for line in reversed(str(d.get("tail", "")).splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                inner = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in inner:
                return inner
    return d


def cell(d: dict, key: str):
    binding = d.get("binding") or {}
    v = binding.get(key, d.get(key))
    if v is None:
        return "-"
    return f"{v:.4f}" if isinstance(v, float) else str(v)


def main(argv: list[str]) -> int:
    paths = argv or sorted(
        glob.glob(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_r*.json")))
    if not paths:
        print("no BENCH_r*.json artifacts found", file=sys.stderr)
        return 1
    rounds = []
    statuses = []
    for p in paths:
        try:
            with open(p) as f:
                raw = json.load(f)
        except OSError as e:
            # the file itself is absent/unopenable: a usage problem, not a
            # round that ran — skip it without a column
            print(f"skipping {p}: {e}", file=sys.stderr)
            continue
        except json.JSONDecodeError as e:
            # the round RAN but its artifact is truncated/corrupt: keep the
            # column, flag it — same contract as an rc!=0 round (ISSUE 6
            # satellite: invalid rounds are verdicts, not holes)
            print(f"invalid round {p}: {e}", file=sys.stderr)
            rounds.append((os.path.basename(p), {}))
            statuses.append("INVALID(unreadable)")
            continue
        d = unwrap(raw) if isinstance(raw, dict) else {}
        rounds.append((os.path.basename(p), d if isinstance(d, dict) else {}))
        statuses.append(round_status(raw if isinstance(raw, dict) else {},
                                     d))
    if not rounds:
        return 1
    binding_keys = list(BINDING_ORDER)
    for _, d in rounds:  # self-described keys this tool predates
        for k in (d.get("binding") or {}):
            if k not in binding_keys:
                binding_keys.append(k)
    audit_keys = sorted({k for _, d in rounds for k in d
                         if k.endswith(AUDIT_SUFFIXES)
                         and isinstance(d[k], list)})

    def audit_cell(v) -> str:
        """Compact list rendering that fits a table column: int lists (stall
        attempts) join verbatim, float lists (GB/s passes) compress to a
        min..max xN range."""
        if not isinstance(v, list):
            return "-"
        if not v:
            return "[]"
        if all(isinstance(x, int) for x in v):
            return ",".join(str(x) for x in v)
        if all(isinstance(x, (int, float)) for x in v):
            return f"{min(v):.2f}..{max(v):.2f}x{len(v)}"
        return ",".join("?" if x is None else str(x) for x in v)

    audit_cells = {k: [audit_cell(d.get(k)) for _, d in rounds]
                   for k in audit_keys}

    def headline_cell(d: dict) -> str:
        # the headline-shape vision arm's gating decision (r5+): a skipped
        # arm is a decision, not a missing measurement, so show it
        h = d.get("bounded_vision_headline")
        if not isinstance(h, dict):
            return "-"
        probe = h.get("link_probe_gbps")
        probe_s = f"@{probe:.4f}" if isinstance(probe, (int, float)) else "@?"
        if h.get("attempted"):
            stalls = h.get("stalls")
            return f"ran{probe_s}:{'?' if stalls is None else stalls}st"
        return f"skip{probe_s}"

    headline_cells = [headline_cell(d) for _, d in rounds]
    have_headline = any(c != "-" for c in headline_cells)
    have_decode = any(cell(d, k) != "-" for _, d in rounds
                      for k in DECODE_KEYS)
    have_decode2 = any(cell(d, k) != "-" for _, d in rounds
                       for k in DECODE2_KEYS)
    have_stall = any(cell(d, k) != "-" for _, d in rounds
                     for k in STALL_KEYS)
    have_cache = any(cell(d, k) != "-" for _, d in rounds
                     for k in CACHE_KEYS)
    have_stream = any(cell(d, k) != "-" for _, d in rounds
                      for k in STREAM_KEYS)
    have_sched = any(cell(d, k) != "-" for _, d in rounds
                     for k in SCHED_KEYS)
    have_slo = any(cell(d, k) != "-" for _, d in rounds
                   for k in SLO_KEYS)
    have_resil = any(cell(d, k) != "-" for _, d in rounds
                     for k in RESIL_KEYS)
    have_write = any(cell(d, k) != "-" for _, d in rounds
                     for k in WRITE_KEYS)
    have_resume = any(cell(d, k) != "-" for _, d in rounds
                      for k in RESUME_KEYS)
    have_dist = any(cell(d, k) != "-" for _, d in rounds
                    for k in DIST_KEYS)
    have_cluster = any(cell(d, k) != "-" for _, d in rounds
                       for k in CLUSTER_KEYS)
    have_tune = any(cell(d, k) != "-" for _, d in rounds
                    for k in TUNE_KEYS)
    have_pushdown = any(cell(d, k) != "-" for _, d in rounds
                        for k in PUSHDOWN_KEYS)
    have_fabric = any(cell(d, k) != "-" for _, d in rounds
                      for k in FABRIC_KEYS)
    name_w = max(len(k) for k in binding_keys + CONTEXT_KEYS + DECODE_KEYS
                 + DECODE2_KEYS + STALL_KEYS + CACHE_KEYS + STREAM_KEYS
                 + SCHED_KEYS + SLO_KEYS + RESIL_KEYS + WRITE_KEYS
                 + RESUME_KEYS + DIST_KEYS + CLUSTER_KEYS + TUNE_KEYS
                 + PUSHDOWN_KEYS + FABRIC_KEYS + audit_keys) + 2
    # every rendered cell folds into ONE column width, or rows misalign
    col_w = max(max(len(n) for n, _ in rounds) + 2, 12,
                *(len(c) + 2 for cs in audit_cells.values() for c in cs),
                *(len(c) + 2 for c in headline_cells),
                *(len(s) + 2 for s in statuses),
                2)
    header = " " * name_w + "".join(n.rjust(col_w) for n, _ in rounds)
    print(header)
    # round validity first: an INVALID column explains a row of "-" cells
    # before anyone misreads them as "nothing measured that round"
    print("round".ljust(name_w)
          + "".join(s.rjust(col_w) for s in statuses))
    print("binding (comparable round-over-round):")
    for k in binding_keys:
        print(k.ljust(name_w)
              + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    print("context (weather / fixture-bound — NOT comparable):")
    for k in CONTEXT_KEYS:
        print(k.ljust(name_w)
              + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_decode:
        print("decode path (vision JPEG arms: img/s + which decode "
              "optimizations engaged):")
        for k in DECODE_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_decode2:
        print("decode v2 (native-vs-cv2 A/B + decoded-cache cold/warm "
              "pair; ratios are same-run):")
        for k in DECODE2_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_stall:
        print("stall attribution (per-step goodput + where the waits "
              "went; 100 goodput = 0-stall):")
        for k in STALL_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_cache:
        print("hot-set cache (cold/warm epoch pair: warm serves from RAM; "
              "warm miss ~0 = read bucket collapsed):")
        for k in CACHE_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_stream:
        print("streaming (completion-driven intra-batch dataflow; "
              "resnet vs resnet_nostream rows are the A/B):")
        for k in STREAM_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_sched:
        print("multi-tenant (2 vision + 1 parquet tenant concurrent; "
              "bounded mt_pq queue-wait p99 = no starvation):")
        for k in SCHED_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_slo:
        print("request latency / SLO (traced request p50/p99 per arm; "
              "slo_ok=1 = no tenant burning):")
        for k in SLO_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_resil:
        print("resilience (seeded chaos arm: chaos_ok=1 = completed "
              "bit-identical under injected faults):")
        for k in RESIL_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_write:
        print("write path (engine checkpoint vs pickle + warm-spill "
              "epoch; spill_cache_miss_bytes=0 = zero source reads):")
        for k in WRITE_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_resume:
        print("resume (kill/restart harness: resume_ok=1 = bit-identical "
              "continue, no epoch replay, no orphans; async-save stall "
              "vs sync wall):")
        for k in RESUME_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_dist:
        print("distributed (N-process data plane: dist_ok=1 = bit-identical "
              "to single-process; peer_hit_ratio = batch bytes served "
              "peer-to-peer, not re-read from SSD):")
        for k in DIST_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_cluster:
        print("cluster obs (rank-0 federation over every worker's /stats: "
              "hosts_unhealthy=0 = clean fleet; trace_linked_ratio = peer "
              "serves carrying cross-host trace context):")
        for k in CLUSTER_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_tune:
        print("kernel bypass & autotune (tuned_vs_hand >= 1.0 = closed-loop "
              "tuner never ships worse than the hand knobs; SQPOLL A/B = "
              "submit syscalls/GB with and without the kernel poller):")
        for k in TUNE_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_pushdown:
        print("near-data pushdown (pushed-vs-unpushed parquet scan + "
              "compressed-vs-raw peer wire: pushdown_ok=1 = identical "
              "aggregates, refuted groups never submitted):")
        for k in PUSHDOWN_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if have_fabric:
        print("peer fabric v2 (batched-vs-unbatched transport A/B at "
              "bit-identical batches; rtt/extent = amortised round-trip "
              "cost; conn_reuse = pooled dials avoided):")
        for k in FABRIC_KEYS:
            print(k.ljust(name_w)
                  + "".join(cell(d, k).rjust(col_w) for _, d in rounds))
    if audit_keys:
        print("audit (per-attempt/per-pass lists behind each best-of):")
        for k in audit_keys:
            print(k.ljust(name_w)
                  + "".join(c.rjust(col_w) for c in audit_cells[k]))
    if have_headline:
        print("headline vision arm (ran@probe_gbps:stalls | skip@probe):")
        print("bounded_vision_headline".ljust(name_w)
              + "".join(c.rjust(col_w) for c in headline_cells))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
