#!/usr/bin/env python
"""Bench regression sentinel: turn the BENCH_r*.json trajectory into a gate.

The repo accumulates one bench artifact per round (plus MULTICHIP_r*.json
from the dry-run lowering sweep), and until now nothing READ the sequence:
r05 shipped ``rc: 124, parsed: null`` and no machinery noticed. This tool
loads the whole trajectory, normalizes each round through the SAME
single-sourced field tuples ``tools/compare_rounds.py`` renders (the
weather-independent comparison set — absolute GB/s is relay weather,
BASELINE.md §C), and emits:

- a **markdown trajectory table** per metric (one column per round),
- a **machine verdict JSON** (``--json`` / stdout in ``--check``):
  per-round validity, per-metric regression flags, and one overall verdict,
- a **nonzero exit** when any round is invalid (``rc != 0`` or
  ``parsed: null`` — a round that produced no evidence is a failure, not a
  hole in the table) or the newest valid round regressed beyond the noise
  band against BOTH the previous valid round and the best of history
  (single-round noise shouldn't page anyone; a real regression is worse
  than everything before it).

Invalid artifacts are first-class verdicts: the sentinel never crashes on
them (that would make the watchdog die exactly when the patient does).
``--known-invalid`` grandfathers named artifacts (the tier-1 wiring lists
BENCH_r05.json, whose invalidity predates the sentinel) so the suite gates
FUTURE rounds without re-flagging history.

Usage:
    python tools/bench_sentinel.py [artifacts...] [--band 0.25]
        [--json OUT.json] [--check] [--known-invalid NAME ...]
(no artifacts: every BENCH_r*.json and MULTICHIP_r*.json in the repo root)

Exit codes: 0 = clean, 1 = regression and/or non-grandfathered invalid
round, 2 = usage error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)  # runnable as a script from anywhere

from compare_rounds import (BINDING_ORDER, CACHE_KEYS, CLUSTER_KEYS,  # noqa: E402
                            DECODE2_KEYS, DECODE_KEYS, DIST_KEYS,
                            FABRIC_KEYS, RESIL_KEYS, RESUME_KEYS, SLO_KEYS,
                            STALL_KEYS, STREAM_KEYS, TUNE_KEYS, WRITE_KEYS,
                            unwrap)

# The gated metric set: (metric, direction) over the single-sourced
# comparison tuples, where direction is "up" (bigger is better) or "down"
# (smaller is better). Only weather-independent metrics are gated —
# compare_rounds' binding/stall/cache/stream sections — plus the decode
# img/s trend (fixture-bound but host-CPU-bound, the ISSUE 2/3 headline).
# Metrics not listed here still PRINT in the trajectory table; they just
# never fail the gate. Single-sourced (linted by tools/lint_stats_names.py
# alongside FLIGHT_FIELDS) so a restyled spelling can't fork the gate from
# the producers.
SENTINEL_FIELDS = (
    ("vs_baseline_host", "up"),
    ("vs_link", "up"),
    ("link_busy_frac", "up"),
    ("train_data_stalls", "down"),
    ("bounded_train_data_stalls", "down"),
    ("resnet_predecoded_stalls", "down"),
    ("resnet_predecoded_stalls_bounded", "down"),
    ("vit_predecoded_stalls", "down"),
    ("vit_predecoded_stalls_bounded", "down"),
    ("resnet_images_per_s", "up"),
    ("resnet_train_images_per_s", "up"),
    ("vit_images_per_s", "up"),
    ("vit_train_images_per_s", "up"),
    ("train_goodput_pct", "up"),
    ("resnet_goodput_pct", "up"),
    ("resnet_predecoded_goodput_pct", "up"),
    ("vit_goodput_pct", "up"),
    ("resnet_warm_vs_cold", "up"),
    ("vit_warm_vs_cold", "up"),
    ("resnet_stream_samples_early", "up"),
    # request-level latency (ISSUE 8): the traced-request p99 per vision
    # arm — the end-to-end "how long did one batch's data take" clock the
    # per-op engine histograms can't see (queue + cache + decode + put
    # included). Host-CPU-bound on the fixture, so gated like the decode
    # img/s trend; slo_ok is the burn-rate verdict (1 = no tenant burning)
    ("resnet_req_lat_p99_us", "down"),
    ("vit_req_lat_p99_us", "down"),
    ("resnet_slo_ok", "up"),
    ("vit_slo_ok", "up"),
    # chaos arm (ISSUE 9): the run must keep completing bit-identical
    # under the seeded fault plan (chaos_ok is 0/1 — any drop fails), and
    # the slowdown paid for absorbing the injected faults stays bounded
    # (same-run ratio, weather-independent)
    ("chaos_ok", "up"),
    ("chaos_slowdown", "down"),
    # decode path v2 (ISSUE 12): the native+fused+ROI decode arm's img/s
    # (fixture-bound but host-CPU-decode-bound, gated like the other
    # decode img/s trends — the acceptance metric is >= 2x the r05
    # 322 img/s baseline) and the decoded-output cache's warm/cold ratio
    # (same-run, weather-independent)
    ("resnet_decode_native_img_per_s", "up"),
    ("resnet_decode_cache_warm_vs_cold", "up"),
    ("vit_decode_native_img_per_s", "up"),
    ("vit_decode_cache_warm_vs_cold", "up"),
    # write path (ISSUE 13): engine checkpoint save rate (host-CPU +
    # NVMe-bound on the fixture box, gated like the decode img/s trends;
    # the acceptance metric is beating the pickle baseline) and the spill
    # tier's serve share on the warm epoch (same-run ratio,
    # weather-independent; a shrinking ratio means evictions stopped
    # demoting or the consult stopped finding them)
    ("ckpt_save_mb_per_s", "up"),
    ("ckpt_roundtrip_ok", "up"),
    ("spill_hit_ratio", "up"),
    # preemption safety (ISSUE 14): the kill/restart harness verdict is
    # 0/1 — any drop from 1 fails the gate outright — and the async
    # save's training-thread stall must stay a small fraction of the
    # sync save wall (the <25% acceptance; stall_frac is a same-run
    # ratio, weather-independent, banded relatively like chaos_slowdown)
    ("resume_ok", "up"),
    ("ckpt_async_stall_frac", "down"),
    ("ckpt_async_stall_p99_us", "down"),
    # distributed data plane (ISSUE 15): the dist arm's verdict is 0/1 —
    # every worker bit-identical to the single-process pipeline, any drop
    # fails outright — and the peer-hit ratio (share of assembled batch
    # bytes served peer-to-peer instead of duplicate SSD reads) is a
    # same-run ratio of a SEEDED row stream, so a shrink means the peer
    # tier stopped serving, not weather
    ("dist_ok", "up"),
    ("dist_peer_hit_ratio", "up"),
    # kernel bypass + autotuner (ISSUE 16): tuned_vs_hand is a same-run
    # interleaved A/B ratio (weather-independent — the tuner's contract
    # is never shipping knobs that measured worse, so a drop below ~1.0
    # is a controller bug, not noise) and the SQPOLL arm's submit
    # syscalls/GB is a same-run count per byte (the kernel poller either
    # absorbs submissions or it doesn't — a rise means the probe fell
    # back or the poller stopped keeping up)
    ("tuned_vs_hand", "up"),
    ("sqpoll_submit_syscalls_per_gb", "down"),
    # cluster observability (ISSUE 18): the federation's trace-linked
    # ratio is a same-run ratio of a deterministic peer-fetch stream (a
    # shrink means peers stopped carrying trace context, not weather).
    # cluster_hosts_unhealthy is NOT here: the count-sized ABS_SLACK
    # would wave a 0 -> 1 flip through, and one dark host is exactly the
    # page — it gates exactly-zero via EXACT_ZERO_FIELDS below.
    ("cluster_trace_linked_ratio", "up"),
    # near-data pushdown (ISSUE 19): pushdown_ok is 0/1 — identical
    # aggregates pushed-vs-unpushed with refuted groups never submitted,
    # any drop fails outright; skipped_bytes counts a SEEDED monotone
    # fixture's refuted row groups (a shrink means the planner stopped
    # refuting, not weather); peer_comp_ratio is the codec's raw/wire
    # ratio over a seeded peer stream (a shrink means serves stopped
    # compressing or fell back)
    ("pushdown_ok", "up"),
    ("parquet_pushdown_skipped_bytes", "up"),
    ("peer_comp_ratio", "up"),
    # peer fabric v2 (ISSUE 20): batched-vs-unbatched transport rate over
    # the same seeded fleet — a same-run interleaved A/B ratio
    # (weather-independent; a drop toward 1.0 means the batch wire
    # stopped amortising round trips, not noise). dist_ok above keeps
    # gating bit-identity for the batched pass itself.
    ("dist_batch_vs_single", "up"),
)

# metrics where ANY nonzero value in the newest valid round fails the
# gate outright — no band, no slack, no history vote. A fleet with one
# unhealthy host is a red run even if the previous round also had one.
EXACT_ZERO_FIELDS = ("cluster_hosts_unhealthy",)

# absolute slack for count-like "down" metrics around small values: going
# 0 -> 1 stall is jitter, not a regression (the llama stall phase is
# best-of-3 for exactly this reason); 0 -> above the slack still fails
ABS_SLACK = 2.0

# "down" metrics that are RATIOS near 1.0, not counts: the count-sized
# ABS_SLACK would swamp them (chaos_slowdown ~1.2 could reach ~3.2 before
# the gate fired) — they band relatively, like the "up" direction.
# ckpt_async_stall_frac is a <1 ratio for the same reason.
RATIO_DOWN = frozenset({"chaos_slowdown", "ckpt_async_stall_frac"})

TABLE_KEYS = list(dict.fromkeys(
    BINDING_ORDER + DECODE_KEYS + DECODE2_KEYS + STALL_KEYS + CACHE_KEYS
    + STREAM_KEYS + SLO_KEYS + RESIL_KEYS + WRITE_KEYS + RESUME_KEYS
    + DIST_KEYS + CLUSTER_KEYS + TUNE_KEYS + FABRIC_KEYS))


def load_round(path: str) -> dict:
    """One artifact -> {'name', 'valid', 'reason', 'rc', 'data'}.

    Invalid (rc != 0, parsed null with nothing recoverable, unreadable
    file, truncated JSON) is a VERDICT, not an exception."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"name": name, "valid": False,
                "reason": f"unreadable: {e}", "rc": None, "data": {}}
    if not isinstance(raw, dict):
        return {"name": name, "valid": False,
                "reason": f"not an object: {type(raw).__name__}",
                "rc": None, "data": {}}
    rc = raw.get("rc")
    data = unwrap(raw)
    has_metrics = isinstance(data, dict) and (
        "metric" in data or "binding" in data)
    if rc not in (None, 0):
        return {"name": name, "valid": False,
                "reason": f"rc={rc}"
                + ("" if has_metrics else ", parsed=null"),
                "rc": rc, "data": data if has_metrics else {}}
    if not has_metrics:
        return {"name": name, "valid": False,
                "reason": "no parsed metrics (parsed=null, no JSON in tail)",
                "rc": rc, "data": {}}
    return {"name": name, "valid": True, "reason": "", "rc": rc,
            "data": data}


def load_multichip(path: str) -> dict:
    """MULTICHIP_r*.json rounds carry {n_devices, rc, ok, skipped}: valid
    when rc == 0; the gated quantity is the ok-count trend. Rounds whose
    dryrun tail carries the MEASURED multi-process ingest line (ISSUE 15:
    ``dist ok: procs=N items_per_s=X peer_hit_ratio=Y``) surface those
    numbers as dist_* columns — the artifact family graduates from
    "lowered OK" to measured ingest rates with a peer-hit ratio."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"name": name, "valid": False,
                "reason": f"unreadable: {e}", "rc": None, "data": {}}
    rc = raw.get("rc")
    if rc not in (None, 0):
        return {"name": name, "valid": False, "reason": f"rc={rc}",
                "rc": rc, "data": {}}
    data = {"multichip_ok": raw.get("ok"),
            "multichip_skipped": raw.get("skipped"),
            "multichip_n_devices": raw.get("n_devices")}
    m = re.search(r"dist ok: procs=(\d+) items_per_s=([\d.]+) "
                  r"peer_hit_ratio=([\d.]+)", str(raw.get("tail", "")))
    if m:
        data["dist_ok"] = 1
        data["dist_procs"] = int(m.group(1))
        data["dist_items_per_s"] = float(m.group(2))
        data["dist_peer_hit_ratio"] = float(m.group(3))
    return {"name": name, "valid": True, "reason": "", "rc": rc,
            "data": data}


def metric_value(data: dict, key: str):
    binding = data.get("binding") or {}
    v = binding.get(key, data.get(key))
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
        else None


def check_metric(key: str, direction: str, series: list[tuple[str, float]],
                 band: float) -> dict | None:
    """Regression verdict for one metric over the VALID rounds carrying it.

    The newest value regresses when it's worse than BOTH the previous
    value and the best of all history by more than the noise *band*
    (relative), with ``ABS_SLACK`` absolute slack for near-zero "down"
    counters. One noisy round against a good history doesn't fire; a new
    worst-in-history does."""
    if len(series) < 2:
        return None
    (prev_name, prev), (last_name, last) = series[-2], series[-1]
    history = [v for _, v in series[:-1]]
    best = max(history) if direction == "up" else min(history)

    def worse_than(v: float, ref: float) -> bool:
        if direction == "up":
            return v < ref * (1.0 - band)
        slack = abs(ref) * band if key in RATIO_DOWN \
            else max(abs(ref) * band, ABS_SLACK)
        return v > ref + slack

    if worse_than(last, prev) and worse_than(last, best):
        return {"metric": key, "direction": direction,
                "latest_round": last_name, "latest": last,
                "previous_round": prev_name, "previous": prev,
                "best": best, "band": band}
    return None


def fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def trajectory_table(rounds: list[dict], keys: list[str]) -> str:
    """Markdown table: one row per metric, one column per round; invalid
    rounds keep their column with an INVALID header row so a hole in the
    trajectory is visible, never silent."""
    names = [r["name"].replace("BENCH_", "").replace("MULTICHIP_", "mc_")
             .replace(".json", "") for r in rounds]
    lines = ["| metric | " + " | ".join(names) + " |",
             "|---" * (len(rounds) + 1) + "|",
             "| _round status_ | " + " | ".join(
                 "ok" if r["valid"] else f"**INVALID** ({r['reason']})"
                 for r in rounds) + " |"]
    for k in keys:
        vals = [metric_value(r["data"], k) for r in rounds]
        if all(v is None for v in vals):
            continue
        lines.append(f"| {k} | " + " | ".join(fmt(v) for v in vals) + " |")
    return "\n".join(lines)


def run_sentinel(paths: list[str], *, band: float,
                 known_invalid: set[str],
                 grandfather_through: str | None = None) -> dict:
    """The whole verdict as one JSON-able dict (the machine artifact).

    *grandfather_through* (an artifact basename, e.g. ``BENCH_r05.json``)
    marks everything up to and including that round as BASELINE: those
    rounds still feed the history every later round is judged against, but
    their own invalidity/regressions no longer gate — the CI wiring pins
    the history that predates the sentinel and gates only future rounds."""
    bench_rounds = [load_round(p) for p in paths
                    if "MULTICHIP" not in os.path.basename(p).upper()]
    mc_rounds = [load_multichip(p) for p in paths
                 if "MULTICHIP" in os.path.basename(p).upper()]
    rounds = bench_rounds + mc_rounds

    def grandfathered(name: str) -> bool:
        if name in known_invalid:
            return True
        if grandfather_through is None:
            return False
        # rounds sort lexically (rNN zero-padded); compare within the same
        # artifact family so MULTICHIP names don't cross-compare to BENCH
        gf = grandfather_through
        fam = gf.split("_r")[0]
        return name.startswith(fam) and name <= gf

    invalid = [r for r in rounds if not r["valid"]]
    gating_invalid = [r for r in invalid if not grandfathered(r["name"])]

    regressions = []
    valid_bench = [r for r in bench_rounds if r["valid"]]
    for key, direction in SENTINEL_FIELDS:
        series = [(r["name"], metric_value(r["data"], key))
                  for r in valid_bench]
        series = [(n, v) for n, v in series if v is not None]
        hit = check_metric(key, direction, series, band)
        if hit is not None:
            hit["grandfathered"] = grandfathered(hit["latest_round"])
            regressions.append(hit)
    # exact-zero gate: the newest valid round carrying the metric must
    # report exactly 0 — banded check_metric can't catch a 0 -> 1 flip
    # (ABS_SLACK exists for count jitter; an unhealthy host isn't jitter)
    for key in EXACT_ZERO_FIELDS:
        series = [(r["name"], metric_value(r["data"], key))
                  for r in valid_bench]
        series = [(n, v) for n, v in series if v is not None]
        if series and series[-1][1] != 0:
            last_name, last = series[-1]
            prev_name, prev = series[-2] if len(series) > 1 \
                else (None, None)
            regressions.append({
                "metric": key, "direction": "zero",
                "latest_round": last_name, "latest": last,
                "previous_round": prev_name, "previous": prev,
                "best": 0, "band": 0.0,
                "grandfathered": grandfathered(last_name)})
    # multichip gate: ok-count may not shrink round-over-round (a config
    # that stopped lowering is a regression even at rc=0)
    valid_mc = [(r["name"], r["data"].get("multichip_ok"))
                for r in mc_rounds
                if r["valid"] and isinstance(r["data"].get("multichip_ok"),
                                             (int, float))]
    if len(valid_mc) >= 2 and valid_mc[-1][1] < valid_mc[-2][1]:
        regressions.append({
            "metric": "multichip_ok", "direction": "up",
            "latest_round": valid_mc[-1][0], "latest": valid_mc[-1][1],
            "previous_round": valid_mc[-2][0], "previous": valid_mc[-2][1],
            "best": max(v for _, v in valid_mc[:-1]), "band": 0.0,
            "grandfathered": grandfathered(valid_mc[-1][0])})
    gating_regressions = [h for h in regressions if not h["grandfathered"]]
    ok = not gating_regressions and not gating_invalid
    return {
        "verdict": "ok" if ok else "fail",
        "band": band,
        "rounds": [{k: r[k] for k in ("name", "valid", "reason", "rc")}
                   for r in rounds],
        "invalid_rounds": [r["name"] for r in invalid],
        "grandfathered_invalid": sorted(
            r["name"] for r in invalid if grandfathered(r["name"])),
        "regressions": regressions,
        "_rounds_full": rounds,  # stripped before JSON emit
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="bench trajectory regression sentinel")
    ap.add_argument("artifacts", nargs="*",
                    help="BENCH_r*.json / MULTICHIP_r*.json paths "
                         "(default: repo root sweep)")
    ap.add_argument("--band", type=float, default=0.25,
                    help="relative noise band before a worse value counts "
                         "as a regression (default 0.25: same-run ratios "
                         "jitter; the gate is for step changes)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the machine verdict JSON here")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: print the verdict JSON on stdout "
                         "(table goes to stderr) and exit by verdict")
    ap.add_argument("--known-invalid", nargs="*", default=[],
                    dest="known_invalid", metavar="NAME",
                    help="artifact basenames whose invalidity predates the "
                         "sentinel (still reported, no longer gating)")
    ap.add_argument("--grandfather-through", default=None,
                    dest="grandfather_through", metavar="NAME",
                    help="treat rounds up to and including this basename "
                         "as baseline: they feed history but their own "
                         "verdicts never gate (the tier-1 wiring pins the "
                         "pre-sentinel history here)")
    args = ap.parse_args(argv)

    paths = args.artifacts
    if not paths:
        root = os.path.dirname(_HERE)
        paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))) + \
            sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    if not paths:
        print("bench_sentinel: no artifacts found", file=sys.stderr)
        return 2

    verdict = run_sentinel(paths, band=args.band,
                           known_invalid=set(args.known_invalid),
                           grandfather_through=args.grandfather_through)
    rounds = verdict.pop("_rounds_full")

    table = trajectory_table(rounds, TABLE_KEYS)
    out = sys.stderr if args.check else sys.stdout
    print("## bench trajectory (weather-independent comparison set)",
          file=out)
    print(table, file=out)
    print(file=out)
    if verdict["regressions"]:
        print("### regressions (beyond the "
              f"{verdict['band']:.0%} noise band, vs previous AND "
              "best-of-history)", file=out)
        for hit in verdict["regressions"]:
            grand = " [grandfathered]" if hit.get("grandfathered") else ""
            print(f"- **{hit['metric']}**: {fmt(hit['latest'])} "
                  f"({hit['latest_round']}) vs prev {fmt(hit['previous'])} "
                  f"({hit['previous_round']}), best {fmt(hit['best'])}"
                  f"{grand}", file=out)
    for r in rounds:
        if not r["valid"]:
            grand = " [grandfathered]" \
                if r["name"] in verdict["grandfathered_invalid"] else ""
            print(f"- invalid round: {r['name']} — {r['reason']}{grand}",
                  file=out)
    print(f"verdict: {verdict['verdict']}", file=out)

    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(verdict, f, indent=1)
    if args.check:
        json.dump(verdict, sys.stdout, indent=1)
        print()
    return 0 if verdict["verdict"] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
