#!/usr/bin/env python
"""Summarize a strom Trace Event JSON (from ``--trace-out`` or the live
``/trace`` endpoint): per-span rollups and per-step stall attribution.

Usage: python tools/trace_report.py trace.json [--steps]

Two sections:
- span rollup: one row per span name (count, total/mean/p50/p99 wall) —
  which subsystems burned how much wall overall;
- stall attribution (default on when step windows exist): per-step
  ingest-wait / decode / put / read / compute buckets and goodput_pct,
  the same accounting ``ctx.stats()["steps"]`` and the bench JSON carry
  (strom/obs/stall.py), printed per step so outlier steps are visible.

The file is plain Trace Event Format, so the same trace also loads in
chrome://tracing / https://ui.perfetto.dev for the zoomable version.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from strom.obs import stall  # noqa: E402
from strom.obs.chrome_trace import load_events  # noqa: E402

# the ONE nearest-rank percentile convention, shared with the bench-JSON
# bucket percentiles computed from the same events (strom/obs/stall.py)
_pct = stall._pct


def span_rollup(events: list[dict]) -> list[tuple]:
    """(name, count, total_ms, mean_us, p50_us, p99_us) per span name,
    total-descending."""
    by_name: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        by_name.setdefault(e["name"], []).append(e.get("dur_us", 0.0))
    rows = []
    for name, durs in by_name.items():
        total = sum(durs)
        rows.append((name, len(durs), total / 1e3, total / len(durs),
                     _pct(durs, 0.50), _pct(durs, 0.99)))
    rows.sort(key=lambda r: -r[2])
    return rows


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report")
    ap.add_argument("trace", help="Trace Event JSON (--trace-out / GET /trace)")
    ap.add_argument("--no-steps", action="store_true",
                    help="skip the per-step stall attribution section")
    args = ap.parse_args(argv)
    try:
        events = load_events(args.trace)
    except (OSError, ValueError, KeyError) as e:
        print(f"trace_report: cannot read {args.trace}: {e}", file=sys.stderr)
        return 1
    if not events:
        print("trace_report: no events in trace", file=sys.stderr)
        return 1
    try:
        _report(events, steps=not args.no_steps)
    except BrokenPipeError:  # `| head` is a normal way to use this tool
        return 0
    return 0


def _report(events: list[dict], *, steps: bool) -> None:
    rows = span_rollup(events)
    name_w = max([len(r[0]) for r in rows] + [len("span")]) + 2
    print(f"{'span'.ljust(name_w)}{'count':>8}{'total_ms':>12}"
          f"{'mean_us':>12}{'p50_us':>10}{'p99_us':>12}")
    for name, n, total_ms, mean, p50, p99 in rows:
        print(f"{name.ljust(name_w)}{n:>8}{total_ms:>12.2f}"
              f"{mean:>12.1f}{p50:>10.1f}{p99:>12.1f}")

    if steps:
        buckets = stall.step_buckets(events)
        if buckets:
            summary = stall.steps_summary(events)
            print(f"\nsteps: {len(buckets)}  goodput "
                  f"{summary['goodput_pct']}% "
                  "(compute / wall; waits attributed below, ms)")
            print(f"{'step':>5}{'wall':>10}{'ingest_wait':>13}{'decode':>9}"
                  f"{'put':>9}{'read':>9}{'compute':>10}")
            for i, s in enumerate(buckets):
                print(f"{i:>5}{s.wall_us / 1e3:>10.2f}"
                      f"{s.ingest_wait_us / 1e3:>13.2f}"
                      f"{s.decode_us / 1e3:>9.2f}{s.put_us / 1e3:>9.2f}"
                      f"{s.read_us / 1e3:>9.2f}{s.compute_us / 1e3:>10.2f}")
        else:
            print("\n(no step windows in trace: run a --train-step bench, "
                  "or consume a pipeline, to get stall attribution)")


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
