#!/usr/bin/env python
"""Summarize strom Trace Event JSON (from ``--trace-out`` or the live
``/trace`` endpoint): per-span rollups, per-step stall attribution, and
per-request / per-tenant causal rollups (ISSUE 8).

Usage: python tools/trace_report.py trace.json [--no-steps] [--requests N]
       python tools/trace_report.py trace_0.json trace_1.json ...
           [--merged-out merged.json]

Given MULTIPLE trace files (one per host — the dist launcher writes
``trace_<rank>.json`` per worker), the tool merges them into one timeline
(ISSUE 18): per-host clock offsets recovered from the traced peer
exchanges align every file onto host 0's timebase, the cross-host
``reqx`` flow chains (client 's' on the asking host, server 't' spans on
the serving host) are counted and reported as linked/unlinked, and
``--merged-out`` writes ONE Perfetto document — each host a process row,
peer fetches rendered as arrows crossing them.

Sections:
- span rollup: one row per span name (count, total/mean/p50/p99 wall) —
  which subsystems burned how much wall overall;
- stall attribution (default on when step windows exist): per-step
  ingest-wait / decode / put / read / compute buckets and goodput_pct,
  the same accounting ``ctx.stats()["steps"]`` and the bench JSON carry
  (strom/obs/stall.py), printed per step so outlier steps are visible;
- request rollup (when req-tagged spans exist): the slowest N requests
  with their CRITICAL PATH — the longest chain through the causal links
  the request tracing recorded (queue → grant → engine slice → decode →
  put), so a slow request reads as "where its time went", not a span
  soup;
- per-tenant table: request count, p50/p99 latency, throttled/errored
  counts from the ``req.done`` markers.

The file is plain Trace Event Format, so the same trace also loads in
chrome://tracing / https://ui.perfetto.dev for the zoomable version.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from strom.obs import stall  # noqa: E402
from strom.obs.chrome_trace import (_clock_shifts, load_events,  # noqa: E402
                                    merge_host_traces)

# the ONE nearest-rank percentile convention, shared with the bench-JSON
# bucket percentiles computed from the same events (strom/obs/stall.py)
_pct = stall._pct


def span_rollup(events: list[dict]) -> list[tuple]:
    """(name, count, total_ms, mean_us, p50_us, p99_us) per span name,
    total-descending."""
    by_name: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        by_name.setdefault(e["name"], []).append(e.get("dur_us", 0.0))
    rows = []
    for name, durs in by_name.items():
        total = sum(durs)
        rows.append((name, len(durs), total / 1e3, total / len(durs),
                     _pct(durs, 0.50), _pct(durs, 0.99)))
    rows.sort(key=lambda r: -r[2])
    return rows


def request_spans(events: list[dict]) -> dict[int, list[dict]]:
    """{req_id: [X spans carrying args.req]}, each list ts-sorted."""
    by_req: dict[int, list[dict]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        rid = (e.get("args") or {}).get("req")
        if rid is None:
            continue
        by_req.setdefault(int(rid), []).append(e)
    for spans in by_req.values():
        spans.sort(key=lambda e: e["ts_us"])
    return by_req


def critical_path(spans: list[dict]) -> list[dict]:
    """The longest chain through a request's causal links: walking from
    the request's start, always take the span that begins inside (or
    first after) the covered window and extends it furthest — the
    sequence whose spans an operator must shorten to shorten the request.
    Container spans that enclose the whole request (the batch/gather
    umbrella) are skipped so the chain names the WORK, not the wrapper."""
    if not spans:
        return []
    t_lo = min(e["ts_us"] for e in spans)
    t_hi = max(e["ts_us"] + e.get("dur_us", 0.0) for e in spans)

    def _umbrella(e: dict) -> bool:
        # a wrapper covers (almost) the whole request AND encloses other
        # spans — length alone must not disqualify: a 260ms sched.queue
        # in a 263ms throttled request IS the answer, not a wrapper
        if e.get("dur_us", 0.0) < 0.95 * max(t_hi - t_lo, 1e-9):
            return False
        lo, hi = e["ts_us"], e["ts_us"] + e.get("dur_us", 0.0)
        return any(o is not e and o["ts_us"] >= lo - 1e-9
                   and o["ts_us"] + o.get("dur_us", 0.0) <= hi + 1e-9
                   for o in spans)

    inner = [e for e in spans if not _umbrella(e)] or spans
    inner.sort(key=lambda e: (e["ts_us"], -e.get("dur_us", 0.0)))
    chain: list[dict] = []
    covered = t_lo
    i = 0
    while i < len(inner):
        # candidates starting at or before the covered edge: take the one
        # reaching furthest; none -> jump the gap to the next span
        best = None
        while i < len(inner) and inner[i]["ts_us"] <= covered + 1e-9:
            end = inner[i]["ts_us"] + inner[i].get("dur_us", 0.0)
            if best is None or end > best[0]:
                best = (end, inner[i])
            i += 1
        if best is None:
            best = (inner[i]["ts_us"] + inner[i].get("dur_us", 0.0),
                    inner[i])
            i += 1
        if best[0] > covered or not chain:
            chain.append(best[1])
            covered = max(covered, best[0])
    return chain


def request_rollup(events: list[dict], top: int = 10) -> list[dict]:
    """The slowest *top* requests: wall, span count, and the critical
    path rendered name(ms)→name(ms). Request metadata (tenant, kind,
    throttled) comes from the ``req.done`` instants when present."""
    done = {int(e["args"]["req"]): e["args"] for e in events
            if e.get("name") == "req.done"
            and isinstance(e.get("args"), dict) and "req" in e["args"]}
    rows = []
    for rid, spans in request_spans(events).items():
        t_lo = min(e["ts_us"] for e in spans)
        t_hi = max(e["ts_us"] + e.get("dur_us", 0.0) for e in spans)
        meta = done.get(rid, {})
        wall = meta.get("dur_us", t_hi - t_lo)
        chain = critical_path(spans)
        rows.append({
            "req": rid,
            "tenant": meta.get("tenant", "?"),
            "kind": meta.get("kind", "?"),
            "wall_us": wall,
            "spans": len(spans),
            "throttled": bool(meta.get("throttled")),
            "error": meta.get("error"),
            "path": "→".join(
                f"{e['name']}({e.get('dur_us', 0.0) / 1e3:.1f}ms)"
                for e in chain),
        })
    rows.sort(key=lambda r: -r["wall_us"])
    return rows[:top]


def tenant_table(events: list[dict]) -> list[tuple]:
    """(tenant, requests, p50_ms, p99_ms, throttled, errors) per tenant
    from the req.done markers, request-count-descending. Data-path
    requests only: "step" markers (whose wall is mostly consumer compute)
    are excluded, the same policy Request.finish applies to req_lat — so
    these percentiles agree with /slo and the bench req_lat columns."""
    by_tenant: dict[str, list[dict]] = {}
    for e in events:
        if e.get("name") != "req.done":
            continue
        a = e.get("args") or {}
        if a.get("kind") == "step":
            continue
        by_tenant.setdefault(a.get("tenant", "?"), []).append(a)
    rows = []
    for tenant, metas in by_tenant.items():
        durs = [m.get("dur_us", 0.0) for m in metas]
        rows.append((tenant, len(metas),
                     _pct(durs, 0.50) / 1e3, _pct(durs, 0.99) / 1e3,
                     sum(1 for m in metas if m.get("throttled")),
                     sum(1 for m in metas if m.get("error"))))
    rows.sort(key=lambda r: -r[1])
    return rows


def flow_links(host_events: "dict[str, list[dict]]") -> dict:
    """The cross-host ``reqx`` flow chains: one chain per peer fetch,
    flow id minted on the asking host ('s' phase at send), echoed by the
    serving host's span binders ('t') and closed by the client's 'f'.
    Returns ``{"linked": n, "unlinked": n, "pairs": {(client, server): n}}``
    — *linked* = the id appears on >= 2 hosts (the arrow has both ends;
    an unlinked chain means the peer answered without trace context, an
    old peer or a downgraded one)."""
    by_id: dict[int, dict[str, set]] = {}
    for host, evs in host_events.items():
        for e in evs:
            if e.get("cat") == "reqx" and e.get("ph") in ("s", "t", "f"):
                by_id.setdefault(e.get("id", 0), {}) \
                    .setdefault(host, set()).add(e["ph"])
    linked = unlinked = 0
    pairs: dict[tuple, int] = {}
    for phases_by_host in by_id.values():
        if len(phases_by_host) >= 2:
            linked += 1
            clients = [h for h, ps in phases_by_host.items() if "s" in ps]
            servers = [h for h, ps in phases_by_host.items() if "t" in ps]
            for c in clients:
                for s in servers:
                    if s != c:
                        pairs[(c, s)] = pairs.get((c, s), 0) + 1
        else:
            unlinked += 1
    return {"linked": linked, "unlinked": unlinked, "pairs": pairs}


def _cluster_report(host_events: "dict[str, list[dict]]") -> None:
    shifts = _clock_shifts(host_events)
    print(f"hosts: {len(host_events)}")
    for host, evs in host_events.items():
        spans = sum(1 for e in evs if e.get("ph") == "X")
        print(f"  {host}: {len(evs)} events ({spans} spans), "
              f"clock shift {shifts.get(host, 0.0):+.1f}us")
    links = flow_links(host_events)
    total = links["linked"] + links["unlinked"]
    ratio = links["linked"] / total if total else 0.0
    print(f"peer-fetch flows: {total} ({links['linked']} cross-host "
          f"linked, {links['unlinked']} unlinked; "
          f"linked ratio {ratio:.2f})")
    for (c, s), n in sorted(links["pairs"].items()):
        print(f"  {c} -> {s}: {n} fetches")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report")
    ap.add_argument("traces", nargs="+", metavar="trace",
                    help="Trace Event JSON (--trace-out / GET /trace); "
                         "several = per-host files to merge (ISSUE 18)")
    ap.add_argument("--no-steps", action="store_true",
                    help="skip the per-step stall attribution section")
    ap.add_argument("--requests", type=int, default=10, metavar="N",
                    help="show the N slowest requests' critical paths "
                         "(0 = skip; default 10)")
    ap.add_argument("--merged-out", default=None, metavar="PATH",
                    dest="merged_out",
                    help="write the merged multi-host Perfetto document "
                         "here (multi-trace mode)")
    args = ap.parse_args(argv)
    host_events: dict[str, list[dict]] = {}
    for path in args.traces:
        host = os.path.splitext(os.path.basename(path))[0]
        try:
            host_events[host] = load_events(path)
        except (OSError, ValueError, KeyError) as e:
            print(f"trace_report: cannot read {path}: {e}", file=sys.stderr)
            return 1
    if not any(host_events.values()):
        print("trace_report: no events in trace", file=sys.stderr)
        return 1
    multi = len(host_events) > 1
    try:
        if multi:
            _cluster_report(host_events)
            if args.merged_out:
                import json

                with open(args.merged_out, "w") as f:
                    json.dump(merge_host_traces(host_events), f)
                print(f"merged trace -> {args.merged_out}")
            print()
        # single-timeline sections over the (shifted) union: cross-host
        # stall attribution is meaningless, so steps stay single-mode only
        shifts = _clock_shifts(host_events) if multi else {}
        events = sorted(
            ({**e, "ts_us": e["ts_us"] + shifts.get(h, 0.0)}
             for h, evs in host_events.items() for e in evs),
            key=lambda e: e["ts_us"])
        _report(events, steps=not args.no_steps and not multi,
                requests=args.requests)
    except BrokenPipeError:  # `| head` is a normal way to use this tool
        return 0
    return 0


def _report(events: list[dict], *, steps: bool, requests: int = 10) -> None:
    rows = span_rollup(events)
    name_w = max([len(r[0]) for r in rows] + [len("span")]) + 2
    print(f"{'span'.ljust(name_w)}{'count':>8}{'total_ms':>12}"
          f"{'mean_us':>12}{'p50_us':>10}{'p99_us':>12}")
    for name, n, total_ms, mean, p50, p99 in rows:
        print(f"{name.ljust(name_w)}{n:>8}{total_ms:>12.2f}"
              f"{mean:>12.1f}{p50:>10.1f}{p99:>12.1f}")

    if steps:
        buckets = stall.step_buckets(events)
        if buckets:
            summary = stall.steps_summary(events)
            print(f"\nsteps: {len(buckets)}  goodput "
                  f"{summary['goodput_pct']}% "
                  "(compute / wall; waits attributed below, ms)")
            print(f"{'step':>5}{'wall':>10}{'ingest_wait':>13}{'decode':>9}"
                  f"{'put':>9}{'read':>9}{'compute':>10}")
            for i, s in enumerate(buckets):
                print(f"{i:>5}{s.wall_us / 1e3:>10.2f}"
                      f"{s.ingest_wait_us / 1e3:>13.2f}"
                      f"{s.decode_us / 1e3:>9.2f}{s.put_us / 1e3:>9.2f}"
                      f"{s.read_us / 1e3:>9.2f}{s.compute_us / 1e3:>10.2f}")
        else:
            print("\n(no step windows in trace: run a --train-step bench, "
                  "or consume a pipeline, to get stall attribution)")

    if requests:
        reqs = request_rollup(events, top=requests)
        if reqs:
            print(f"\nslowest requests (top {len(reqs)}; critical path = "
                  "longest causal chain):")
            for r in reqs:
                flags = "".join(f" [{f}]" for f, on in
                                (("throttled", r["throttled"]),
                                 ("error", bool(r["error"]))) if on)
                print(f"  req {r['req']} tenant={r['tenant']} "
                      f"kind={r['kind']} wall={r['wall_us'] / 1e3:.1f}ms "
                      f"spans={r['spans']}{flags}")
                if r["path"]:
                    print(f"    {r['path']}")
        tenants = tenant_table(events)
        if tenants:
            print(f"\n{'tenant':<16}{'requests':>9}{'p50_ms':>9}"
                  f"{'p99_ms':>9}{'throttled':>11}{'errors':>8}")
            for t, n, p50, p99, thr, err in tenants:
                print(f"{t:<16}{n:>9}{p50:>9.1f}{p99:>9.1f}"
                      f"{thr:>11}{err:>8}")


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
